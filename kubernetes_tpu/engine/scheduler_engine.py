"""Host-side scheduling engine: snapshot -> device batch -> assume.

The TPU-native replacement for genericScheduler.Schedule
(reference: plugin/pkg/scheduler/core/generic_scheduler.go:88-142) operating
on the whole pending queue at once:

  1. delta-refresh the tensor snapshot from the SchedulerCache (the analog of
     cache.UpdateNodeNameToInfoMap at generic_scheduler.go:101);
  2. run engine/batch.place_batch on device — sequential semantics preserved
     (see batch.py docstring);
  3. map node indices back to names and AssumePod each placement into the
     cache (scheduler.go:188 assume; binding is the caller's async job,
     scheduler.go:224-250).

Pods whose features the kernels over-approximate (PodBatch.needs_host_check)
take the exact object-level oracle path against the updated cache — the
"exact host-side verification" safety net of SURVEY.md §7(e).

Device arrays are cached keyed on snapshot.version so an unchanged cluster
uploads nothing between batches.

The pipelined drain rides the dispatch_waves / harvest_waves pair instead
of schedule(): dispatch encodes a chunk (vocab_gen-keyed encoding reuse),
launches waves_loop WITHOUT the device→host sync, and returns a WaveHandle;
harvest blocks on the handle, re-validates the blind wave's placements
against current occupancy (the capacity fence, its topology mirror, and —
for gang-bearing waves — the all-or-nothing gang fence), finishes
strict-tail pods via the conflict-round loop (waves.tail_rounds_loop),
assumes survivors columnar (grouped per node+class, folded into the
snapshot via raw-delta math), and hands conflicts back for requeue.
schedule() remains the synchronous path for everything the wave engine
can't take (host-check classes, Policy algorithms, workload spreading).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.analysis import sanitize
from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.engine.batch import NodeState, gather_place_batch
from kubernetes_tpu.engine import waves
from kubernetes_tpu.observability import podtrace
from kubernetes_tpu.observability import recorder as flightrec
from kubernetes_tpu.observability.podtrace import TRACER
from kubernetes_tpu.observability.recorder import RECORDER
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.ops.predicates import bucket
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.classes import ClassBatch
from kubernetes_tpu.state.snapshot import (
    ClusterSnapshot,
    R_CPU,
    R_MEM,
    R_OVERLAY,
    R_SCRATCH,
)


class EvalCache:
    """Per-request amortization for the extender's evaluate_pod hot path —
    the sidecar analog of the reference's 100-entry equivalence LRU
    (core/equivalence_cache.go:33-54) plus vocab-growth isolation:

    - pair collection (collect_pod_pairs over every NodeInfo) cached keyed
      on snapshot.version, with existing pods' topology keys interned ONCE
      per version (not per request);
    - (ClassBatch, AffinityData) LRU keyed on (snapshot.version, pod class
      key) so repeat evaluations of equivalent pods skip tensorization;
    - label-vocab isolation: a pod whose selectors/topology keys would GROW
      the shared vocab (adversarial label churn -> full snapshot rebuild +
      recompile per request) is routed to the exact object-level oracle
      instead, and its pairs are queued; the next cache sync interns the
      queue in one batch, so rebuilds are bounded at one per sync no matter
      the request pattern (VERDICT r3 weak #5)."""

    MAX_PENDING = 4096

    def __init__(self, lru_size: int = 100, result_size: int = 2048):
        from collections import OrderedDict
        self.lru_size = lru_size
        self.result_size = result_size
        self._lru = OrderedDict()
        self._results = OrderedDict()
        self._results_ver = None  # results are reachable only within one
        # snapshot-version window (rkey embeds the version); a version move
        # clears the memo wholesale instead of letting up to result_size
        # dead ~25KB (fits, scores) pairs rot in FIFO order
        self._pairs_version = -1
        self._pairs = None
        self._pending_pairs: set = set()
        self._pending_images: set = set()
        self._pending_conflicts: set = set()
        self._pending_pds: set = set()
        self._sync_seen = False
        self.oracle_routes = 0  # diagnostics for tests/metrics
        self.builds = 0
        self.result_hits = 0
        # affinity-relevance generation, maintained by the owner (the
        # extender backend): bumped whenever the set of cached pods that
        # carry pod (anti-)affinity may have changed. Affinity-free
        # encodings key on (vocab_gen, aff_gen) instead of the full
        # snapshot version, so a stream of plain binds (scheduleOne compat
        # mode) reuses them instead of re-tensorizing per capacity delta.
        self.aff_gen = 0
        # True when NO pod in the owner's cache carries pod (anti-)affinity
        # — lets plain-pod evaluations skip pair collection + AffinityData
        # entirely (the symmetry check has nothing to check). Owners that
        # cannot prove this leave it False; everything still works, slower.
        self.cluster_aff_free = False

    def on_sync(self) -> None:
        """Cluster state resynced (the sidecar's /cache/... endpoints) —
        queued request pairs may intern at the next evaluation."""
        self._sync_seen = True
        self.aff_gen += 1
        self._results.clear()

    def flush_pending(self, snap: ClusterSnapshot) -> None:
        """Intern the queued request vocab entries in ONE rebuild per vocab,
        only after a sync boundary — the bounded-growth half of the
        isolation story."""
        if not self._sync_seen:
            return
        if self._pending_pairs:
            for k, v in self._pending_pairs:
                snap.ensure_label_pair(k, v)
            self._pending_pairs.clear()
            snap.finalize_labels()
        if self._pending_images:
            for name in self._pending_images:
                snap.ensure_image(name)
            self._pending_images.clear()
            snap.finalize_images()
        if self._pending_conflicts or self._pending_pds:
            for key in self._pending_conflicts:
                snap.ensure_conflict_key(key)
            for kind, vid in self._pending_pds:
                snap.ensure_pd_id(kind, vid)
            self._pending_conflicts.clear()
            self._pending_pds.clear()
            snap.finalize_volumes()
        self._sync_seen = False

    # -------------------------------------------------------------- pairs

    def pairs_for(self, snap: ClusterSnapshot, infos):
        """(all_pairs, aff_pairs) for the current cluster state; interns
        existing-pod topology keys + any queued request pairs, then
        finalizes the label matrix so the version is stable afterwards."""
        from kubernetes_tpu.ops.affinity import (
            collect_pod_pairs,
            intern_topology_pairs,
        )
        if self._pairs_version == snap.version and self._pairs is not None:
            return self._pairs
        all_pairs, aff_pairs = collect_pod_pairs(infos)
        intern_topology_pairs(snap, [], aff_pairs)
        for k, v in self._pending_pairs:
            snap.ensure_label_pair(k, v)
        self._pending_pairs.clear()
        snap.finalize_labels()
        self._pairs = (all_pairs, aff_pairs)
        self._pairs_version = snap.version
        return self._pairs

    # ----------------------------------------------------- vocab isolation

    def vocab_missing(self, pod: Pod, snap: ClusterSnapshot,
                      volume_ctx=None) -> bool:
        """Would encoding this pod grow ANY snapshot vocab (label pairs,
        container images, volume conflict keys / PD ids)? If yes, queue the
        entries for the next sync and answer True (caller routes to the
        oracle). Guarding only labels would leave image/volume churn as a
        per-request rebuild vector — PodBatch interns those too
        (snapshot.py ensure_image/ensure_conflict_key/ensure_pd_id)."""
        pairs = set()
        vocab = snap.label_vocab
        grown = False
        pend = len(self._pending_images) + len(self._pending_conflicts) \
            + len(self._pending_pds)
        for c in pod.containers:
            if c.image and snap.image_vocab.get(c.image, "") < 0:
                grown = True
                if pend < self.MAX_PENDING:
                    self._pending_images.add(c.image)
        if pod.volumes:
            from kubernetes_tpu.state import volumes as volmod
            for key, _ro in volmod.pod_conflict_keys(pod):
                if snap.conflict_vocab.get(key, "") < 0:
                    grown = True
                    if pend < self.MAX_PENDING:
                        self._pending_conflicts.add(key)
            if volume_ctx is not None:
                for kind, vid in volmod.pd_filter_ids(pod, volume_ctx):
                    if snap.pd_vocab.get(str(kind) + "\x00" + vid, "") < 0:
                        grown = True
                        if pend < self.MAX_PENDING:
                            self._pending_pds.add((kind, vid))
        for k, v in pod.node_selector.items():
            if vocab.get(k, v) < 0:
                pairs.add((k, v))
        a = pod.affinity
        terms = []
        if a is not None and a.node_affinity is not None:
            if a.node_affinity.required_terms:
                terms.extend(a.node_affinity.required_terms)
            terms.extend(t for _w, t in a.node_affinity.preferred_terms)
        from kubernetes_tpu.api.types import SelectorOperator
        for t in terms:
            for r in t.match_expressions:
                if SelectorOperator(r.operator) == SelectorOperator.IN:
                    for v in r.values:
                        if vocab.get(r.key, v) < 0:
                            pairs.add((r.key, v))
                else:  # Exists/NotIn/Gt/Lt expand over node-present values
                    for v in snap.node_values_for_key(r.key):
                        if vocab.get(r.key, v) < 0:
                            pairs.add((r.key, v))
        from kubernetes_tpu.ops.affinity import _term_topology_keys
        for key in _term_topology_keys(pod):
            for v in snap.node_values_for_key(key):
                if vocab.get(key, v) < 0:
                    pairs.add((key, v))
        if pairs or grown:
            if len(self._pending_pairs) < self.MAX_PENDING:
                self._pending_pairs.update(pairs)
            self.oracle_routes += 1
            return True
        return False

    # ------------------------------------------------------------------ LRU

    @staticmethod
    def _wkey(workloads: Sequence) -> tuple:
        return tuple(sorted((w.kind, w.namespace, w.name, w.resource_version)
                            for w in workloads))

    def get_encoded(self, pod: Pod, snap: ClusterSnapshot, build,
                    workloads: Sequence = (), ckey=None, aff_free=False):
        """Encoded-class entry via the LRU; `build()` constructs on miss.

        Key: affinity-FREE classes (no pod affinity, no workloads, cluster
        proven affinity-free) key on (vocab_gen, aff_gen) — their encoding
        reads only vocabs and the node order, so capacity deltas (binds)
        don't invalidate them. Affinity-BEARING classes key on the full
        snapshot version, exactly as the reference re-derives predicate
        metadata against the live cache per pod."""
        from kubernetes_tpu.state.classes import pod_class_key
        wkey = self._wkey(workloads)
        struct = (snap.vocab_gen, self.aff_gen) if aff_free else snap.version
        key = (struct, wkey, ckey if ckey is not None else pod_class_key(pod))
        hit = self._lru.get(key)
        if hit is not None:
            self._lru.move_to_end(key)
            return hit
        val = build()
        self.builds += 1
        self._lru[key] = val
        if len(self._lru) > self.lru_size:
            self._lru.popitem(last=False)
        return val

    # ------------------------------------------------------------- results

    def _roll_results(self, version) -> None:
        if version != self._results_ver:
            self._results.clear()
            self._results_ver = version

    def get_result(self, key):
        """(fits, scores) memo for one (snapshot version, priority config,
        class) — the fused-verb cache: /prioritize after /filter for the
        same pod (or any equivalent pod at the same cluster state) returns
        without touching the device. Invalidation is structural: the
        snapshot version moving clears the whole window (old-version
        entries can never hit again — version is monotonic), on_sync
        clears outright."""
        self._roll_results(key[0])
        hit = self._results.get(key)
        if hit is not None:
            self._results.move_to_end(key)
            self.result_hits += 1
        return hit

    def put_result(self, key, value) -> None:
        self._roll_results(key[0])
        self._results[key] = value
        if len(self._results) > self.result_size:
            self._results.popitem(last=False)


class PlacementResult:
    __slots__ = ("pod", "node_name", "fit_count")

    def __init__(self, pod: Pod, node_name: Optional[str], fit_count: int):
        self.pod = pod
        self.node_name = node_name
        self.fit_count = fit_count

    def __repr__(self):
        return f"Placement({self.pod.key()} -> {self.node_name})"


def _oracle_eval(pod, infos, snap, priorities, workloads, hard_weight,
                 volume_ctx, policy_algos):
    """Exact object-level /filter + /prioritize (the reference's per-pod
    predicate/priority calls, no tensorization)."""
    from kubernetes_tpu.ops.oracle_ext import AffinityMeta, SchedulingContext
    ctx = SchedulingContext(infos, list(workloads),
                            hard_pod_affinity_weight=hard_weight,
                            volume_ctx=volume_ctx,
                            policy_algos=policy_algos)
    meta = AffinityMeta(pod, ctx)
    names = snap.node_names
    n_pad = snap.valid.shape[0]
    m = np.zeros(n_pad, dtype=bool)
    for i, nm in enumerate(names):
        m[i] = oracle.pod_fits(pod, infos[nm], ctx, meta)
    s = np.zeros(n_pad, dtype=np.int64)
    fit_idx = np.nonzero(m)[0]
    if len(fit_idx):
        fit_infos = [infos[names[i]] for i in fit_idx]
        per = oracle.prioritize(pod, fit_infos, priorities, ctx)
        s[fit_idx] = per
    return m, s


class _EncodedClass:
    """One LRU entry of the extender fast lane: the host encodings plus
    their DEVICE-resident uploads, so repeat evaluations of an equivalent
    pod re-dispatch the compiled kernel over buffers already in HBM instead
    of re-tensorizing + re-transferring per request."""

    __slots__ = ("batch", "adata", "parr", "aff")

    def __init__(self, batch, adata, parr, aff):
        self.batch = batch
        self.adata = adata
        self.parr = parr    # device pod-side pytree (shape-bucketed)
        self.aff = aff      # device affinity pytree, or None when inert


def _fused_eval(parr, narr, aff, priorities, weights, aff_mode):
    """The single-pod [1,N] evaluation as ONE traced program: predicate
    chain + weighted priorities + (when live) the zero-occupancy affinity/
    spread kernels. Fusing matters on a tunneled TPU backend: the previous
    eager composition dispatched every jnp op as its own RPC (~60+ round
    trips per warm /filter — the bulk of the 935 ms p50 BENCH_r05 measured);
    one jit call is one dispatch."""
    from kubernetes_tpu.ops.affinity import (
        interpod_score,
        spread_score,
        step_fits,
        step_prio_counts,
        step_spread_counts,
    )
    from kubernetes_tpu.ops.pallas_kernels import precompute_static_fast
    from kubernetes_tpu.ops.predicates import fits

    fits_on, prio_on, spread_on = aff_mode
    w_ip, w_sp = weights
    m = fits(parr, narr)[0]
    s = prio.score(parr, narr, priorities)[0]
    if fits_on or prio_on or spread_on:
        labels = narr["labels"]
        pre = precompute_static_fast(aff, labels)
        c_dim = aff["m_aff"].shape[0]
        commdom0 = jnp.zeros((c_dim, labels.shape[1]), dtype=jnp.int32)
        committed0 = jnp.zeros((c_dim, labels.shape[0]), dtype=jnp.int32)
        comm_cnt0 = jnp.zeros(c_dim, dtype=jnp.int32)
        if fits_on:
            m = m & step_fits(aff, pre, 0, commdom0, comm_cnt0, labels)
        if prio_on:
            cnt = step_prio_counts(aff, pre, 0, commdom0, labels)
            s = s + w_ip * interpod_score(cnt, m)
        if spread_on:
            cnt = step_spread_counts(aff, 0, committed0)
            s = s + w_sp * spread_score(aff, aff["sp_has"][0], cnt, m)
    return m, s


_fused_eval_jit = jax.jit(_fused_eval,
                          static_argnames=("priorities", "weights",
                                           "aff_mode"))


def _fused_eval_batch(parr, narr, aff, priorities, weights, aff_mode):
    """The [C, N] sibling of _fused_eval (ISSUE 9): every row of a coalesced
    multi-frontend batch evaluated in ONE traced program — predicate chain +
    weighted priorities + (when live) the zero-occupancy affinity/spread
    kernels, class-vectorized via step_fits_all / step_prio_counts_all (the
    ISSUE 5 conflict-round forms; row c is bit-identical to _fused_eval of
    class c alone, since zero occupancy has no cross-row carry). 100
    concurrent frontends therefore cost ~1 dispatch, not 100."""
    from kubernetes_tpu.ops.affinity import (
        interpod_score,
        spread_score,
        step_fits_all,
        step_prio_counts_all,
    )
    from kubernetes_tpu.ops.pallas_kernels import precompute_static_fast
    from kubernetes_tpu.ops.predicates import fits

    fits_on, prio_on, spread_on = aff_mode
    w_ip, w_sp = weights
    m = fits(parr, narr)                       # [C, N]
    s = prio.score(parr, narr, priorities)     # [C, N]
    if fits_on or prio_on or spread_on:
        labels = narr["labels"]
        pre = precompute_static_fast(aff, labels)
        c_dim = aff["m_aff"].shape[0]
        commdom0 = jnp.zeros((c_dim, labels.shape[1]), dtype=jnp.int32)
        committed0 = jnp.zeros((c_dim, labels.shape[0]), dtype=jnp.int32)
        comm_cnt0 = jnp.zeros(c_dim, dtype=jnp.int32)
        if fits_on:
            m = m & step_fits_all(aff, pre, commdom0, comm_cnt0, labels)
        if prio_on:
            cnt = step_prio_counts_all(aff, pre, commdom0, labels)
            s = s + w_ip * interpod_score(cnt, m)
        if spread_on:
            dyn = aff["sp_cls"].astype(jnp.int32) @ committed0
            s = s + w_sp * spread_score(aff, aff["sp_has"],
                                        aff["sp_static"] + dyn, m)
    return m, s


_fused_eval_batch_jit = jax.jit(_fused_eval_batch,
                                static_argnames=("priorities", "weights",
                                                 "aff_mode"))


def evaluate_pod(pod: Pod, infos, snap: ClusterSnapshot,
                 priorities: Tuple[Tuple[str, int], ...],
                 workloads: Sequence = (), hard_weight: int = 1,
                 volume_ctx=None, policy_algos=None, eval_cache=None,
                 device_nodes_provider=None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-node (fits [N] bool, scores [N] int32) for ONE pod against the
    cluster state — the extender's /filter + /prioritize evaluation
    (core/extender.go:100 Filter, :157 Prioritize). No state is committed:
    a single pod has no in-batch carry, so the affinity/spread kernels run
    with zero occupancy (the static side only — exactly what the reference's
    per-pod predicate/priority calls see through the scheduler cache).

    `snap` must already be refreshed against `infos`. Falls back to the
    exact host oracle when the pod's features over-approximate on device
    (needs_host_check / affinity slot overflow).

    Score caveat (pre-dating the fast lane, preserved): the oracle route
    normalizes reduce-priorities over the FILTERED set and reports 0 for
    non-fitting nodes, while the device route scores every node with
    fits=None normalization — so the two routes can differ on the exact
    integers (never on fit verdicts). A single pod always takes ONE route
    per call, and /filter+/prioritize share it via the result memo, so a
    scheduler never sees mixed-route scores for one pod.

    The warm fast lane (eval_cache given) is layered:
      1. result memo — same class at the same snapshot version returns the
         cached (m, s) with zero device work (the fused filter+prioritize
         contract: the second verb rides the first's evaluation);
      2. encoded-class LRU — holds device-RESIDENT pod/affinity arrays;
         affinity-free classes survive capacity deltas (vocab_gen keying);
      3. one fused jit dispatch over the caller's device-resident node
         arrays (device_nodes_provider — CALLED only after vocab flushes,
         so a label-matrix rebuild can never race a stale upload;
         node_arrays(snap) uploads fresh when absent).
    """
    from kubernetes_tpu.ops.affinity import (
        AffinityData,
        _has_affinity,
        collect_pod_pairs,
        intern_topology_pairs,
    )
    from kubernetes_tpu.ops.predicates import pod_arrays_bucketed
    from kubernetes_tpu.state.classes import pod_class_key
    from kubernetes_tpu.utils.trace import COUNTERS, timed_span

    w_ip = sum(w for nm, w in priorities if nm == "InterPodAffinityPriority")
    w_sp = sum(w for nm, w in priorities if nm == "SelectorSpreadPriority")

    if eval_cache is not None:
        # queued churn pairs intern in one batch at a sync boundary
        eval_cache.flush_pending(snap)
        # vocab isolation: a pod that would grow any snapshot vocab must
        # not touch the snapshot at all (EvalCache docstring)
        if eval_cache.vocab_missing(pod, snap, volume_ctx=volume_ctx):
            with timed_span("extender.oracle_eval"):
                return _oracle_eval(pod, infos, snap, priorities, workloads,
                                    hard_weight, volume_ctx, policy_algos)
        ckey = pod_class_key(pod)
        # priorities + hard_weight are part of BOTH cache keys: the
        # encoding's `need` gate and the scores depend on them, and nothing
        # forces a shared EvalCache to serve one fixed configuration
        cfg = (priorities, hard_weight)
        rkey = (snap.version, eval_cache._wkey(workloads), cfg, ckey)
        hit = eval_cache.get_result(rkey)
        if hit is not None:
            COUNTERS.inc("extender.result_hit")
            return hit
        # a pod with no pod (anti-)affinity in a cluster with no
        # affinity-carrying pods and no workloads has an all-zero
        # AffinityData by construction — skip pair collection and the
        # affinity build entirely, and key the encoding on the vocab
        # generation so binds don't invalidate it
        aff_free = (eval_cache.cluster_aff_free and not workloads
                    and not _has_affinity(pod))
        if aff_free:
            def _build():
                with timed_span("extender.encode"):
                    b = ClassBatch([pod], snap)
                    return _EncodedClass(b, None,
                                         pod_arrays_bucketed(b.reps_batch),
                                         None)
        else:
            with timed_span("extender.pairs"):
                all_pairs, aff_pairs = eval_cache.pairs_for(snap, infos)

            def _build():
                with timed_span("extender.encode"):
                    COUNTERS.inc("extender.affinity_data_build")
                    b = ClassBatch([pod], snap)
                    a = AffinityData(b.reps, snap, all_pairs, aff_pairs,
                                     list(workloads), hard_weight)
                    need = (a.fits_needed
                            or (bool(w_ip) and a.prio_needed)
                            or (bool(w_sp) and a.spread_needed))
                    return _EncodedClass(
                        b, a, pod_arrays_bucketed(b.reps_batch),
                        a.device_arrays() if need else None)

        enc = eval_cache.get_encoded(pod, snap, _build, workloads=workloads,
                                     ckey=(cfg, ckey), aff_free=aff_free)
        out = _eval_dispatch(pod, infos, snap, priorities, workloads,
                             hard_weight, volume_ctx, policy_algos, enc,
                             device_nodes_provider, w_ip, w_sp)
        eval_cache.put_result(rkey, out)
        return out

    # uncached path (no EvalCache owner): build fresh per call, then the
    # SAME dispatch tail — args-mode and the warm lane cannot drift
    all_pairs, aff_pairs = collect_pod_pairs(infos)
    intern_topology_pairs(snap, [pod], aff_pairs)
    batch = ClassBatch([pod], snap)
    adata = AffinityData(batch.reps, snap, all_pairs, aff_pairs,
                         list(workloads), hard_weight)
    need = (adata.fits_needed or (bool(w_ip) and adata.prio_needed)
            or (bool(w_sp) and adata.spread_needed))
    enc = _EncodedClass(batch, adata, pod_arrays_bucketed(batch.reps_batch),
                        adata.device_arrays() if need else None)
    return _eval_dispatch(pod, infos, snap, priorities, workloads,
                          hard_weight, volume_ctx, policy_algos, enc,
                          device_nodes_provider, w_ip, w_sp)


def _eval_dispatch(pod, infos, snap, priorities, workloads, hard_weight,
                   volume_ctx, policy_algos, enc: "_EncodedClass",
                   device_nodes_provider, w_ip: int, w_sp: int):
    """Shared routing tail of evaluate_pod: exact-oracle gate
    (needs_host_check / slot overflow / Policy algorithms), then ONE fused
    kernel dispatch over the caller's device-resident node arrays. Both the
    warm fast lane and the uncached args-mode path end here, so the
    dispatch contract cannot drift between them."""
    from kubernetes_tpu.ops.predicates import node_arrays
    from kubernetes_tpu.utils.trace import COUNTERS, timed_span

    batch, adata = enc.batch, enc.adata
    if batch.reps_batch.needs_host_check[0] \
            or (adata is not None and adata.overflow[0]) \
            or (policy_algos is not None and policy_algos.active):
        # exact object-level path (same routing as SchedulingEngine.schedule;
        # Policy-configured algorithms always evaluate exactly here — one
        # pod per extender call keeps the oracle cheap)
        with timed_span("extender.oracle_eval"):
            return _oracle_eval(pod, infos, snap, priorities, workloads,
                                hard_weight, volume_ctx, policy_algos)
    plain = tuple((nm, w) for nm, w in priorities
                  if nm not in prio.AFFINITY_PRIORITIES)
    fits_on = adata is not None and adata.fits_needed
    prio_on = adata is not None and bool(w_ip) and adata.prio_needed
    spread_on = adata is not None and bool(w_sp) and adata.spread_needed
    narr = device_nodes_provider() if device_nodes_provider is not None \
        else node_arrays(snap)
    with timed_span("extender.kernel"):
        COUNTERS.inc("extender.fused_eval")
        m, s = _fused_eval_jit(
            enc.parr, narr,
            enc.aff if (fits_on or prio_on or spread_on) else None,
            plain, (w_ip, w_sp), (fits_on, prio_on, spread_on))
        # the extender's one result fetch: the verb returns (fits, scores)
        # to an HTTP caller, so this stall IS the response (m must be
        # writable below; s stays a read-only view)
        m = np.array(m)  # graftlint: sync-ok
        s = np.asarray(s)  # graftlint: sync-ok (same blessed fetch)
    m[len(snap.node_names):] = False
    return m, s


def evaluate_pods_batch(pods: Sequence[Pod], infos, snap: ClusterSnapshot,
                        priorities: Tuple[Tuple[str, int], ...],
                        workloads: Sequence = (), hard_weight: int = 1,
                        volume_ctx=None, policy_algos=None, eval_cache=None,
                        device_nodes_provider=None
                        ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Coalesced multi-frontend evaluation (ISSUE 9): one (fits, scores)
    pair per pod, computed with at most ONE fused [C, N] kernel dispatch
    for the batch's unique pod classes — the device half of the extender's
    micro-batch window. Per-pod ROUTING is identical to evaluate_pod:

      - vocab growth       -> exact host oracle (isolation unchanged);
      - result-memo hit    -> served with zero device work;
      - one unique class   -> delegated to evaluate_pod (the single-pod
        warm lane, so its encoded-class LRU and span counters keep their
        exact contracts — and the fastlane tests their invariants);
      - several classes    -> ONE ClassBatch over the class reps, class
        axis padded to the bucket ladder (pod_arrays_bucketed rows=), one
        _fused_eval_batch_jit dispatch, rows scattered per request;
        host-check / slot-overflow / Policy classes drop to the oracle
        per class exactly as _eval_dispatch routes the single pod.

    Every class's (m, s) enters the result memo, so followers of the same
    coalescing window and later requests hit without dispatching. `snap`
    must already be refreshed; no state is committed (zero-occupancy
    evaluation, same contract as evaluate_pod)."""
    from collections import OrderedDict

    from kubernetes_tpu.ops.affinity import AffinityData, _has_affinity
    from kubernetes_tpu.ops.predicates import node_arrays, pod_arrays_bucketed
    from kubernetes_tpu.state.classes import pod_class_key
    from kubernetes_tpu.utils.trace import COUNTERS, timed_span

    n = len(pods)
    if eval_cache is None:
        # no cache owner: per-request evaluation is the only honest shape
        # (nothing to coalesce against between stateless snapshots)
        return [evaluate_pod(p, infos, snap, priorities, workloads,
                             hard_weight, volume_ctx, policy_algos, None,
                             device_nodes_provider) for p in pods]
    results: List[Optional[Tuple[np.ndarray, np.ndarray]]] = [None] * n
    eval_cache.flush_pending(snap)
    w_ip = sum(w for nm, w in priorities if nm == "InterPodAffinityPriority")
    w_sp = sum(w for nm, w in priorities if nm == "SelectorSpreadPriority")
    cfg = (priorities, hard_weight)
    wkey = eval_cache._wkey(workloads)

    def _oracle(pod):
        with timed_span("extender.oracle_eval"):
            return _oracle_eval(pod, infos, snap, priorities, workloads,
                                hard_weight, volume_ctx, policy_algos)

    # per-pod routing: vocab isolation + memo, then class dedup
    uniq = OrderedDict()  # ckey -> [pod indices], first-seen order
    rep_of = {}
    for i, pod in enumerate(pods):
        if eval_cache.vocab_missing(pod, snap, volume_ctx=volume_ctx):
            results[i] = _oracle(pod)
            continue
        ckey = pod_class_key(pod)
        rkey = (snap.version, wkey, cfg, ckey)
        hit = eval_cache.get_result(rkey)
        if hit is not None:
            COUNTERS.inc("extender.result_hit")
            results[i] = hit
            continue
        members = uniq.get(ckey)
        if members is None:
            uniq[ckey] = members = []
            rep_of[ckey] = pod
        members.append(i)
    # canonical class order (sorted by key repr): the encoded-batch LRU
    # entry is keyed on the class TUPLE, and the same class set arriving
    # in a different interleaving must hit the same entry — row c of the
    # encoding maps to canonical class c by construction
    order = sorted(uniq, key=repr)
    uniq = OrderedDict((ck, uniq[ck]) for ck in order)
    reps: List[Pod] = [rep_of[ck] for ck in order]
    if not uniq:
        return results  # type: ignore[return-value]
    if len(uniq) == 1 or (policy_algos is not None and policy_algos.active):
        # one class (the compat-storm common case) rides the single-pod
        # warm lane — encoded-class LRU, result memo, exact span counters;
        # Policy-configured algorithms always evaluate per pod exactly
        for ckey, members in uniq.items():
            out = evaluate_pod(pods[members[0]], infos, snap, priorities,
                               workloads, hard_weight, volume_ctx,
                               policy_algos, eval_cache,
                               device_nodes_provider)
            for i in members:
                results[i] = out
        return results  # type: ignore[return-value]

    COUNTERS.inc("extender.batch_classes", len(uniq))
    aff_free = (eval_cache.cluster_aff_free and not workloads
                and not any(_has_affinity(r) for r in reps))
    if not aff_free:
        with timed_span("extender.pairs"):
            all_pairs, aff_pairs = eval_cache.pairs_for(snap, infos)

    def _build():
        with timed_span("extender.encode"):
            b = ClassBatch(reps, snap)
            c_pad = bucket(b.num_classes, lo=4)
            if aff_free:
                return _EncodedClass(
                    b, None, pod_arrays_bucketed(b.reps_batch, rows=c_pad),
                    None)
            COUNTERS.inc("extender.affinity_data_build")
            a = AffinityData(b.reps, snap, all_pairs, aff_pairs,
                             list(workloads), hard_weight, c_pad=c_pad)
            need = (a.fits_needed or (bool(w_ip) and a.prio_needed)
                    or (bool(w_sp) and a.spread_needed))
            return _EncodedClass(
                b, a, pod_arrays_bucketed(b.reps_batch, rows=c_pad),
                a.device_arrays() if need else None)

    enc = eval_cache.get_encoded(reps[0], snap, _build, workloads=workloads,
                                 ckey=(cfg, tuple(uniq)), aff_free=aff_free)
    batch, adata = enc.batch, enc.adata
    fits_on = adata is not None and adata.fits_needed
    prio_on = adata is not None and bool(w_ip) and adata.prio_needed
    spread_on = adata is not None and bool(w_sp) and adata.spread_needed
    plain = tuple((nm, w) for nm, w in priorities
                  if nm not in prio.AFFINITY_PRIORITIES)
    m_all = s_all = None
    nhc = batch.reps_batch.needs_host_check
    for c, (ckey, members) in enumerate(uniq.items()):
        if nhc[c] or (adata is not None and adata.overflow[c]):
            out = _oracle(reps[c])  # exact object-level route, per class
        else:
            if m_all is None:
                narr = device_nodes_provider() \
                    if device_nodes_provider is not None \
                    else node_arrays(snap)
                with timed_span("extender.kernel_batch"):
                    COUNTERS.inc("extender.fused_eval_batch")
                    m_d, s_d = _fused_eval_batch_jit(
                        enc.parr, narr,
                        enc.aff if (fits_on or prio_on or spread_on)
                        else None,
                        plain, (w_ip, w_sp),
                        (fits_on, prio_on, spread_on))
                    # the batch's one result fetch: every coalesced verb
                    # returns its row to an HTTP caller, so this stall IS
                    # the response set
                    m_all = np.array(m_d)  # graftlint: sync-ok
                    s_all = np.asarray(s_d)  # graftlint: sync-ok (same
                    # blessed fetch)
                m_all[:, len(snap.node_names):] = False
            out = (m_all[c], s_all[c])
        eval_cache.put_result((snap.version, wkey, cfg, ckey), out)
        for i in members:
            results[i] = out
    return results  # type: ignore[return-value]


def _aff_node_views(adata, snap):
    """(key_node [C, A, N] int8, static_forbid_hit [C, N] int8): the
    per-NODE projections of the anti-term keymasks and static forbid rows.
    Wave-eligible classes have singleton domains, so "node n is in a
    forbidden domain of term (c, a)" reduces to "a matching pod sits ON n
    and n carries the term's key" — these two views are all the per-wave
    mask needs, and neither carries the label axis (which scales with the
    cluster once hostname keys are interned). Computed once per encoding
    build as dense float64 GEMMs restricted to the NONZERO rows (BLAS,
    counts far below 2^53 — exact)."""
    lab_t = snap.labels.astype(np.float64).T              # [L, N]
    C, A, L = adata.anti_keymask.shape
    n = lab_t.shape[1]
    km = adata.anti_keymask.reshape(C * A, L)
    key_node = np.zeros((C * A, n), dtype=np.int8)
    rows = np.nonzero(km.any(axis=1))[0]
    if rows.size:
        key_node[rows] = (km[rows].astype(np.float64) @ lab_t) > 0
    fs = adata.forbid_static
    static_hit = np.zeros((C, n), dtype=np.int8)
    frows = np.nonzero(fs.any(axis=1))[0]
    if frows.size:
        static_hit[frows] = (fs[frows].astype(np.float64) @ lab_t) > 0
    return key_node.reshape(C, A, n), static_hit


def _aff_tail_cols(adata, prio_on: bool) -> np.ndarray:
    """Label columns the SEEDED STRICT TAIL can actually read: domains of
    the wave_strict classes' own terms (allow + anti + static rows), of
    terms TARGETING them (the symmetry sources), and — when preferred
    scoring is live — of every priority-side keymask. Everything else in
    the label axis (hostname columns interned for the wave classes' anti
    terms, selector vocab) is provably inert inside the tail's
    step_fits/step_prio_counts contractions, so the tail runs at
    Lp = O(referenced domains), not L = O(cluster)."""
    sc = adata.wave_strict
    L = adata.forbid_static.shape[1]
    use = np.zeros(L, dtype=bool)
    if sc.any():
        use |= adata.aff_keymask[sc].astype(bool).any(axis=(0, 1))
        use |= adata.aff_allow[sc].astype(bool).any(axis=(0, 1))
        use |= adata.anti_keymask[sc].astype(bool).any(axis=(0, 1))
        use |= adata.forbid_static[sc].astype(bool).any(axis=0)
        tgt = adata.m_anti[:, :, sc].astype(bool).any(axis=2)   # [C, A]
        use |= (adata.anti_keymask.astype(bool)
                & tgt[:, :, None]).any(axis=(0, 1))
    if prio_on:
        use |= adata.p_keymask.astype(bool).any(axis=(0, 1))
        use |= adata.q_keymask.astype(bool).any(axis=(0, 1))
        use |= adata.prio_static.astype(bool).any(axis=0)
    cols = np.nonzero(use)[0]
    if cols.size == 0:
        cols = np.zeros(1, dtype=np.int64)  # degenerate: keep shapes sane
    return cols


_AFF_SLICE3 = ("aff_allow", "aff_keymask", "anti_keymask", "p_keymask",
               "q_keymask")
_AFF_SLICE2 = ("forbid_static", "prio_static")


def _aff_tail_arrays(adata, snap, cols: np.ndarray, rmesh=None):
    """AffinityData device arrays with every domain axis sliced to the
    tail's column projection, plus the matching `labels_aff` [N, Lp] node
    incidence the scan contracts against (place_batch swaps it in for
    nodes["labels"] on the affinity side only). With a resident mesh the
    node-axis members place sharded (mesh.aff_spec), everything else
    replicated — once per encoding, resident across every tail dispatch."""
    def _sh(k):
        return None if rmesh is None else rmesh.aff_sharding(k)
    out = {}
    for k in ("fail_all", "forbid_static", "aff_active", "aff_allow",
              "aff_has_static", "aff_self", "aff_keymask", "anti_active",
              "anti_keymask", "m_aff", "m_anti", "prio_static", "p_w",
              "p_keymask", "mp", "q_w", "q_keymask", "mq", "sp_static",
              "sp_cls", "sp_has", "Z", "node_has_zone", "wave_gate"):
        a = getattr(adata, k)
        if k in _AFF_SLICE3:
            a = a[:, :, cols]
        elif k in _AFF_SLICE2:
            a = a[:, cols]
        # static-per-encoding host arrays (AffinityData owns them, nothing
        # mutates them after build) — zero-copy is the point; the sanitizer
        # seals the sources so a violation crashes at the offending write
        out[k] = sanitize.upload_frozen(a, sharding=_sh(k))
    # advanced indexing already copies, so freezing the fresh row is free
    out["labels_aff"] = sanitize.upload_frozen(snap.labels[:, cols],
                                               sharding=_sh("labels_aff"))
    return out


class _WaveEncoding:
    """Device-resident class encoding reused across pipelined drain chunks.

    A 30k-pod storm arrives as ~8 pipelined chunks of the SAME handful of
    spec classes; re-running ClassBatch/PodBatch per chunk would re-pay the
    tensorization the equivalence classes exist to amortize. This caches the
    padded device class arrays keyed on snapshot.vocab_gen (capacity deltas
    never invalidate an encoding — only vocab growth / node-membership moves
    do, same keying as the extender's affinity-free fast lane) plus the host
    rows the harvest fence reads.

    Affinity chunks (ISSUE 3) add the AffinityData for the class set — its
    STATIC topology arrays (vs already-bound cluster pods) plus a host
    accumulator committed_nodes [C, N] recording this engine's OWN
    fence-accepted commits since the build, so each dispatch seeds the
    device wave loop with exact current occupancy without ever re-walking
    the bound-pod set. The occupancy axis is PER NODE, not per label
    column: wave-eligible classes have singleton domains (domain == node),
    and a [C, L] form would drag the label axis — which scales with the
    cluster once hostname keys are interned — through every wave and fence
    (the PR-start collapse, PROFILE_r08.md). The strict tail gets a
    PROJECTED domain view instead (tail_cols: only columns its classes'
    terms touch). Validity is (vocab_gen, cache.aff_seq) plus — for
    affinity encodings, whose topology views bake label CONTENT —
    snapshot.labels_gen: the engine folds its own assumes into aff_seq
    expectations, so a mismatch means FOREIGN affinity churn (watch
    add/remove, TTL expiry, forgotten bind, node relabel) and the static
    arrays rebuild at the next dispatch."""

    __slots__ = ("vocab_gen", "labels_gen", "key_index", "reps", "cls_arr",
                 "num_classes",
                 "c_pad", "req_rows", "special", "derived", "ports_max",
                 "raw_rows", "delta_ok", "cls_prio", "adata", "wave_strict",
                 "has_aff_pod", "fits_on", "prio_on", "aff_seq",
                 "committed_nodes", "key_node", "static_forbid_hit",
                 "tail_cols", "aff_wave_dev", "aff_tail_dev",
                 "anti_terms", "aff_terms", "foreign_forbid",
                 "foreign_forbid_dom", "aff_patch_dirty",
                 "host_exact", "host_static", "policy_on", "spread_on",
                 "wkey", "has_static_cols")

    def __init__(self, vocab_gen, key_index, reps, cls_arr, num_classes,
                 c_pad, req_rows, special, derived, ports_max,
                 adata=None, fits_on=False, prio_on=False,
                 has_aff_pod=None, aff_seq=0, aff_wave_dev=None,
                 aff_tail_dev=None, key_node=None, static_forbid_hit=None,
                 tail_cols=None, n_pad=0, labels_gen=0,
                 host_exact=None, host_static=None, policy_on=False,
                 spread_on=False, wkey=(), has_static_cols=False):
        self.vocab_gen = vocab_gen
        self.labels_gen = labels_gen  # snapshot.labels_gen at build: the
        # topology views (key_node/static_forbid_hit/labels_aff) bake
        # label CONTENT, which vocab_gen does not cover (delta relabel)
        self.key_index = key_index
        self.reps = reps
        self.cls_arr = cls_arr
        self.num_classes = num_classes
        self.c_pad = c_pad
        self.req_rows = req_rows      # [C, R] int64, snapshot-quantized
        self.special = special        # [C] bool: ports/volumes classes
        self.derived = derived        # per-class (Resource, ncpu, nmem, ports)
        self.ports_max = ports_max    # highest requested host port, or -1
        self.adata = adata            # AffinityData at c_pad, or None
        self.fits_on = fits_on        # required (anti-)affinity live
        self.prio_on = prio_on        # preferred-affinity scoring live
        self.wave_strict = adata.wave_strict if adata is not None \
            else np.zeros(c_pad, dtype=bool)
        # host-check / Policy absorption (ISSUE 18): host_exact classes
        # ride the wave as inactive padding-class rows and place at the
        # harvest's exact oracle tail (live-NodeInfo ports, score-
        # affecting preference overflow, Policy order-dependence,
        # affinity slot overflow); host_static classes carry a
        # precomputed exact label-pure fit column (cls_arr["host_fit"])
        # and place on the wave itself. Neither shape flushes the
        # pipeline anymore.
        self.host_exact = host_exact if host_exact is not None \
            else np.zeros(c_pad, dtype=bool)
        self.host_static = host_static if host_static is not None \
            else np.zeros(c_pad, dtype=bool)
        self.policy_on = policy_on    # policy_fit/policy_score baked
        self.spread_on = spread_on    # SelectorSpread riding frozen score
        # workload-set identity at build (the scheduler replaces workload
        # objects on watch events, so `is`-comparison detects any change);
        # compared only when workloads are placement-relevant (policy or
        # spread weight) — see _wave_encoding
        self.wkey = wkey
        # host/policy static columns bake LABEL CONTENT and workload
        # state; a labels_gen move invalidates the whole encoding (no
        # patch path for these columns — conservative, they are rare)
        self.has_static_cols = has_static_cols
        self.has_aff_pod = has_aff_pod if has_aff_pod is not None \
            else np.zeros(c_pad, dtype=bool)
        self.aff_seq = aff_seq        # expected cache.aff_seq (own folds in)
        # device bundles: the wave loop's per-node form and the strict
        # tail's projected-domain form (see _wave_encoding)
        self.aff_wave_dev = aff_wave_dev
        self.aff_tail_dev = aff_tail_dev
        self.key_node = key_node                    # np int8 [C, A, N]
        self.static_forbid_hit = static_forbid_hit  # np int8 [C, N]
        self.tail_cols = tail_cols                  # np int64 [Lp]
        self.committed_nodes = np.zeros((c_pad, n_pad), dtype=np.int32) \
            if fits_on else None
        # Protean overlays (ISSUE 8): FOREIGN churn patched in since the
        # build instead of rebuilt over. foreign_forbid [C, N] counts
        # foreign pods matching class c's required-anti selectors resident
        # on node n (merged into the device static_forbid + both fence
        # views); foreign_forbid_dom is the same over the tail's projected
        # domain columns (multi-node-domain terms of strict-tail classes).
        # Counts, not booleans, so an unbind of a PATCHED source decrements
        # exactly; a build-time static source leaving keeps its baked 0/1
        # hit (forbidding too much is the safe side — the next full
        # rebuild, whenever vocab growth forces one, trues it up).
        self.foreign_forbid = np.zeros((c_pad, n_pad), dtype=np.int32) \
            if fits_on else None
        self.foreign_forbid_dom = np.zeros(
            (c_pad, len(tail_cols)), dtype=np.int32) \
            if fits_on and tail_cols is not None else None
        self.aff_patch_dirty = False
        # per-class required term lists for foreign-event matching
        # [(class, slot, term, rep)] — empty for affinity-free encodings
        self.anti_terms: list = []
        self.aff_terms: list = []
        # raw int64 per-class delta rows (requested cpu/mem/gpu/scratch/
        # overlay + nonzero cpu/mem) for snapshot.apply_assume_delta, and
        # which classes qualify for it (no ports/volumes/extended — those
        # touch more than the seven raw columns)
        self.raw_rows = np.empty((num_classes, 7), dtype=np.int64)
        self.delta_ok = np.empty(num_classes, dtype=bool)
        for c, (req, ncpu, nmem, ports) in enumerate(derived):
            self.raw_rows[c] = (req.milli_cpu, req.memory, req.nvidia_gpu,
                                req.storage_scratch, req.storage_overlay,
                                ncpu, nmem)
            self.delta_ok[c] = not (ports or req.extended or special[c])
        # per-class PRIORITY column (ISSUE 14): rides the raw-delta fold
        # into the snapshot's band aggregates — class keys include
        # priority (state/classes.py), so this is exact per class
        self.cls_prio = np.fromiter((rep.priority for rep in reps),
                                    dtype=np.int64, count=num_classes)


class WaveHandle:
    """One in-flight pipelined wave: the un-fetched device result plus
    everything the harvest fence needs. Holding this without calling
    np.asarray on `packed` is the whole point — the device computes while
    the host does the previous wave's bookkeeping."""

    __slots__ = ("pods", "pc", "enc", "packed", "state_out", "counter_out",
                 "nodes", "blind", "pop_ts", "dispatch_ts", "pad_floor",
                 "committed_out", "strict_idx", "gangs", "wave_id",
                 "host_idx")

    def __init__(self, pods, pc, enc, packed, state_out, counter_out, nodes,
                 blind, pop_ts, dispatch_ts, pad_floor=0,
                 committed_out=None, strict_idx=None, gangs=None,
                 wave_id=-1, host_idx=None):
        self.pad_floor = pad_floor
        self.pods = pods
        self.pc = pc                  # host int32 [n] class index per pod
        self.enc = enc
        self.packed = packed          # device [3P+2] (see waves_loop)
        self.state_out = state_out    # device NodeState after the waves
        self.counter_out = counter_out  # device uint32 RR counter
        self.nodes = nodes            # device node arrays at dispatch time
        self.blind = blind            # node NAMES mutated since dispatch
        self.pop_ts = pop_ts
        self.dispatch_ts = dispatch_ts
        self.committed_out = committed_out  # device [C,N] topology occupancy
        # pods routed to the seeded strict tail (wave_strict classes) —
        # inactive on the wave path, placed by harvest's tail scan
        self.strict_idx = strict_idx if strict_idx is not None \
            else np.empty(0, dtype=np.int64)
        # quorum-ready gangs riding this wave (ISSUE 5): [(name, member
        # indices into `pods`, quorum)] — the harvest's gang fence commits
        # or atomically rolls back each one
        self.gangs = gangs or []
        # host_exact rows (ISSUE 18): riding as inactive padding-class
        # rows, placed by the harvest's exact oracle tail AFTER the
        # fence — never counted unschedulable off the device result
        self.host_idx = host_idx if host_idx is not None \
            else np.empty(0, dtype=np.int64)
        # flight-recorder wave id (ISSUE 13): joins this wave's dispatch /
        # harvest / bind-flush events on the exported timeline; -1 when
        # the recorder was off at dispatch
        self.wave_id = wave_id

    def block(self) -> None:
        """Force device completion now (sequential/debug mode): the values
        are identical whenever fetched; only the overlap is forfeited."""
        self.packed.block_until_ready()  # graftlint: sync-ok — this
        # method EXISTS to stall (overlap=False debug mode)


class WaveHarvest:
    """Fenced result of one wave: pods to bind (node_name set, already
    assumed), fence conflicts to requeue WITHOUT backoff (a capacity race
    with the blind wave, not unschedulability), unschedulable pods, and —
    for gang-bearing waves (ISSUE 5) — the gangs whose quorum committed
    (the caller marks them degraded) plus the members of gangs the fence
    ROLLED BACK atomically (requeue WITH backoff: the gang lost as a
    unit, exactly the below-quorum rollback of the classic round)."""

    __slots__ = ("bound", "conflicts", "unschedulable", "t_block",
                 "gang_committed", "gang_requeued", "liveness_requeued",
                 "conflict_reasons")

    def __init__(self, bound, conflicts, unschedulable, t_block,
                 gang_committed=None, gang_requeued=None,
                 liveness_requeued=None, conflict_reasons=None):
        self.bound = bound
        self.conflicts = conflicts
        self.unschedulable = unschedulable
        self.t_block = t_block
        self.gang_committed = gang_committed or []
        self.gang_requeued = gang_requeued or []  # [(pod, reason)]
        # rows whose target node died / was cordoned mid-flight (ISSUE 8):
        # requeue WITH backoff — not a capacity race, not unschedulability
        self.liveness_requeued = liveness_requeued or []
        # typed requeue attribution (ISSUE 15): podtrace.REASON_* code
        # per entry of `conflicts`, parallel — capacity races vs topology
        # vs stale encodings stop folding into one count
        self.conflict_reasons = conflict_reasons or []


class SchedulingEngine:
    def __init__(self, cache: SchedulerCache,
                 priorities: Tuple[Tuple[str, int], ...] = prio.DEFAULT_PRIORITIES,
                 mem_shift: int = 10, workloads_provider=None,
                 hard_pod_affinity_weight: int = 1,
                 volume_ctx=None, policy_algos=None, mesh=None):
        from kubernetes_tpu.state.volumes import VolumeContext
        self.cache = cache
        self.priorities = priorities
        # Policy-configured parameterized algorithms (ServiceAffinity,
        # NodeLabelPresence, NodeLabel, ServiceAntiAffinity) — the
        # CreateFromConfig arguments (ops/policy_algos.py)
        self.policy_algos = policy_algos
        # resident device mesh (ISSUE 12): a 1-D jax.sharding.Mesh whose
        # axis is the NODE axis. When set, every node-indexed device
        # buffer this engine owns — the snapshot sync, the wave
        # encodings' topology views, the committed-occupancy seed — is
        # uploaded SHARDED across the mesh and stays resident between
        # waves; waves_loop runs its explicit two-stage SPMD path. A
        # single-device mesh is meaningless residency — treat as None
        # (the unsharded engine IS the one-device layout).
        self.mesh = None
        self._rmesh = None
        if mesh is not None and int(mesh.devices.size) > 1:
            from kubernetes_tpu.parallel.mesh import ResidentMesh
            self.mesh = mesh
            self._rmesh = ResidentMesh(mesh)
            # the node axis pads to a multiple of BOTH the baseline
            # alignment (8) and the device count so shard_map splits it
            # evenly on any mesh size (a bare max(8, D) breaks D=3/5/6/7:
            # N padded to a multiple of 8 need not divide by D)
            import math
            self.snapshot = ClusterSnapshot(
                mem_shift=mem_shift,
                node_pad=math.lcm(8, int(mesh.devices.size)))
        else:
            self.snapshot = ClusterSnapshot(mem_shift=mem_shift)
        # PV/PVC mirror (the pvInfo/pvcInfo listers of factory.go); the
        # owner (Scheduler) mutates it and bumps .version on watch events
        self.volume_ctx = volume_ctx if volume_ctx is not None else VolumeContext()
        self.rr = oracle.RoundRobin()  # shared counter, device + oracle paths
        # Service/RC/RS/SS objects for spreading & service affinity — the
        # factory's extra informers (factory.go:120-140)
        self.workloads_provider = workloads_provider or (lambda: [])
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self._device_nodes = None
        self._device_version = -1
        # priority-band device bundle for the wave-path victim scan
        # (ISSUE 14): uploaded on demand, keyed on snapshot.version —
        # preemption rounds are rare next to waves, so this stays out of
        # _nodes_on_device and its upload counters entirely
        self._prio_dev = None
        self._prio_dev_version = -1
        # targeted-refresh bookkeeping: when the OWNER (one Scheduler that
        # routes every cache mutation through note_node_dirty/
        # note_full_refresh) sets track_dirty, _refresh() passes the dirty
        # node set as snapshot.refresh's changed_hint instead of walking all
        # N generation counters per round. Default off: a bare engine whose
        # cache is mutated behind its back (tests, ad-hoc callers) cannot
        # uphold the hint's assertion.
        self.track_dirty = False
        self._pending_dirty: set = set()
        self._need_full_refresh = True
        # liveness fence (ISSUE 8): node names the OWNER declared dying
        # (DELETED / cordoned / NotReady watch event observed but not yet
        # applied to the cache) — the harvest fence requeues any blind-wave
        # row targeting one instead of binding into a ghost. The owner
        # marks BEFORE flushing the pipeline and clears after the event is
        # applied (the refreshed snapshot then carries the verdict itself).
        self._doomed_nodes: set = set()
        # pipelined-drain state (dispatch_waves/harvest_waves)
        self._wave_enc = None
        self._rr_chain = None  # device RR counter chaining between waves
        # per-encoding cache of waves.precompute (the capacity-INdependent
        # [C, N] tensors): every wave/tail dispatch of a drain used to
        # recompute the selector/taint/node-affinity label-axis matmuls —
        # the largest per-dispatch device cost once the loops themselves
        # went round-granular (ISSUE 5). Keyed on the encoding object and
        # the IDENTITY of the static node device arrays (_nodes_on_device
        # replaces a buffer only when the snapshot marked it dirty, so
        # identity is the exact staleness signal).
        self._pre_cache = None
        self._blind_listeners: List[set] = []  # per-inflight-wave touch sets
        # pod-axis padding floor for dispatch_waves: the pipeline pins this
        # to its chunk size so an arrival stream's ragged pops (345, 589,
        # 100, ...) all reuse ONE compiled wave shape instead of paying a
        # multi-second XLA compile per fresh power-of-2 bucket mid-stream
        self.wave_pad_floor = 0
        # conflict-round tail (ISSUE 5): the harvest's seeded strict tail
        # runs as waves.tail_rounds_loop (round-depth sequentiality, exact
        # required-affinity semantics, wave-style tie-breaks) when the
        # tail is big enough to pay for the round body; small tails keep
        # the per-pod scan, whose per-step cost is a fraction of a round.
        # GRAFT_TAIL_ROUNDS=0 forces the scan everywhere (the oracle mode
        # the tail-round fuzz compares against); GRAFT_TAIL_ROUNDS_MIN
        # moves the crossover (0 = rounds always).
        import os as _os
        self.tail_rounds = _os.environ.get("GRAFT_TAIL_ROUNDS", "1") != "0"
        self.tail_rounds_min = int(
            _os.environ.get("GRAFT_TAIL_ROUNDS_MIN", "48"))

    # ------------------------------------------------------------------ api

    def schedule(self, pods: Sequence[Pod], assume: bool = True,
                 mode: str = "strict") -> List[PlacementResult]:
        """Schedule a batch. Returns one PlacementResult per pod, in input
        order. When assume=True, successful placements are assumed into the
        cache with pod.node_name set (the caller binds asynchronously).

        mode="strict" reproduces the reference's sequential scheduleOne
        semantics exactly (engine/batch.py lax.scan); mode="wave" is the
        wave-parallel throughput mode (engine/waves.py) with identical
        predicate/priority integer semantics but batch-defined tie-spreading.
        """
        if not pods:
            return []
        infos = self._refresh()
        from kubernetes_tpu.ops.affinity import AffinityData, \
            collect_pod_pairs, intern_topology_pairs
        all_pairs, aff_pairs = collect_pod_pairs(infos)
        # topology keys referenced by ANY affinity term (pending or existing)
        # must be in the label vocab BEFORE the label matrix is finalized —
        # a key only an existing pod's anti-affinity mentions would otherwise
        # have no domain columns and the symmetry forbid would silently
        # evaporate (r2 correctness bug; ref predicates.go:1146)
        intern_topology_pairs(self.snapshot, pods, aff_pairs)
        # ClassBatch next: selector compilation may grow the label vocab and
        # rebuild the label matrix; upload happens after, dirty-arrays only.
        # Encoding runs once per distinct pod spec (state/classes.py — the
        # tensor analog of the equivalence cache, equivalence_cache.go:54).
        batch = ClassBatch(pods, self.snapshot)

        # Affinity/spread class data (ops/affinity.py): static domain
        # vectors vs existing pods, class-to-class match matrices for
        # in-batch interactions, workload membership for spreading. Replaces
        # the round-1 host-path routing of every affinity-bearing pod —
        # only slot-overflow classes fall back to the oracle now.
        c_pad = bucket(batch.num_classes + 1)
        adata = AffinityData(batch.reps, self.snapshot, all_pairs, aff_pairs,
                             self.workloads_provider(),
                             self.hard_pod_affinity_weight, c_pad=c_pad)
        for c in np.nonzero(adata.overflow[:batch.num_classes])[0]:
            batch.mark_host_check_class(int(c))
        policy_active = self.policy_algos is not None \
            and self.policy_algos.active
        workloads_now = None
        if policy_active:
            workloads_now = self.workloads_provider()
            # service-coupled classes are order-dependent in-batch (the
            # reference's pod lister is the scheduler cache) -> host path
            for c in np.nonzero(self.policy_algos.needs_host(
                    batch.reps, workloads_now))[0]:
                batch.mark_host_check_class(int(c))

        # Split BEFORE the per-class static arrays and device transfers:
        # a mixed batch throws this call's remaining staging work away.
        nhc = batch.reps_batch.needs_host_check[batch.pod_class]
        if mode == "strict" and assume and nhc.any() and not nhc.all():
            # exact scheduleOne sequencing across the host/device boundary:
            # a host-path pod between two device pods must see the first's
            # commit and be seen by the second's (scheduler.go:253 is one
            # strict FIFO). Process maximal same-path runs in order, each
            # through the full pipeline against the updated cache; flags are
            # class-deterministic, so each run is homogeneous and recursion
            # terminates after one level.
            results = []
            i = 0
            while i < len(pods):
                j = i + 1
                while j < len(pods) and nhc[j] == nhc[i]:
                    j += 1
                results.extend(self.schedule(list(pods[i:j]), assume=True,
                                             mode=mode))
                i = j
            return results

        policy_arrays = None
        if policy_active:
            policy_arrays = self.policy_algos.static_class_arrays(
                batch.reps, self.snapshot, workloads_now, all_pairs, c_pad,
                skip=batch.reps_batch.needs_host_check[:batch.num_classes])
        w_ip = sum(w for nm, w in self.priorities
                   if nm == "InterPodAffinityPriority")
        w_sp = sum(w for nm, w in self.priorities
                   if nm == "SelectorSpreadPriority")
        fits_on = adata.fits_needed
        prio_on = bool(w_ip) and adata.prio_needed
        spread_on = bool(w_sp) and adata.spread_needed
        aff_mode = (fits_on, prio_on, spread_on)
        aff_arrays = adata.device_arrays() if any(aff_mode) else None
        kernel_priorities = self.priorities if aff_arrays is not None else \
            tuple((nm, w) for nm, w in self.priorities
                  if nm not in prio.AFFINITY_PRIORITIES)
        # size the port bitmap to the highest word any node uses or any batch
        # pod requests (power-of-2 bucketed so the compiled shapes are stable)
        max_words = self.snapshot.port_words_used()
        if np.any(batch.reps_batch.ports >= 0):
            max_words = max(max_words,
                            int(batch.reps_batch.ports.max()) // 32 + 1)
        port_words = bucket(max(max_words, 1), lo=1)
        nodes = self._nodes_on_device(port_words=port_words)

        fast_idx = np.nonzero(~nhc)[0]
        slow_idx = np.nonzero(nhc)[0].tolist()
        results: List[Optional[PlacementResult]] = [None] * len(pods)

        if len(fast_idx):
            # shape bucketing: pad the class axis and the pod axis to
            # power-of-2 buckets so round-over-round batch sizes reuse the
            # same compiled kernels. Padding classes are `impossible` (fit
            # nothing, commit nothing, no RR ticks) and padding pods map to
            # the first padding class.
            from kubernetes_tpu.ops.predicates import pod_arrays_padded
            cls_arr = pod_arrays_padded(batch.reps_batch, c_pad)
            if policy_arrays is not None:
                pfit, pscore = policy_arrays
                if pfit is not None:
                    cls_arr["policy_fit"] = jnp.asarray(pfit)
                if pscore is not None:
                    cls_arr["policy_score"] = jnp.asarray(pscore)
            pf = len(fast_idx)
            p_pad = bucket(pf)
            pc_fast = np.full(p_pad, batch.num_classes, dtype=np.int32)
            pc_fast[:pf] = batch.pod_class[fast_idx]
            state = NodeState(nodes["requested"], nodes["nonzero"],
                              nodes["pod_count"], nodes["port_bitmap"],
                              nodes["vol_present"], nodes["vol_rw"],
                              nodes["pd_present"], nodes["pd_counts"])
            if mode == "wave":
                selected, fit_counts, rr_end = self._run_wave(
                    batch, adata, cls_arr, nodes, state, fast_idx, pc_fast,
                    pf, aff_arrays, aff_mode, kernel_priorities,
                    (w_ip, w_sp))
            else:
                selected, fit_counts, _, rr_end = gather_place_batch(
                    cls_arr, jnp.asarray(pc_fast), nodes, state,
                    jnp.uint32(self.rr.counter), kernel_priorities,
                    aff=aff_arrays, aff_mode=aff_mode)
                # the synchronous engine's result fetch: schedule() owes
                # its caller host placements, so the stall is the contract
                selected = np.asarray(selected)[:pf]  # graftlint: sync-ok
                fit_counts = np.asarray(fit_counts)[:pf]  # graftlint: sync-ok
            self.rr.counter = int(rr_end)  # graftlint: sync-ok — scalar
            # draw-count fetch rides the result fetch above (device idle)
            names = self.snapshot.node_names
            placements = []
            # plain-int lists: numpy scalar indexing in a 30k-iteration loop
            # costs ~3x a list walk
            sel_l = np.asarray(selected).tolist()
            fc_l = np.asarray(fit_counts).tolist()
            pc_l = pc_fast.tolist()
            mk = PlacementResult
            for j, i in enumerate(fast_idx.tolist()):
                sel = sel_l[j]
                pod = pods[i]
                if sel >= 0:
                    name = names[sel]
                    results[i] = mk(pod, name, fc_l[j])
                    if assume:
                        pod.node_name = name
                        placements.append((pod, pc_l[j]))
                else:
                    results[i] = mk(pod, None, fc_l[j])
            if placements:
                # one lock + one derived-quantity walk per PLACED class
                derived: Dict[int, tuple] = {}
                for _, c in placements:
                    if c not in derived:
                        rep = batch.reps[c]
                        derived[c] = (rep.resource_request(),
                                      *rep.nonzero_request(),
                                      rep.used_ports())
                self.cache.assume_pods_bulk(placements, derived)
                self._touch(p.node_name for p, _ in placements)

        # exact host path for over-approximated pods, AFTER device placements
        # so they see committed capacity (FIFO order within themselves)
        if slow_idx:
            from kubernetes_tpu.ops.oracle_ext import SchedulingContext
            infos = self.cache.node_infos()
            names = self.snapshot.node_names
            ctx = SchedulingContext(
                infos, self.workloads_provider(),
                hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                volume_ctx=self.volume_ctx,
                policy_algos=self.policy_algos)
            for i in slow_idx:
                name = oracle.schedule_one(pods[i], names, infos, self.rr,
                                           self.priorities, ctx)
                results[i] = PlacementResult(pods[i], name, 1 if name else 0)
                if name is not None and assume:
                    self._assume(pods[i], name)
                    infos = self.cache.node_infos()
                    ctx.infos = infos
                    ctx.invalidate()

        return results  # type: ignore[return-value]

    # ------------------------------------------------------------- internals

    def _run_wave(self, batch, adata, cls_arr, nodes, state, fast_idx,
                  pc_fast, pf, aff_arrays, aff_mode, kernel_priorities,
                  weights):
        """Wave mode with affinity routing: classes whose REQUIRED
        (anti-)affinity makes placement order-dependent run through the
        strict scan AFTER the wave pass — seeded with the wave's topology
        occupancy so in-batch interactions stay exact — while everything
        else takes the throughput path with batch-frozen spread/interpod
        scores (waves.frozen_affinity_scores)."""
        w_ip, w_sp = weights
        fits_on, prio_on, spread_on = aff_mode
        extra = None
        if prio_on or spread_on:
            extra = waves.frozen_affinity_scores(
                cls_arr, nodes, state, aff_arrays,
                (w_ip if prio_on else 0, w_sp if spread_on else 0))
        ser = adata.serialize[pc_fast[:pf]]
        selected = np.full(pf, -1, dtype=np.int32)
        fit_counts = np.zeros(pf, dtype=np.int32)
        rr = self.rr.counter
        wave_pos = np.nonzero(~ser)[0]
        strict_pos = np.nonzero(ser)[0]
        state_cur = state
        if len(wave_pos):
            wp = len(wave_pos)
            pcw = np.full(bucket(wp), batch.num_classes, dtype=np.int32)
            pcw[:wp] = pc_fast[wave_pos]
            # aff/aff_mode reach only the straggler fallback inside
            # place_waves: preferred scoring stays batch-frozen (extra),
            # so prio/spread are off there to avoid double-counting
            sel_w, fc_w, state_cur, rr = waves.place_waves(
                cls_arr, nodes, state_cur, pcw, rr, kernel_priorities,
                extra_score=extra, aff=aff_arrays,
                aff_mode=(fits_on, False, False))
            selected[wave_pos] = sel_w[:wp]
            fit_counts[wave_pos] = fc_w[:wp]
        if len(strict_pos):
            sp_n = len(strict_pos)
            pcs = np.full(bucket(sp_n), batch.num_classes, dtype=np.int32)
            pcs[:sp_n] = pc_fast[strict_pos]
            aff_init = None
            if aff_arrays is not None:
                c_dim = aff_arrays["m_aff"].shape[0]
                comm_np = np.zeros((c_dim, int(nodes["alloc"].shape[0])),
                                   dtype=np.int32)
                for j in wave_pos:
                    if selected[j] >= 0:
                        comm_np[pc_fast[j], selected[j]] += 1
                committed0 = jnp.asarray(comm_np)
                commdom0 = committed0 @ nodes["labels"].astype(jnp.int32)
                comm_cnt0 = committed0.sum(axis=1)
                aff_init = (commdom0, committed0, comm_cnt0)
            sel_s, fc_s, _, rr_d = gather_place_batch(
                cls_arr, jnp.asarray(pcs), nodes, state_cur,
                jnp.uint32(rr), kernel_priorities, aff=aff_arrays,
                aff_mode=aff_mode, aff_init=aff_init)
            # strict-tail result fetch (classic wave mode is synchronous
            # by definition — the caller consumes placements immediately)
            selected[strict_pos] = np.asarray(sel_s)[:sp_n]  # graftlint: sync-ok
            fit_counts[strict_pos] = np.asarray(fc_s)[:sp_n]  # graftlint: sync-ok
            rr = int(rr_d)  # graftlint: sync-ok (scalar, device idle)
        return selected, fit_counts, rr

    def _assume(self, pod: Pod, node_name: str) -> None:
        pod.node_name = node_name
        self.cache.assume_pod(pod)
        self._touch((node_name,))

    # ------------------------------------------------- targeted refresh

    def _touch(self, node_names) -> None:
        """Record cache mutations for BOTH consumers: the targeted-refresh
        dirty set (cleared each refresh) and any in-flight wave's blind set
        (cleared at that wave's harvest — its fence must re-validate
        against exactly these nodes)."""
        if self.track_dirty or self._blind_listeners:
            names = list(node_names)
            if self.track_dirty:
                self._pending_dirty.update(names)
            for s in self._blind_listeners:
                s.update(names)

    def note_node_dirty(self, *node_names: str) -> None:
        """The owner observed a cache mutation touching these nodes (watch
        event applied, bind forgotten)."""
        self._touch(node_names)

    def note_full_refresh(self) -> None:
        """The owner cannot name what changed (node membership/spec moved,
        assumed-pod TTL expiry) — the next refresh walks everything."""
        self._need_full_refresh = True

    def note_node_doomed(self, *node_names: str) -> None:
        """The owner observed a node-dying watch event (DELETED, cordon,
        NotReady) it has NOT yet applied: any in-flight wave row targeting
        these nodes must requeue at the fence, not bind (ISSUE 8)."""
        self._doomed_nodes.update(node_names)

    def clear_node_doomed(self, *node_names: str) -> None:
        """The dying event is applied — the snapshot now carries the
        verdict (schedulable=False / node absent), so the doom mark is
        redundant for every later dispatch."""
        self._doomed_nodes.difference_update(node_names)

    def _refresh(self) -> Dict[str, object]:
        """Snapshot refresh with the targeted-hint fast path when the owner
        tracks dirt (ISSUE 2: the batch drain's analog of the extender's
        per-bind changed_hint). Returns the infos map."""
        infos = self.cache.node_infos()
        hint = None
        if self.track_dirty and not self._need_full_refresh \
                and self.snapshot._shape_sig is not None:
            hint = sorted(self._pending_dirty)
        self.snapshot.refresh(infos, volume_ctx=self.volume_ctx,
                              changed_hint=hint)
        self._pending_dirty.clear()
        self._need_full_refresh = False
        return infos

    _NODE_ARRAY_KEYS = ("alloc", "requested", "nonzero", "pod_count",
                        "allowed_pods", "schedulable", "mem_pressure",
                        "disk_pressure", "labels", "taints_sched",
                        "taints_pref", "port_bitmap", "valid", "avoid",
                        "image_sizes", "has_zone", "vol_present", "vol_rw",
                        "pd_present", "pd_counts", "pd_kind", "pd_max")

    def _nodes_on_device(self, port_words: int = 1):
        """Incremental host->HBM sync: re-upload an array only when its shape
        changed or the snapshot marked it dirty. Steady-state rounds move only
        requested/nonzero/pod_count (~KBs), not the 40MB+ full snapshot.

        port_words: how many 32-bit words of the 65536-bit per-node port
        bitmap to ship — the caller sizes it to cover the highest port in use
        by any node or requested by any batch pod (bucketed, so width changes
        rarely); a cluster with no host ports uploads one zero word per node
        instead of 8KB.

        With a resident mesh (ISSUE 12) every array uploads SHARDED via the
        shared spec tables and the dynamic arrays ride the ROW-DELTA path:
        when the snapshot can name the touched rows (snapshot.dirty_rows —
        the apply_assume_delta / bulk-writer contract), only the shards
        owning those rows re-upload; untouched shards keep their existing
        device buffers by reference. The upload unit is a whole shard, so
        a micro-wave's assume fold moves O(touched_shards x N/D) rows —
        a fraction of the full [N, R] mirror whenever the fold doesn't
        touch every shard (engine.shard_upload_bytes counts the actual
        traffic)."""
        snap = self.snapshot
        if self._device_nodes is None:
            self._device_nodes = {}
        rmesh = self._rmesh
        rows = snap.dirty_rows if rmesh is not None else None
        uploaded = 0
        delta_used = False
        delta_bytes = 0
        for k in self._NODE_ARRAY_KEYS:
            if k == "port_bitmap":
                host = snap.port_bitmap[:, :port_words]
            else:
                host = getattr(snap, k)
            cur = self._device_nodes.get(k)
            if cur is None or cur.shape != host.shape or k in snap.dirty:
                # COPY, never alias: the CPU backend zero-copies aligned
                # numpy buffers, and these snapshot arrays are mutated in
                # place (refresh deltas, apply_assume_delta) while a
                # pipelined wave may still be executing against them
                # asynchronously. The pragma makes GL001 reject any future
                # jnp.asarray "optimization" here; GRAFT_SANITIZE=1
                # additionally asserts the upload really did not alias.
                # (The mesh path inherits the contract: upload_copied
                # sharded copies host-side before placement, and
                # ResidentMesh.update_rows copies each touched slice.)
                if rmesh is not None:
                    if rows is not None and cur is not None \
                            and cur.shape == host.shape \
                            and k in snap.DYNAMIC:
                        self._device_nodes[k] = rmesh.update_rows(
                            cur, host, rows)
                        delta_used = True
                        delta_bytes += rmesh.touched_nbytes(host, rows)
                        continue
                    self._device_nodes[k] = sanitize.upload_copied(  # graftlint: copy-required
                        host, sharding=rmesh.node_sharding(k, host.ndim))
                else:
                    self._device_nodes[k] = sanitize.upload_copied(  # graftlint: copy-required
                        np.ascontiguousarray(host)
                        if k == "port_bitmap" else host)
                uploaded += 1
        if uploaded or delta_used:
            from kubernetes_tpu.utils.trace import COUNTERS
            if uploaded:
                COUNTERS.inc("engine.device_upload_arrays", uploaded)
            if delta_used:
                # DISTINCT rows this sync shipped through the per-shard
                # delta path (counted once, not once per dynamic array —
                # comparable to snapshot.assume_delta_rows' per-placement
                # count), plus the actual bytes moved (whole touched
                # shards, every dynamic array included)
                COUNTERS.inc("engine.shard_delta_rows", len(rows))
                COUNTERS.inc("engine.shard_upload_bytes", delta_bytes)
        snap.dirty.clear()
        if rmesh is not None:
            snap.dirty_rows = set()  # arm row tracking for the next sync
        self._device_version = snap.version
        return self._device_nodes

    # ------------------------------------------- wave-path preemption

    def _prio_on_device(self):
        """Device bundle for the victim scan: spare capacity columns plus
        the priority-band aggregates, quantized at upload (band sums
        CEIL, need floors — the over-approximation direction
        ops/preempt.py documents). Re-uploaded whenever the snapshot
        version moved; ~[N, B] int32s, a fraction of one wave upload."""
        snap = self.snapshot
        if self._prio_dev is not None \
                and self._prio_dev_version == snap.version:
            return self._prio_dev
        shift = snap.mem_shift
        host = {
            "spare_cpu": (snap.alloc[:, R_CPU].astype(np.int64)
                          - snap.requested[:, R_CPU]).astype(np.int32),
            "spare_mem": (snap.alloc[:, R_MEM].astype(np.int64)
                          - snap.requested[:, R_MEM]).astype(np.int32),
            "pod_count": snap.pod_count,
            "allowed": snap.allowed_pods,
            "band_cpu": snap.band_cpu.astype(np.int32),
            "band_mem": (-((-snap.band_mem) >> shift)).astype(np.int32),
            "band_count": snap.band_count,
            "band_prio": np.clip(snap.band_prio_host, -(2 ** 31) + 1,
                                 2 ** 31 - 1).astype(np.int32),
        }
        # COPY, never alias: pod_count/allowed/band_* are live snapshot
        # arrays mutated in place between preemption rounds (refresh
        # deltas, apply_assume_delta band folds)
        self._prio_dev = {
            k: sanitize.upload_copied(v)  # graftlint: copy-required
            for k, v in host.items()}
        self._prio_dev_version = snap.version
        return self._prio_dev

    def preempt_scan(self, pods: Sequence[Pod]):
        """ONE fused [C, N] victim pre-filter for a round of preemptors
        (ISSUE 14): returns (candidate [C, N] bool, bound [C, N] int32,
        class_of [len(pods)]) with C the padded unique-(need, priority)
        class count — or None when the band vocab overflowed / priorities
        exceed int32, routing the caller to the exact host pre-filter."""
        from kubernetes_tpu.ops import preempt as preempt_ops
        from kubernetes_tpu.utils.trace import COUNTERS

        snap = self.snapshot
        if snap.prio_band_overflow or not hasattr(snap, "band_cpu") \
                or not pods:
            return None
        shift = snap.mem_shift
        uniq: Dict[tuple, int] = {}
        rows: List[tuple] = []
        class_of: List[int] = []
        for p in pods:
            if not (-(2 ** 31) < p.priority < 2 ** 31):
                return None
            req = p.resource_request()
            key = (req.milli_cpu, req.memory, p.priority)
            c = uniq.get(key)
            if c is None:
                c = len(rows)
                uniq[key] = c
                # need: cpu exact, mem FLOOR-quantized (under-estimates
                # need — the superset direction)
                rows.append((req.milli_cpu, req.memory >> shift,
                             p.priority))
            class_of.append(c)
        # pad the class axis to the bucket ladder (GL003: a ragged
        # per-round preemptor count must never reach the jit); padding
        # rows carry PAD_PRIO, below every band — no candidates
        c_pad = bucket(len(rows), lo=4)
        need_cpu = np.zeros(c_pad, dtype=np.int32)
        need_mem = np.zeros(c_pad, dtype=np.int32)
        prio = np.full(c_pad, preempt_ops.PAD_PRIO, dtype=np.int32)
        for c, (cpu, mem_q, pr) in enumerate(rows):
            need_cpu[c] = min(cpu, 2 ** 31 - 1)
            need_mem[c] = min(mem_q, 2 ** 31 - 1)
            prio[c] = pr
        dev = self._prio_on_device()
        COUNTERS.inc("engine.preempt_scan_dispatch")
        cand_d, bound_d = preempt_ops.victim_scan_jit(
            jnp.asarray(need_cpu), jnp.asarray(need_mem),
            jnp.asarray(prio), dev["spare_cpu"], dev["spare_mem"],
            dev["pod_count"], dev["allowed"], dev["band_cpu"],
            dev["band_mem"], dev["band_count"], dev["band_prio"])
        # the scan's one result fetch: the host planner consumes the
        # candidate rows NOW — a preemption round is synchronous by
        # contract (it runs inside the harvest tail)
        cand = np.asarray(cand_d)  # graftlint: sync-ok
        bound = np.asarray(bound_d)  # graftlint: sync-ok (same fetch)
        return cand, bound, class_of

    # ------------------------------------------------- pipelined drain

    def _kernel_priorities(self) -> Tuple[Tuple[str, int], ...]:
        return tuple((nm, w) for nm, w in self.priorities
                     if nm not in prio.AFFINITY_PRIORITIES)

    _STATE_NODE_KEYS = frozenset({
        "requested", "nonzero", "pod_count", "port_bitmap",
        "vol_present", "vol_rw", "pd_present", "pd_counts",
        # node CONDITION arrays flip under churn (kills, NotReady flaps,
        # cordons, respawns) but precompute does not read them since
        # ISSUE 8 (node_condition_fit is ANDed fresh per dispatch) —
        # keying on them rebuilt the ~1s-at-5k-nodes static pre once per
        # fault event, which IS the churn throughput collapse
        "schedulable", "valid", "mem_pressure", "disk_pressure"})

    def _tail_wave_pre(self, enc: "_WaveEncoding", nodes):
        """The drain's shared waves.precompute instance (see _pre_cache).
        precompute reads only the class encoding and STATIC node arrays —
        the evolving NodeState is threaded separately — and it skips
        InterPodAffinity/SelectorSpread names outright, so one instance
        computed at the kernel priorities serves both the wave loop and
        the (possibly IP-bearing) tail priorities byte-for-byte."""
        from kubernetes_tpu.utils.trace import COUNTERS

        # the key holds the STATIC device arrays THEMSELVES (not their
        # id()s): the cache must keep them alive so a freed buffer's
        # recycled address can never alias a fresh upload into a stale hit
        key = tuple(nodes[k] for k in sorted(nodes)
                    if k not in self._STATE_NODE_KEYS)
        hit = self._pre_cache
        if hit is not None and hit[0] is enc and len(hit[1]) == len(key) \
                and all(a is b for a, b in zip(hit[1], key)):
            return hit[2]
        COUNTERS.inc("engine.wave_pre_build")
        pre = waves.precompute_jit(enc.cls_arr, nodes,
                                   self._kernel_priorities())
        self._pre_cache = (enc, key, pre)
        return pre

    # ---------------------------------------- Protean delta patch (ISSUE 8)

    def _try_patch_foreign(self, enc: "_WaveEncoding") -> bool:
        """Absorb FOREIGN occupancy churn into the cached wave encoding by
        patching exactly the rows it touched (PAPERS.md §Protean: key the
        cache on what invalidates it) instead of rebuilding AffinityData
        wholesale. Patchable events are plain pods entering/leaving known
        nodes: a plain pod matching an encoded class's required-ANTI
        selector adds/removes a forbidden source on exactly one node (and,
        for strict-tail classes, its projected domain columns); a plain
        pod matching nothing is a no-op for every topology view. Returns
        False — rebuild — when the event log no longer covers the gap, a
        churned pod CARRIES (anti-)affinity terms (it is a potential
        symmetry source whose own terms bake into forbid_static), it
        matches an encoded class's own required-AFFINITY selector (the
        allow set must both grow and shrink exactly), or its node is
        unknown to the snapshot. Delta-0 events (the pod's NodeInfo
        became a tombstone stub under the same name) are no-op patches:
        the snapshot keeps the row and its labels, so nothing the build
        resolved through that node moved."""
        from kubernetes_tpu.ops.affinity import _has_affinity
        from kubernetes_tpu.ops.oracle_ext import term_matches_pod
        from kubernetes_tpu.utils.trace import COUNTERS

        events = self.cache.aff_events_since(enc.aff_seq)
        if events is None:
            return False
        if not events:
            return True
        snap = self.snapshot
        ad = enc.adata
        patched = 0
        touched = False
        for _seq, pod, node_name, delta in events:
            if delta == 0:
                # "structure moved" sentinel: the pod's NodeInfo became a
                # TOMBSTONE stub under the same name (cache.remove_node).
                # The snapshot keeps the row and its label content, so
                # every domain the build resolved through that node —
                # the pod's own contributions AND any symmetry terms it
                # carries — is still exact: a tombstone move is a no-op
                # for the topology views whatever the pod carries.
                patched += 1
                continue
            if _has_affinity(pod):
                return False  # potential symmetry source entering or
                # leaving: its own terms bake into forbid_static — no
                # row patch expresses that
            if ad is None:
                # affinity-free encoding: plain churn cannot touch it —
                # advancing the expectation IS the patch
                patched += 1
                continue
            for _c, _s, term, rep in enc.aff_terms:
                if term_matches_pod(term, rep, pod):
                    return False  # allow-set delta: must be exact both ways
            n_idx = snap.node_index.get(node_name, -1)
            if n_idx < 0:
                return False
            for c, a, term, rep in enc.anti_terms:
                if not term_matches_pod(term, rep, pod):
                    continue
                ff = enc.foreign_forbid
                if ff is not None:
                    if delta > 0:
                        ff[c, n_idx] += 1
                        touched = True
                    elif ff[c, n_idx] > 0:
                        ff[c, n_idx] -= 1
                        touched = True
                    # else: a build-time static source left — the baked
                    # 0/1 hit cannot decrement; stay forbidden (safe side)
                fd = enc.foreign_forbid_dom
                if fd is not None and enc.tail_cols is not None \
                        and enc.tail_cols.size:
                    cols_hit = (
                        (ad.anti_keymask[c, a, enc.tail_cols] > 0)
                        & (snap.labels[n_idx, enc.tail_cols] > 0))
                    if delta > 0:
                        fd[c, cols_hit] += 1
                        touched = True
                    else:
                        dec = cols_hit & (fd[c] > 0)
                        if dec.any():
                            fd[c, dec] -= 1
                            touched = True
            patched += 1
        enc.aff_seq = events[-1][0]
        if touched:
            enc.aff_patch_dirty = True
        COUNTERS.inc("engine.aff_patch_rows", patched)
        if patched and RECORDER.enabled:
            RECORDER.record(flightrec.PATCH, a=patched)
        return True

    def _try_patch_labels(self, enc: "_WaveEncoding", infos) -> bool:
        """Absorb label-CONTENT churn (relabels to already-interned
        columns) into the cached encoding by re-deriving the topology
        projections of exactly the touched node ROWS. The gate is
        COLUMN-aware: a relabel only forces a rebuild when the changed
        columns intersect the domains a baked array actually resolved
        through — a zone flip on a node hosting anti-affinity targets is
        patchable when every anti term keys on hostname columns (the
        dominant production shape). Rebuild triggers: a changed column
        under a term keymask whose selector matches a resident pod (the
        baked forbid/allow domain moved), a resident pods_with_affinity
        whose OWN term topology keys cover a changed column (its symmetry
        contribution moved), patched foreign-forbid weight riding changed
        columns, or a relabel that merges two nodes into one anti domain
        of a wave-eligible class (the singleton-domain invariant the
        per-node wave mask rides)."""
        from kubernetes_tpu.ops.affinity import _term_topology_keys
        from kubernetes_tpu.ops.oracle_ext import term_matches_pod
        from kubernetes_tpu.utils.trace import COUNTERS

        snap = self.snapshot
        entries = snap.labels_rows_since(enc.labels_gen)
        if entries is None:
            return False
        if not entries:
            return True
        ad = enc.adata
        if ad is None:
            enc.labels_gen = snap.labels_gen
            return True
        L = ad.anti_keymask.shape[2]
        by_row: Dict[int, set] = {}
        for r, cols in entries:
            by_row.setdefault(r, set()).update(
                int(c) for c in cols if c < L)
        rows = sorted(by_row)
        names = snap.node_names
        vocab_cols = snap.label_vocab.by_key
        for r in rows:
            if r >= len(names):
                return False
            info = infos.get(names[r])
            if info is None:
                return False
            cols = np.asarray(sorted(by_row[r]), dtype=np.int64)
            if cols.size == 0:
                continue
            for c, a, term, rep in enc.anti_terms:
                if ad.anti_keymask[c, a, cols].any() and any(
                        term_matches_pod(term, rep, q) for q in info.pods):
                    return False  # a baked forbid source's domain moved
            for c, s, term, rep in enc.aff_terms:
                if ad.aff_keymask[c, s, cols].any() and any(
                        term_matches_pod(term, rep, q) for q in info.pods):
                    return False  # a baked allow source's domain moved
            colset = by_row[r]
            for q in info.pods_with_affinity:
                for key in _term_topology_keys(q):
                    if any(k < L and k in colset
                           for k in vocab_cols.get(key, ())):
                        return False  # a symmetry source's domain moved
            if enc.foreign_forbid is not None \
                    and enc.foreign_forbid[:, r].any() and any(
                        ad.anti_keymask[c, a, cols].any()
                        for c, a, _t, _rep in enc.anti_terms):
                return False  # patched per-node weight resolved through
                # a column this relabel moved
            if enc.foreign_forbid_dom is not None \
                    and enc.tail_cols is not None and enc.tail_cols.size:
                in_tail = np.isin(enc.tail_cols, cols)
                if in_tail.any() \
                        and enc.foreign_forbid_dom[:, in_tail].any():
                    return False
        if enc.key_node is not None:
            km = ad.anti_keymask                            # [C, A, L]
            wave_cls = ~ad.wave_strict                      # [C]
            km_wave = km[wave_cls]
            all_cols = sorted(set().union(*by_row.values())) \
                if by_row else []
            if km_wave.size and all_cols:
                # singleton-domain invariant check over the wave-eligible
                # classes' anti columns this relabel touched
                cols_arr = np.asarray(all_cols, dtype=np.int64)
                active = km_wave.astype(bool).any(axis=(0, 1))[cols_arr]
                hit = cols_arr[active]
                if hit.size and np.any(
                        snap.domain_node_counts()[hit] > 1):
                    return False
            C_, A_, L_ = km.shape
            lab_t = snap.labels[rows].astype(np.float64).T  # [L, r]
            kn_rows = ((km.reshape(C_ * A_, L_).astype(np.float64) @ lab_t)
                       > 0).reshape(C_, A_, len(rows))
            # copy-on-write: the current arrays back frozen device uploads
            # (sanitize seals them) — never mutate them in place
            key_node = enc.key_node.copy()
            key_node[:, :, rows] = kn_rows.astype(np.int8)
            enc.key_node = key_node
            sfh = enc.static_forbid_hit.copy()
            sfh[:, rows] = ((ad.forbid_static.astype(np.float64) @ lab_t)
                            > 0).astype(np.int8)
            enc.static_forbid_hit = sfh
            enc.aff_patch_dirty = True
        if enc.tail_cols is not None and enc.aff_tail_dev is not None:
            enc.aff_tail_dev["labels_aff"] = sanitize.upload_frozen(
                snap.labels[:, enc.tail_cols],
                sharding=None if self._rmesh is None
                else self._rmesh.aff_sharding("labels_aff"))
        enc.labels_gen = snap.labels_gen
        COUNTERS.inc("engine.label_patch_rows", len(rows))
        if rows and RECORDER.enabled:
            RECORDER.record(flightrec.PATCH, b=len(rows))
        return True

    def _flush_aff_patches(self, enc: "_WaveEncoding") -> None:
        """Re-upload the device views a patch invalidated — one batched
        refresh per dispatch, however many events were absorbed. Fresh
        temporaries are frozen (never the live overlays: those keep
        mutating patch over patch)."""
        if not enc.aff_patch_dirty:
            return

        def _sh(k):
            return None if self._rmesh is None \
                else self._rmesh.aff_sharding(k)
        if enc.aff_wave_dev is not None:
            merged = enc.static_forbid_hit.astype(np.int32)
            if enc.foreign_forbid is not None:
                merged = merged + enc.foreign_forbid
            enc.aff_wave_dev["static_forbid"] = sanitize.upload_frozen(
                np.minimum(merged, 127).astype(np.int8),
                sharding=_sh("static_forbid"))
            enc.aff_wave_dev["key_node"] = sanitize.upload_frozen(
                enc.key_node.copy(), sharding=_sh("key_node"))
        if enc.aff_tail_dev is not None and enc.tail_cols is not None:
            base = enc.adata.forbid_static[:, enc.tail_cols].astype(np.int32)
            if enc.foreign_forbid_dom is not None:
                base = base + enc.foreign_forbid_dom
            enc.aff_tail_dev["forbid_static"] = sanitize.upload_frozen(
                np.minimum(base, 127).astype(np.int8),
                sharding=_sh("forbid_static"))
        enc.aff_patch_dirty = False

    def _wave_encoding(self, pods: Sequence[Pod], infos):
        """(encoding, pod_class[n]) for a pipeline chunk, via the
        (vocab_gen, aff_seq, workload-identity)-keyed reuse cache.
        EVERY chunk shape is wave-eligible now (ISSUE 18): affinity
        classes the topology counters express run per-wave on device
        (ISSUE 3), label-pure host-check classes carry an exact
        precomputed host_fit column, Policy classes carry frozen
        policy_fit/policy_score columns with a fence-side exact
        re-check, and everything else (live-NodeInfo ports, preference
        overflow, Policy order-dependence, affinity slot overflow)
        rides inactive and places at the harvest's exact oracle tail."""
        import dataclasses as _dc

        from kubernetes_tpu.ops.affinity import (
            AffinityData,
            _has_affinity,
            collect_pod_pairs,
            intern_topology_pairs,
        )
        from kubernetes_tpu.ops.predicates import pod_arrays_padded
        from kubernetes_tpu.state.classes import pod_class_key
        from kubernetes_tpu.utils.trace import COUNTERS

        snap = self.snapshot
        enc = self._wave_enc
        policy_active = self.policy_algos is not None \
            and self.policy_algos.active
        w_ip = sum(w for nm, w in self.priorities
                   if nm == "InterPodAffinityPriority")
        w_sp = sum(w for nm, w in self.priorities
                   if nm == "SelectorSpreadPriority")
        # workloads are placement-relevant only through Policy predicates
        # or a live SelectorSpread weight; otherwise their churn can never
        # change a placement and the encoding ignores them entirely
        workloads_now = tuple(self.workloads_provider()) \
            if (policy_active or w_sp) else ()
        fresh = enc is not None and enc.vocab_gen == snap.vocab_gen
        if fresh and enc.policy_on != policy_active:
            fresh = False
        if fresh and (policy_active or w_sp):
            wk = enc.wkey
            if len(wk) != len(workloads_now) or not all(
                    a is b for a, b in zip(wk, workloads_now)):
                # workload set moved (the scheduler replaces workload
                # objects on watch events, so identity detects every
                # change): the frozen policy/spread arrays and the
                # needs_host classification are stale — full rebuild
                fresh = False
        if fresh and enc.has_static_cols \
                and enc.labels_gen != snap.labels_gen:
            # host/policy static columns bake label content; checked
            # BEFORE the affinity label-patch path so a patched encoding
            # can never keep a stale column
            fresh = False
        if fresh and enc.adata is not None \
                and enc.labels_gen != snap.labels_gen:
            # label content moved: patch the touched rows (Protean,
            # ISSUE 8) or fall through to the rebuild
            fresh = self._try_patch_labels(enc, infos)
        if fresh and enc.aff_seq != self.cache.aff_seq:
            # foreign occupancy churn: patch the touched rows or rebuild
            fresh = self._try_patch_foreign(enc)
        if fresh:
            key_index = enc.key_index
            pc = np.empty(len(pods), dtype=np.int32)
            hit = True
            for i, p in enumerate(pods):
                c = key_index.get(pod_class_key(p), -1)
                if c < 0:
                    hit = False
                    break
                pc[i] = c
            if hit:
                COUNTERS.inc("engine.wave_encode_reuse")
                return enc, pc
        # rebuild over the union with the cached reps so chunks alternating
        # between two class sets don't thrash the cache. Seeding FIRST also
        # keeps prior class indices stable, so a mid-drain rebuild leaves
        # any in-flight handle's class rows meaningful.
        seed: List[Pod] = []
        if enc is not None and enc.vocab_gen == snap.vocab_gen:
            seed = enc.reps
        aff_seq0 = self.cache.aff_seq
        chunk_aff = any(_has_affinity(p) for p in seed) \
            or any(_has_affinity(p) for p in pods)
        cluster_aff = any(bool(i.pods_with_affinity) for i in infos.values())
        # spread-only chunks build AffinityData too (ISSUE 18): the
        # workload-membership arrays drive the frozen SelectorSpread
        # score, so workload-bearing streams no longer flush the pipeline
        build_adata = chunk_aff or cluster_aff \
            or (bool(w_sp) and bool(workloads_now))
        all_pairs: list = []
        aff_pairs: list = []
        if build_adata or policy_active:
            all_pairs, aff_pairs = collect_pod_pairs(infos)
        if build_adata:
            # topology keys referenced by ANY affinity term must be interned
            # BEFORE the label matrix finalizes (the r2 symmetry bug), same
            # ordering contract as schedule()
            intern_topology_pairs(snap, seed + list(pods), aff_pairs)
        batch = ClassBatch(seed + list(pods), snap)
        n_cls = batch.num_classes
        rb = batch.reps_batch
        c_pad = bucket(n_cls + 1)
        # host-check absorption (ISSUE 18): label-pure host classes get an
        # exact precomputed fit column and ride the wave; the rest (live-
        # NodeInfo ports, score-affecting preference overflow, shapes the
        # column cannot derive, Policy order-dependence, affinity slot
        # overflow below) ride as inactive rows and place at the harvest's
        # exact oracle tail. No chunk SHAPE flushes the pipeline anymore.
        host_exact = np.zeros(c_pad, dtype=bool)
        host_static = np.zeros(c_pad, dtype=bool)
        nhc = rb.needs_host_check[:n_cls]
        host_exact[:n_cls] = nhc & rb.host_check_dynamic[:n_cls]
        host_fit_rows: Dict[int, np.ndarray] = {}
        for c in np.nonzero(nhc & ~rb.host_check_dynamic[:n_cls])[0]:
            row = rb.host_static_fit(int(c), snap)
            if row is None:
                host_exact[c] = True  # not derivable from labels alone
            else:
                host_static[c] = True
                host_fit_rows[int(c)] = row
        if policy_active:
            # service-coupled classes are order-dependent in-batch (the
            # reference's pod lister is the scheduler cache) -> exact tail
            host_exact[:n_cls] |= np.asarray(
                self.policy_algos.needs_host(batch.reps, workloads_now),
                dtype=bool)[:n_cls]
        adata = None
        fits_on = prio_on = spread_on = False
        has_aff_pod = None
        aff_wave_dev = aff_tail_dev = None
        key_node = static_forbid_hit = tail_cols = None
        if build_adata:
            COUNTERS.inc("engine.wave_aff_build")
            # the churn-robustness observable (ISSUE 8): every wholesale
            # AffinityData build the patch paths could NOT absorb. Under
            # the churn profile this must stay O(vocab growth + class-set
            # growth), not O(foreign binds) — the bench reports it.
            COUNTERS.inc("engine.aff_full_rebuilds")
            adata = AffinityData(batch.reps, snap, all_pairs, aff_pairs,
                                 workloads_now,
                                 self.hard_pod_affinity_weight,
                                 c_pad=c_pad)
            # slot overflow no longer flushes (ISSUE 18): overflow classes
            # join the exact oracle tail — the classic round marked them
            # host-check; same semantics, minus the pipeline drain
            host_exact[:n_cls] |= adata.overflow[:n_cls]
            fits_on = adata.fits_needed
            prio_on = bool(w_ip) and adata.prio_needed
            spread_on = bool(w_sp) and adata.spread_needed
            has_aff_pod = np.zeros(c_pad, dtype=bool)
            for c, rep in enumerate(batch.reps):
                has_aff_pod[c] = _has_affinity(rep)
            if fits_on:
                key_node, static_forbid_hit = _aff_node_views(adata, snap)

                def _sh(k):
                    return None if self._rmesh is None \
                        else self._rmesh.aff_sharding(k)
                # static per encoding — frozen-alias seam, like the tail;
                # node-axis members shard over the resident mesh
                aff_wave_dev = {
                    "m_anti": sanitize.upload_frozen(adata.m_anti,
                                                     sharding=_sh("m_anti")),
                    "key_node": sanitize.upload_frozen(
                        key_node, sharding=_sh("key_node")),
                    "static_forbid": sanitize.upload_frozen(
                        static_forbid_hit, sharding=_sh("static_forbid")),
                    "wave_gate": sanitize.upload_frozen(
                        adata.wave_gate, sharding=_sh("wave_gate")),
                }
            if fits_on or prio_on or spread_on:
                tail_cols = _aff_tail_cols(adata, prio_on)
                aff_tail_dev = _aff_tail_arrays(adata, snap, tail_cols,
                                                rmesh=self._rmesh)
        COUNTERS.inc("engine.wave_encode_build")
        cls_arr = pod_arrays_padded(rb, c_pad)
        if host_fit_rows:
            # the host-check static column: exact label-pure fit rows for
            # host_static classes, folded into the fused [C, N] eval via
            # predicates.static_fits (padding rows True — the validity
            # mask already excludes them)
            hf = np.ones((c_pad, snap.valid.shape[0]), dtype=bool)
            for c, row in host_fit_rows.items():
                hf[c] = row
            cls_arr["host_fit"] = sanitize.upload_frozen(hf)
        policy_cols = False
        if policy_active:
            pfit, pscore = self.policy_algos.static_class_arrays(
                batch.reps, snap, workloads_now, all_pairs, c_pad,
                skip=host_exact[:n_cls])
            if pfit is not None:
                cls_arr["policy_fit"] = jnp.asarray(pfit)
                policy_cols = True
            if pscore is not None:
                cls_arr["policy_score"] = jnp.asarray(pscore)
                policy_cols = True
        key_index = {pod_class_key(rep): c
                     for c, rep in enumerate(batch.reps)}
        special = ((rb.ports[:n_cls, 0] >= 0)
                   | (rb.vol_hard[:n_cls].sum(axis=1)
                      + rb.vol_ro[:n_cls].sum(axis=1)
                      + rb.pd_req[:n_cls].sum(axis=1) > 0))
        derived = [(rep.resource_request(), *rep.nonzero_request(),
                    rep.used_ports()) for rep in batch.reps]
        ports_max = int(rb.ports.max()) if np.any(rb.ports >= 0) else -1
        # clone the reps for reuse: the originals get node_name assigned at
        # assume time, which would corrupt their class key as seeds
        reps = [_dc.replace(p) for p in batch.reps]
        self._wave_enc = enc2 = _WaveEncoding(
            snap.vocab_gen, key_index, reps, cls_arr, n_cls, c_pad,
            rb.req[:n_cls].astype(np.int64), special, derived, ports_max,
            adata=adata, fits_on=fits_on, prio_on=prio_on,
            has_aff_pod=has_aff_pod, aff_seq=aff_seq0,
            aff_wave_dev=aff_wave_dev, aff_tail_dev=aff_tail_dev,
            key_node=key_node, static_forbid_hit=static_forbid_hit,
            tail_cols=tail_cols, n_pad=snap.valid.shape[0],
            labels_gen=snap.labels_gen,
            host_exact=host_exact, host_static=host_static,
            policy_on=policy_active, spread_on=spread_on,
            wkey=workloads_now,
            has_static_cols=bool(host_fit_rows) or policy_cols)
        if adata is not None:
            from kubernetes_tpu.ops.oracle_ext import _own_terms
            for c, rep in enumerate(reps):
                for a, term in enumerate(_own_terms(rep, anti=True)):
                    enc2.anti_terms.append((c, a, term, rep))
                for s, term in enumerate(_own_terms(rep, anti=False)):
                    enc2.aff_terms.append((c, s, term, rep))
        return enc2, batch.pod_class[len(seed):].copy()

    def dispatch_waves(self, pods: Sequence[Pod], pop_ts: float = 0.0,
                       gangs=None) -> Optional[WaveHandle]:
        """Encode a chunk and launch its wave placement WITHOUT blocking —
        the device computes while the caller does the previous wave's
        bookkeeping (JAX async dispatch). The chunk is evaluated against the
        snapshot as of NOW, which is blind to the still-unharvested wave's
        commits; harvest_waves' fence re-validates (capacity AND topology
        occupancy). Required (anti-)affinity chunks are wave-eligible
        (ISSUE 3): counter-expressible classes re-evaluate their masks per
        wave on device, inexpressible ones ride as inactive rows and the
        harvest finishes them via the seeded strict tail. Host-check and
        Policy chunks ride too (ISSUE 18): label-pure host classes via
        the precomputed host_fit column, the rest as inactive rows placed
        at the harvest's exact oracle tail. Returns None only for the one
        disclosed corner — a gang whose quorum is unreachable from its
        wave-eligible members (it would roll back forever); every other
        chunk shape dispatches, and the only remaining pipeline flush
        triggers are Node SPEC events (_node_event_needs_flush, r11).

        `gangs` = [(name, member indices into `pods`, quorum)]: quorum-
        ready gangs riding this wave as ordinary batch rows (ISSUE 5).
        Dispatch treats them like any other pod; atomicity lives entirely
        in harvest_waves' gang fence, so the pipeline never drains for a
        gang chunk."""
        import time as _time

        from kubernetes_tpu.utils.trace import COUNTERS, timed_span

        if not pods:
            return None
        # flight recorder (ISSUE 13): one host-side timestamp when armed,
        # nothing at all when off — the event itself is emitted after the
        # async launch, carrying only host scalars already in hand
        _rec_t0 = _time.monotonic() if RECORDER.enabled else 0.0
        with timed_span("pipeline.dispatch"):
            infos = self._refresh()
            out = self._wave_encoding(pods, infos)
            if out is None:
                return None
            enc, pc = out
            hx = enc.host_exact[pc]
            host_idx = np.nonzero(hx)[0].astype(np.int64)
            if gangs and host_idx.size:
                # the one remaining chunk-shape flush corner (disclosed):
                # a gang whose quorum is unreachable from its wave-
                # eligible members would roll back on every re-dispatch —
                # only IT flushes to the classic round
                hset = set(host_idx.tolist())
                for _gname, idxs, quorum in gangs:
                    if sum(1 for i in idxs if i not in hset) < quorum:
                        COUNTERS.inc("engine.wave_flush_gang_host")
                        return None
            if enc.adata is not None:
                # patched topology views re-upload once per dispatch,
                # however many churn events were absorbed since the last
                self._flush_aff_patches(enc)
            n = len(pods)
            p_pad = bucket(max(n, self.wave_pad_floor or 1))
            pc_pad = np.full(p_pad, enc.num_classes, dtype=np.int32)
            pc_pad[:n] = pc
            if host_idx.size:
                # host_exact rows ride as the PADDING class: impossible on
                # device (fit nothing, no RR ticks, retire on the first
                # wave) — the harvest's exact oracle tail places them
                # against live NodeInfo truth after the fence
                pc_pad[host_idx] = enc.num_classes
                COUNTERS.inc("engine.wave_host_rows", int(host_idx.size))
            max_words = self.snapshot.port_words_used()
            if enc.ports_max >= 0:
                max_words = max(max_words, enc.ports_max // 32 + 1)
            port_words = bucket(max(max_words, 1), lo=1)
            nodes = dict(self._nodes_on_device(port_words=port_words))
            state = NodeState(nodes["requested"], nodes["nonzero"],
                              nodes["pod_count"], nodes["port_bitmap"],
                              nodes["vol_present"], nodes["vol_rw"],
                              nodes["pd_present"], nodes["pd_counts"])
            counter = self._rr_chain if self._rr_chain is not None \
                else jnp.uint32(self.rr.counter)
            extra = None
            if enc.prio_on or enc.spread_on:
                # preferred-affinity / SelectorSpread scores, frozen
                # against the encoding's static topology view (the
                # wave-mode approximation, same as the classic _run_wave's
                # batch-frozen extra_score) — over the tail's projected
                # domain axis, which covers every priority-side keymask
                # column by construction. Spread rides frozen too (ISSUE
                # 18): within-batch drift of workload counts is the same
                # documented score-only approximation.
                w_ip = sum(w for nm, w in self.priorities
                           if nm == "InterPodAffinityPriority")
                w_sp = sum(w for nm, w in self.priorities
                           if nm == "SelectorSpreadPriority")
                extra = waves.frozen_affinity_scores(
                    enc.cls_arr, nodes, state, enc.aff_tail_dev,
                    (w_ip if enc.prio_on else 0,
                     w_sp if enc.spread_on else 0))
            strict_idx = np.empty(0, dtype=np.int64)
            committed_out = None
            if enc.fits_on:
                ser = enc.wave_strict[pc] & ~hx
                strict_idx = np.nonzero(ser)[0]
                act = np.zeros(p_pad, dtype=bool)
                act[:n] = ~(ser | hx)
                # committed_nodes must upload as a COPY: the harvest FOLD
                # mutates it in place (np.add.at) while this wave may
                # still be executing against it asynchronously (the same
                # race class _nodes_on_device documents). GL001's
                # copy-required contract + the class-scoped alias check
                # both reject a jnp.asarray regression here.
                committed_dev = sanitize.upload_copied(  # graftlint: copy-required
                    enc.committed_nodes,
                    sharding=None if self._rmesh is None
                    else self._rmesh.committed_sharding())
                packed, state_out, committed_out = waves.waves_loop(
                    enc.cls_arr, nodes, state, jnp.asarray(pc_pad), counter,
                    self._kernel_priorities(), 64, extra_score=extra,
                    aff=enc.aff_wave_dev,
                    committed0=committed_dev,
                    active0=jnp.asarray(act),
                    pre=self._tail_wave_pre(enc, nodes),
                    spmd_mesh=self.mesh)
                if strict_idx.size:
                    COUNTERS.inc("engine.affinity_strict_tail",
                                 int(strict_idx.size))
            else:
                packed, state_out = waves.waves_loop(
                    enc.cls_arr, nodes, state, jnp.asarray(pc_pad), counter,
                    self._kernel_priorities(), 64, extra_score=extra,
                    pre=self._tail_wave_pre(enc, nodes),
                    spmd_mesh=self.mesh)
            counter_out = packed[3 * p_pad].astype(jnp.uint32)
            self._rr_chain = counter_out
            blind: set = set()
            self._blind_listeners.append(blind)
            COUNTERS.inc("engine.wave_dispatch")
            # admitted-pod count per dispatch: wave_dispatch_pods /
            # wave_dispatch is the realized micro-wave size, the stream
            # loop's admission observable (ISSUE 7)
            COUNTERS.inc("engine.wave_dispatch_pods", n)
            if gangs:
                COUNTERS.inc("engine.gang_wave_dispatch", len(gangs))
            wave_id = -1
            if RECORDER.enabled or TRACER.enabled:
                # one wave-id sequence for BOTH observers, so a pod's
                # WAVE_DISPATCHED joins the ring's dispatch/harvest
                # events on the exported timeline
                wave_id = RECORDER.next_wave()
            if _rec_t0 and RECORDER.enabled:
                RECORDER.record(flightrec.DISPATCH, wave=wave_id,
                                t0=_rec_t0,
                                dur=_time.monotonic() - _rec_t0,
                                a=n, b=len(gangs) if gangs else 0)
            if TRACER.enabled:
                TRACER.batch_event(podtrace.WAVE_DISPATCHED,
                                   [p.key() for p in pods], a=wave_id)
            return WaveHandle(list(pods), pc, enc, packed, state_out,
                              counter_out, nodes, blind, pop_ts,
                              _time.monotonic(), self.wave_pad_floor,
                              committed_out=committed_out,
                              strict_idx=strict_idx, gangs=gangs,
                              wave_id=wave_id, host_idx=host_idx)

    def harvest_waves(self, handle: WaveHandle) -> WaveHarvest:
        """Block on one wave's device→host sync, fence its placements
        against post-blind-window occupancy, and assume the survivors
        (columnar). The fence is exact for resources and pod count (the
        snapshot is re-refreshed here, so it reflects every commit and
        watch event the device did not see); port/volume classes requeue
        conservatively when their node was touched in the blind window.
        Conflicting pods are returned for requeue WITHOUT backoff — they
        lost a capacity race, they are not unschedulable."""
        import time as _time

        from kubernetes_tpu.utils.trace import COUNTERS, timed_span

        _rec_t0 = _time.monotonic() if RECORDER.enabled else 0.0
        # the fence below compares against snapshot arrays — fold in any
        # commits/events since the last dispatch (hinted: near-free when
        # nothing moved)
        self._refresh()
        enc = handle.enc
        snap = self.snapshot
        if enc is self._wave_enc and enc.adata is not None \
                and enc.aff_seq != self.cache.aff_seq:
            # foreign churn landed while this wave was in flight: patch
            # the overlays NOW so the topology fence below compares
            # against it exactly; a failed patch leaves the mismatch and
            # _fence_affinity requeues every relevant row conservatively
            self._try_patch_foreign(enc)
        n = len(handle.pods)
        p_pad = bucket(max(n, handle.pad_floor or 1))
        t0 = _time.perf_counter()
        with timed_span("pipeline.device_block"):
            # THE pipeline's blessed block: harvest exists to absorb this
            # wave's device wait while the NEXT wave already runs
            packed_h = np.asarray(handle.packed)  # graftlint: sync-ok
        t_block = _time.perf_counter() - t0
        # block-END instant on the ring's timebase: the device-eval lane's
        # right edge (the exporter reconstructs the window as
        # [dispatch end → this instant])
        _rec_block_end = _time.monotonic() if _rec_t0 else 0.0
        # the per-wave device->host payload: [3P+2] int32 regardless of N —
        # the scale_sweep's proof that harvesting never fetches node-axis
        # tensors (the winner reduce already collapsed them on device)
        COUNTERS.inc("engine.host_fetch_bytes", int(packed_h.nbytes))
        if self.mesh is not None:
            # structural traffic accounting for the two-stage winner
            # reduce (ISSUE 12): each INNER wave iteration's cross-shard
            # stage moves the [D, C] tie-count table + O(P) candidate
            # combines — scale by waves_used (packed[3P+1]), not per
            # dispatch, so the counter states actual cross-device traffic.
            # The bench reads this against the O(N) rows a single-device
            # gather would have moved.
            COUNTERS.inc("engine.reduce_candidate_rows",
                         int(self.mesh.devices.size) * handle.enc.c_pad
                         * int(packed_h[3 * p_pad + 1]))
        sel = packed_h[:n].copy()
        fc = packed_h[p_pad:p_pad + n].copy()
        act = packed_h[2 * p_pad:2 * p_pad + n].astype(bool)
        counter_h = int(np.uint32(packed_h[3 * p_pad]))
        tail_idx = np.nonzero(act)[0]
        if handle.host_idx.size:
            # host_exact rows retire inactive off the padding class on the
            # first wave; they never ride the device tail — the exact
            # oracle tail below places them after the fence
            tail_idx = np.setdiff1d(tail_idx, handle.host_idx)
        straggler_idx = np.empty(0, dtype=np.int64)
        if enc.adata is not None and tail_idx.size:
            # max-waves stragglers may NOT ride the seeded tail in an
            # affinity chunk: the tail's domain projection carries only
            # the wave_strict classes' columns (_aff_tail_cols), so a
            # straggler's own anti terms — and the symmetry sources
            # targeting its labels — would be invisible to the scan.
            # Requeue without backoff instead; the next dispatch re-waves
            # them against the updated occupancy (each re-dispatch of the
            # bottleneck commits at least one pod, so this terminates).
            straggler_idx = tail_idx
            tail_idx = np.empty(0, dtype=np.int64)
            COUNTERS.inc("engine.affinity_straggler_requeues",
                         int(straggler_idx.size))
        if handle.strict_idx.size:
            # wave_strict classes (own required affinity, multi-node-domain
            # anti shapes, fail_all) never entered the waves: finish them —
            # together with any max_waves stragglers (affinity-free
            # encodings only, see above) — via ONE seeded strict scan, in
            # FIFO order, against the wave's final device state AND its
            # final topology occupancy, exactly what the classic
            # _run_wave's strict branch would have seen.
            tail_idx = np.unique(np.concatenate([tail_idx,
                                                 handle.strict_idx]))
        if tail_idx.size:
            # the straggler/tail RR draws land after the next wave's
            # (already-chained) counter — deterministic in both pipelined
            # and sequential modes, since dispatch k+1 always precedes
            # harvest k in either.
            n_tail = len(tail_idx)
            pcs = np.full(bucket(n_tail), enc.num_classes, dtype=np.int32)
            pcs[:n_tail] = handle.pc[tail_idx]
            aff_arrays = None
            aff_init = None
            aff_mode = (False, False, False)
            tail_prios = self._kernel_priorities()
            if enc.adata is not None and (enc.fits_on or enc.prio_on):
                aff_arrays = enc.aff_tail_dev
                committed0 = handle.committed_out.astype(jnp.int32) \
                    if handle.committed_out is not None else jnp.zeros(
                        (enc.c_pad, int(handle.nodes["alloc"].shape[0])),
                        dtype=jnp.int32)
                # project the wave's per-node occupancy onto the tail's
                # domain columns: commdom[c, j] = committed @ labels[:, j]
                # (device GEMM over the SMALL projected axis)
                commdom0 = jnp.matmul(
                    committed0, aff_arrays["labels_aff"].astype(jnp.int32),
                    preferred_element_type=jnp.int32)
                aff_init = (commdom0, committed0, committed0.sum(axis=1))
                aff_mode = (enc.fits_on, enc.prio_on, False)
                if enc.prio_on:
                    tail_prios = tuple(
                        (nm, w) for nm, w in self.priorities
                        if nm != "SelectorSpreadPriority")
            COUNTERS.inc("engine.wave_tail_dispatch")
            if self.tail_rounds and n_tail >= self.tail_rounds_min:
                # conflict-round tail (ISSUE 5): the whole tail as ONE
                # while_loop dispatch whose sequential depth is the round
                # count — required semantics exact at every commit, tie-
                # breaks wave-style (waves.tail_rounds_loop docstring)
                COUNTERS.inc("engine.tail_round_dispatch")
                with timed_span("pipeline.tail"):
                    packed_t, _st = waves.tail_rounds_loop(
                        enc.cls_arr, handle.nodes, handle.state_out,
                        jnp.asarray(pcs), jnp.uint32(counter_h), tail_prios,
                        aff=aff_arrays, aff_mode=aff_mode, aff_init=aff_init,
                        pre=self._tail_wave_pre(enc, handle.nodes))
                    # seeded tail fetch: the fence below needs these rows
                    # on host NOW — the tail is the last device work in
                    # this harvest
                    packed_th = np.asarray(packed_t)  # graftlint: sync-ok
                p_t = len(pcs)
                sel[tail_idx] = packed_th[:n_tail]
                fc[tail_idx] = packed_th[p_t:p_t + n_tail]
                counter_h = int(np.uint32(packed_th[2 * p_t]))
                COUNTERS.inc("engine.tail_rounds",
                             int(packed_th[2 * p_t + 1]))
            else:
                # per-pod scan (small tails, and the GRAFT_TAIL_ROUNDS=0
                # oracle mode): classic sequential semantics, the
                # constraint reference the round fuzz compares against
                with timed_span("pipeline.tail"):
                    sel_s, fc_s, _st, rr_d = gather_place_batch(
                        enc.cls_arr, jnp.asarray(pcs), handle.nodes,
                        handle.state_out, jnp.uint32(counter_h), tail_prios,
                        aff=aff_arrays, aff_mode=aff_mode, aff_init=aff_init)
                    # same fetch contract as the rounds branch above
                    sel[tail_idx] = np.asarray(sel_s)[:n_tail]  # graftlint: sync-ok
                    fc[tail_idx] = np.asarray(fc_s)[:n_tail]  # graftlint: sync-ok
                    counter_h = int(rr_d)  # graftlint: sync-ok (scalar)
        if self._rr_chain is handle.counter_out:
            self._rr_chain = None
        self.rr.counter = counter_h
        self._blind_listeners.remove(handle.blind)

        pods = handle.pods
        strag = set(straggler_idx.tolist())
        placed_idx = np.nonzero(sel >= 0)[0]
        acc_idx = np.empty(0, dtype=np.int64)
        acc_node = np.empty(0, dtype=np.int64)
        acc_cls = np.empty(0, dtype=np.int32)
        conflict_idx: List[int] = []
        conflict_codes: List[int] = []
        liveness_idx: List[int] = []
        if placed_idx.size:
            with timed_span("pipeline.fence"):
                (acc_idx, acc_node, acc_cls, conflict_idx, liveness_idx,
                 conflict_codes) = self._fence(handle, sel, placed_idx)
        # the GANG FENCE (ISSUE 5): all-or-nothing atomicity for gangs that
        # rode this wave as ordinary batches. A gang COMMITS when >= quorum
        # members survived placement AND the capacity/topology fence; below
        # quorum, every member — placed, fenced, or unschedulable — is
        # dropped from the accepted set BEFORE anything is assumed (atomic
        # rollback with zero partial residue, by construction: nothing of a
        # losing gang ever reaches the cache) and requeues WITH backoff,
        # exactly the classic round's below-quorum semantics.
        gang_committed: List[str] = []
        gang_requeued: List[Tuple[Pod, str]] = []
        drop = None
        if handle.gangs:
            acc_mask = np.zeros(n, dtype=bool)
            acc_mask[acc_idx] = True
            drop = np.zeros(n, dtype=bool)
            for gname, idxs, quorum in handle.gangs:
                ia = np.asarray(idxs, dtype=np.int64)
                ok_n = int(acc_mask[ia].sum())
                if ok_n >= quorum:
                    gang_committed.append(gname)
                    continue
                COUNTERS.inc("engine.gang_fence_rollbacks")
                COUNTERS.inc("engine.fence_reason_gang", len(ia))
                drop[ia] = True
                reason = (f"gang {gname}: only {ok_n}/{len(ia)} members "
                          f"placeable past the wave fence (quorum {quorum})")
                gang_requeued.extend((pods[int(i)], reason) for i in ia)
            if drop.any():
                keep = ~drop[acc_idx]
                acc_idx = acc_idx[keep]
                acc_node = acc_node[keep]
                acc_cls = acc_cls[keep]
            else:
                drop = None
        host_rows = set(handle.host_idx.tolist())
        unschedulable = [(pods[i], int(fc[i]))
                         for i in np.nonzero(sel < 0)[0].tolist()
                         if i not in strag and i not in host_rows
                         and (drop is None or not drop[i])]
        bound: List[Pod] = []
        # conflicts + their typed reason codes, parallel (ISSUE 15):
        # max-waves stragglers are an affinity-routing verdict
        conflicts: List[Pod] = []
        conflict_reasons: List[int] = []
        for i in straggler_idx.tolist():
            if drop is None or not drop[i]:
                conflicts.append(pods[i])
                conflict_reasons.append(podtrace.REASON_AFFINITY)
        for i, code in zip(conflict_idx, conflict_codes):
            if drop is None or not drop[i]:
                conflicts.append(pods[i])
                conflict_reasons.append(code)
        # liveness rejects (ISSUE 8): the target node died / was cordoned
        # mid-flight — requeue WITH backoff (the caller's contract): the
        # node is not coming back on a capacity-race timescale, and a
        # plain re-add would hot-loop the doomed rows against the same
        # dying topology until the event drains
        liveness = [pods[i] for i in liveness_idx
                    if drop is None or not drop[i]]
        if acc_idx.size:
            names = snap.node_names
            groups = []
            acc_l = acc_idx.tolist()
            node_l = acc_node.tolist()
            cls_l = acc_cls.tolist()
            change = np.nonzero((acc_node[1:] != acc_node[:-1])
                                | (acc_cls[1:] != acc_cls[:-1]))[0] + 1
            bounds = [0] + change.tolist() + [len(acc_l)]
            with timed_span("pipeline.assume"):
                for b0, b1 in zip(bounds[:-1], bounds[1:]):
                    name = names[node_l[b0]]
                    run = [pods[i] for i in acc_l[b0:b1]]
                    for p in run:
                        p.node_name = name
                    groups.append((name, run) + enc.derived[cls_l[b0]])
                infos_touched = self.cache.assume_pods_grouped(groups)
                # fold the assumes into the snapshot WITHOUT a node
                # walk: classes with pure base-resource footprints go
                # through the exact raw-delta path (generation synced
                # so the next refresh skips these nodes); the rest take
                # the normal dirty-note rewrite
                dok = enc.delta_ok[acc_cls]
                dirty_names = {names[i] for i in
                               set(acc_node[~dok].tolist())}
                if dok.any():
                    snap.apply_assume_delta(
                        acc_node[dok], enc.raw_rows[acc_cls[dok]],
                        [(nm, info) for nm, info in
                         infos_touched.items()
                         if nm not in dirty_names],
                        prio_rows=enc.cls_prio[acc_cls[dok]])
                if dirty_names:
                    self._touch(dirty_names)
                blind_names = [nm for nm in infos_touched
                               if nm not in dirty_names]
                for s in self._blind_listeners:
                    s.update(blind_names)
            if enc is self._wave_enc:
                # fold fence-accepted commits into the encoding's
                # cumulative per-node topology occupancy — the host
                # mirror the next dispatch seeds the device loop from —
                # and into its aff_seq expectation (assume_pods_grouped
                # just bumped cache.aff_seq once per assumed pod; the
                # churn sequence covers ALL pods since ISSUE 8). A stale
                # enc skips both: its aff_seq mismatch routes the next
                # dispatch through the patch/rebuild gate, which already
                # sees these assumes in the live NodeInfos.
                if enc.committed_nodes is not None:
                    np.add.at(enc.committed_nodes, (acc_cls, acc_node),
                              1)
                enc.aff_seq += len(acc_l)
            bound = [pods[i] for i in sorted(acc_l)]
        if host_rows:
            # the exact oracle tail (ISSUE 18): host_exact rows place
            # AFTER the wave rows' assume, against live NodeInfo truth —
            # exactly the classic round's slow_idx FIFO loop, so each
            # host pod sees every commit this harvest just made (and each
            # other's). Rolled-back gangs' members are excluded (their
            # gang fence already requeued them WITH backoff — zero
            # partial residue holds).
            h_rows = [i for i in sorted(host_rows)
                      if drop is None or not drop[i]]
            if h_rows:
                from kubernetes_tpu.ops.oracle_ext import SchedulingContext
                COUNTERS.inc("engine.wave_host_tail", len(h_rows))
                with timed_span("pipeline.host_tail"):
                    infos_t = self.cache.node_infos()
                    names_t = snap.node_names
                    ctx = SchedulingContext(
                        infos_t, self.workloads_provider(),
                        hard_pod_affinity_weight=(
                            self.hard_pod_affinity_weight),
                        volume_ctx=self.volume_ctx,
                        policy_algos=self.policy_algos)
                    for i in h_rows:
                        name = oracle.schedule_one(
                            pods[i], names_t, infos_t, self.rr,
                            self.priorities, ctx)
                        if name is not None:
                            self._assume(pods[i], name)
                            infos_t = self.cache.node_infos()
                            ctx.infos = infos_t
                            ctx.invalidate()
                            bound.append(pods[i])
                        else:
                            unschedulable.append((pods[i], 0))
        if _rec_t0 and RECORDER.enabled:
            RECORDER.record(flightrec.HARVEST, wave=handle.wave_id,
                            t0=_rec_block_end - t_block, dur=t_block,
                            a=len(bound),
                            b=len(conflicts) + len(liveness))
            if conflicts or liveness:
                RECORDER.record(flightrec.FENCE_REQUEUE,
                                wave=handle.wave_id,
                                a=len(conflicts), b=len(liveness))
        if TRACER.enabled:
            # per-pod harvest/fence stamps (ISSUE 15): survivors get
            # HARVESTED (the device phase's right edge on their
            # timeline), losers a FENCE_REQUEUED carrying the typed
            # reason — host ints only, the sync above already happened
            t_h = _time.monotonic()
            if bound:
                TRACER.batch_event(podtrace.HARVESTED,
                                   [p.key() for p in bound],
                                   a=handle.wave_id, t0=t_h)
            for p, code in zip(conflicts, conflict_reasons):
                TRACER.event(p.key(), podtrace.FENCE_REQUEUED, a=code,
                             b=handle.wave_id, t0=t_h)
            for p in liveness:
                TRACER.event(p.key(), podtrace.FENCE_REQUEUED,
                             a=podtrace.REASON_LIVENESS,
                             b=handle.wave_id, t0=t_h)
            for p, _why in gang_requeued:
                TRACER.event(p.key(), podtrace.FENCE_REQUEUED,
                             a=podtrace.REASON_GANG,
                             b=handle.wave_id, t0=t_h)
        return WaveHarvest(bound, conflicts, unschedulable, t_block,
                           gang_committed=gang_committed,
                           gang_requeued=gang_requeued,
                           liveness_requeued=liveness,
                           conflict_reasons=conflict_reasons)

    def _fence(self, handle: WaveHandle, sel: np.ndarray,
               placed_idx: np.ndarray):
        """Vectorized re-validation of a blind wave's placements against
        current occupancy: exact prefix-capacity + pod-count math, plus the
        TOPOLOGY mirror (ISSUE 3) — required (anti-)affinity placements
        made against the pre-k occupancy re-check against the engine's
        post-k commdom and requeue conservatively instead of colliding.
        Returns (accepted original indices grouped by (node, class) with
        FIFO order inside each node, their node indices, their class
        indices, conflict original indices in FIFO order, liveness
        original indices, typed podtrace.REASON_* codes parallel to the
        conflict list)."""
        from kubernetes_tpu.utils.trace import COUNTERS

        snap = self.snapshot
        enc = handle.enc
        node_of = sel[placed_idx]
        order = np.argsort(node_of, kind="stable")
        gidx = placed_idx[order]
        gnode = node_of[order]
        m = len(gidx)
        seg_start = np.empty(m, dtype=bool)
        seg_start[0] = True
        seg_start[1:] = gnode[1:] != gnode[:-1]
        starts = np.nonzero(seg_start)[0]
        grp = np.cumsum(seg_start) - 1
        rank = np.arange(m) - starts[grp]
        cls_rows = handle.pc[gidx]
        req = enc.req_rows[cls_rows]                      # [m, R] int64
        csum = np.cumsum(req, axis=0)
        prefix = csum - (csum[starts] - req[starts])[grp]  # incl., per node
        # slice snapshot columns to the ENCODING's resource width: vocab
        # growth between dispatch and harvest appends columns these classes
        # cannot request (their rows predate the column), so ignoring the
        # suffix is exact — and indexing with the live width would tear
        ncols = enc.req_rows.shape[1]
        alloc = snap.alloc[gnode][:, :ncols].astype(np.int64)
        used = snap.requested[gnode][:, :ncols].astype(np.int64)
        avail = alloc - used
        plain = [c for c in range(ncols) if c not in (R_SCRATCH, R_OVERLAY)]
        ok = (prefix[:, plain] <= avail[:, plain]).all(axis=1)
        # storage fallback (predicates.go:590-604): overlay-less nodes charge
        # overlay requests against scratch
        no_ov = alloc[:, R_OVERLAY] == 0
        scr_pref = prefix[:, R_SCRATCH] + np.where(no_ov,
                                                   prefix[:, R_OVERLAY], 0)
        scr_avail = avail[:, R_SCRATCH] - np.where(no_ov,
                                                   used[:, R_OVERLAY], 0)
        ok &= scr_pref <= scr_avail
        ok &= no_ov | (prefix[:, R_OVERLAY] <= avail[:, R_OVERLAY])
        ok &= (snap.pod_count[gnode].astype(np.int64) + rank + 1
               <= snap.allowed_pods[gnode])
        spc = enc.special[cls_rows]
        if spc.any() and handle.blind:
            # ports/volume predicates are per-object host state — exact
            # vector re-check is not worth it for these rare classes; a
            # touched node in the blind window requeues them conservatively
            bl = np.zeros(snap.valid.shape[0], dtype=bool)
            idx_map = snap.node_index
            for nm in handle.blind:
                i = idx_map.get(nm, -1)
                if i >= 0:
                    bl[i] = True
            ok &= ~(spc & bl[gnode])
        # typed requeue attribution (ISSUE 15): one reason code per
        # rejected row, first-cause ordering (capacity checks ran first,
        # affinity only re-colors rows capacity passed). The ports/
        # volume conservative requeue above is a capacity-class verdict.
        reason = np.full(m, -1, dtype=np.int8)
        reason[~ok] = podtrace.REASON_CAPACITY
        if enc.fits_on and enc.adata is not None:
            aff_out = self._fence_affinity(enc, cls_rows, gnode)
            if aff_out is not None:
                aff_bad, aff_stale = aff_out
                n_rej = int((aff_bad & ok).sum())
                if n_rej:
                    COUNTERS.inc("engine.affinity_fence_requeues", n_rej)
                reason[aff_bad & (reason < 0)] = \
                    podtrace.REASON_STALE if aff_stale \
                    else podtrace.REASON_AFFINITY
                ok &= ~aff_bad
        # host-check re-validation (ISSUE 18): the host_fit column baked
        # label CONTENT at build; a relabel landing while this wave was
        # in flight makes the column stale — conservative requeue of
        # every host_static row (relabels are rare; the re-dispatch
        # rebuilds the encoding against fresh truth, the has_static_cols
        # invalidation above guarantees it)
        hs_bad = enc.host_static[cls_rows]
        if hs_bad.any() and snap.labels_gen != enc.labels_gen:
            n_h = int((hs_bad & ok).sum())
            if n_h:
                COUNTERS.inc("engine.hostcheck_fence_requeues", n_h)
            reason[hs_bad & (reason < 0)] = podtrace.REASON_HOSTCHECK
            ok &= ~hs_bad
        if enc.policy_on and self.policy_algos is not None \
                and self.policy_algos.active:
            # Policy re-validation (ISSUE 18): the frozen policy_fit
            # column was exact against the build-time workload set and
            # pod locations; re-check the EXACT oracle predicate against
            # live truth for every surviving row — ServiceAffinity moves
            # with every commit, and this fence is what lets Policy
            # chunks ride blind without ghost-binding on stale state
            cand = np.nonzero(ok)[0]
            if cand.size:
                from kubernetes_tpu.ops.oracle_ext import SchedulingContext
                infos_f = self.cache.node_infos()
                ctx = SchedulingContext(
                    infos_f, self.workloads_provider(),
                    hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                    volume_ctx=self.volume_ctx,
                    policy_algos=self.policy_algos)
                names_f = snap.node_names
                p_bad = np.zeros(m, dtype=bool)
                for r in cand.tolist():
                    info = infos_f.get(names_f[int(gnode[r])])
                    node = info.node if info is not None else None
                    if node is None or not self.policy_algos.oracle_fit(
                            handle.pods[int(gidx[r])], node, ctx):
                        p_bad[r] = True
                if p_bad.any():
                    COUNTERS.inc("engine.policy_fence_requeues",
                                 int(p_bad.sum()))
                    reason[p_bad & (reason < 0)] = podtrace.REASON_POLICY
                    ok &= ~p_bad
        # liveness re-validation (ISSUE 8): a row targeting a node the
        # owner declared dying (watch event seen, not yet applied — the
        # doomed set) or one the refreshed snapshot already rules out
        # (deleted membership, cordon/NotReady since dispatch) must not
        # bind into a ghost. These rows requeue WITH backoff, separately
        # from capacity conflicts.
        live_bad = ~(snap.schedulable[gnode] & snap.valid[gnode])
        if self._doomed_nodes:
            idx_map = snap.node_index
            dm = [idx_map[nm] for nm in self._doomed_nodes if nm in idx_map]
            if dm:
                live_bad |= np.isin(gnode, np.asarray(dm))
        if live_bad.any():
            COUNTERS.inc("engine.liveness_fence_requeues",
                         int(live_bad.sum()))
            COUNTERS.inc("engine.fence_reason_liveness",
                         int(live_bad.sum()))
            ok &= ~live_bad
        conflict_mask = ~ok & ~live_bad
        for code in (podtrace.REASON_CAPACITY, podtrace.REASON_AFFINITY,
                     podtrace.REASON_STALE, podtrace.REASON_HOSTCHECK,
                     podtrace.REASON_POLICY):
            n_r = int(((reason == code) & conflict_mask).sum())
            if n_r:
                COUNTERS.inc("engine.fence_reason_"
                             + podtrace.REASON_NAMES[code], n_r)
        conf_pairs = sorted(zip(gidx[conflict_mask].tolist(),
                                reason[conflict_mask].tolist()))
        return (gidx[ok], gnode[ok], cls_rows[ok],
                [i for i, _r in conf_pairs],
                sorted(gidx[live_bad].tolist()),
                [int(r) for _i, r in conf_pairs])

    def _fence_affinity(self, enc: "_WaveEncoding", cls_rows: np.ndarray,
                        gnode: np.ndarray) -> Optional[np.ndarray]:
        """Topology half of the fence: re-evaluate required (anti-)affinity
        for the wave's placements against the engine's CURRENT cumulative
        occupancy (every prior harvest folded). Exactly mirrors the device
        mask (waves._wave_aff_mask) plus the allow side for strict-tail
        classes; in-harvest interactions need no re-check — they ran inside
        one device program against a shared carry. Returns a (bool [m]
        "must requeue" mask, stale flag) pair, or None when no placement
        is affinity-relevant. A STALE encoding (foreign affinity churn
        since dispatch, detected via cache.aff_seq) conservatively
        requeues every relevant placement — the retry re-dispatches
        against a rebuilt encoding; the stale flag types those requeues
        distinctly (ISSUE 15: stale-encoding is an operability story —
        churn outran the patch path — not a capacity race)."""
        ad = enc.adata
        rel = ad.wave_relevant[cls_rows]
        if not rel.any():
            return None
        if enc is not self._wave_enc or enc.aff_seq != self.cache.aff_seq \
                or enc.labels_gen != self.snapshot.labels_gen:
            return rel.copy(), True
        snap = self.snapshot
        cn = enc.committed_nodes.astype(np.float64)           # [C, N]
        C_, A_ = ad.m_anti.shape[:2]
        m2 = ad.m_anti.reshape(C_ * A_, C_).astype(np.float64)
        kn = enc.key_node.reshape(C_ * A_, -1)                # [C*A, N]
        # anti side, per-node form (float64 GEMMs — exact for these counts)
        occ = (m2 @ cn).reshape(C_, A_, -1)
        own_forb = (occ * enc.key_node).sum(axis=1)           # [C, N]
        sym = (m2.T @ (kn * np.repeat(cn, A_, axis=0)))       # [C, N]
        forb = own_forb + sym + enc.static_forbid_hit
        if enc.foreign_forbid is not None:
            # Protean overlay (ISSUE 8): foreign churn patched in since
            # the build — exactly the rows the wholesale rebuild would
            # have re-derived
            forb = forb + enc.foreign_forbid
        aff_bad = forb[cls_rows, gnode] > 0
        cols = enc.tail_cols
        lab_p = cd = None
        if cols is not None and cols.size:
            lab_p = snap.labels[:, cols].astype(np.float64)   # [N, Lp]
            cd = cn @ lab_p                                   # [C, Lp]
            # anti + symmetry over the PROJECTED DOMAIN columns: the
            # per-node form above is exact only for singleton domains
            # (the wave-eligibility invariant); a strict-tail class's
            # zone-scoped term forbids the whole DOMAIN, and a blind
            # placement can land on a DIFFERENT node of a domain another
            # chunk's harvest just occupied. Multi-domain terms — own and
            # symmetry sources — always project into tail_cols
            # (_aff_tail_cols includes wave_strict classes' anti rows and
            # every term targeting them), so this closes the window the
            # per-node mirror cannot see. Hostname columns double-count
            # with the per-node form; harmless in a bool requeue mask.
            m3 = ad.m_anti.astype(np.float64)
            kp = ad.anti_keymask[:, :, cols].astype(np.float64)
            occ_dom = np.einsum("cad,dl->cal", m3, cd)
            own_dom = (occ_dom * kp).sum(axis=1)              # [C, Lp]
            sym_dom = np.einsum("dac,dal->cl", m3,
                                kp * cd[:, None, :])          # [C, Lp]
            dom = own_dom + sym_dom
            if enc.foreign_forbid_dom is not None:
                dom = dom + enc.foreign_forbid_dom
            aff_bad |= np.einsum("ml,ml->m", dom[cls_rows],
                                 lab_p[gnode]) > 0
        own = ad.aff_active.any(axis=1)
        own_rows = np.nonzero(own[cls_rows])[0]
        if own_rows.size and lab_p is not None:
            # allow side (strict-tail classes only), over the tail's
            # projected domain columns: a blind-window bootstrap or
            # co-location choice re-validates against domains occupied NOW
            # — monotone growth can only widen the allow set, so the one
            # true hazard is two chunks bootstrapping the same group into
            # different domains
            c_r = cls_rows[own_rows]
            lab_r = lab_p[gnode[own_rows]]
            m_aff = ad.m_aff.astype(np.float64)
            occp = (np.einsum("csd,dl->csl", m_aff, cd)
                    * ad.aff_keymask[:, :, cols])
            dyn = np.einsum("msl,ml->ms", occp[c_r], lab_r) > 0
            stat = np.einsum(
                "msl,ml->ms",
                ad.aff_allow[c_r][:, :, cols].astype(np.float64), lab_r) > 0
            dyn_total = np.einsum("csd,d->cs", m_aff, cn.sum(axis=1))
            boot = ad.aff_self & ~ad.aff_has_static & (dyn_total == 0)
            ok_terms = (~ad.aff_active[c_r]) | stat | dyn | boot[c_r]
            aff_bad[own_rows] |= ~ok_terms.all(axis=1)
        return aff_bad & rel, False
