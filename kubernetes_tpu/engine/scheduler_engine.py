"""Host-side scheduling engine: snapshot -> device batch -> assume.

The TPU-native replacement for genericScheduler.Schedule
(reference: plugin/pkg/scheduler/core/generic_scheduler.go:88-142) operating
on the whole pending queue at once:

  1. delta-refresh the tensor snapshot from the SchedulerCache (the analog of
     cache.UpdateNodeNameToInfoMap at generic_scheduler.go:101);
  2. run engine/batch.place_batch on device — sequential semantics preserved
     (see batch.py docstring);
  3. map node indices back to names and AssumePod each placement into the
     cache (scheduler.go:188 assume; binding is the caller's async job,
     scheduler.go:224-250).

Pods whose features the kernels over-approximate (PodBatch.needs_host_check)
take the exact object-level oracle path against the updated cache — the
"exact host-side verification" safety net of SURVEY.md §7(e).

Device arrays are cached keyed on snapshot.version so an unchanged cluster
uploads nothing between batches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.engine.batch import NodeState, place_batch
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.ops.predicates import node_arrays, pod_arrays
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch


class PlacementResult:
    __slots__ = ("pod", "node_name", "fit_count")

    def __init__(self, pod: Pod, node_name: Optional[str], fit_count: int):
        self.pod = pod
        self.node_name = node_name
        self.fit_count = fit_count

    def __repr__(self):
        return f"Placement({self.pod.key()} -> {self.node_name})"


class SchedulingEngine:
    def __init__(self, cache: SchedulerCache,
                 priorities: Tuple[Tuple[str, int], ...] = prio.DEFAULT_PRIORITIES,
                 mem_shift: int = 10, workloads_provider=None,
                 hard_pod_affinity_weight: int = 1,
                 volume_ctx=None):
        from kubernetes_tpu.state.volumes import VolumeContext
        self.cache = cache
        self.priorities = priorities
        self.snapshot = ClusterSnapshot(mem_shift=mem_shift)
        # PV/PVC mirror (the pvInfo/pvcInfo listers of factory.go); the
        # owner (Scheduler) mutates it and bumps .version on watch events
        self.volume_ctx = volume_ctx if volume_ctx is not None else VolumeContext()
        self.rr = oracle.RoundRobin()  # shared counter, device + oracle paths
        # Service/RC/RS/SS objects for spreading & service affinity — the
        # factory's extra informers (factory.go:120-140)
        self.workloads_provider = workloads_provider or (lambda: [])
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self._device_nodes = None
        self._device_version = -1

    # ------------------------------------------------------------------ api

    def schedule(self, pods: Sequence[Pod], assume: bool = True
                 ) -> List[PlacementResult]:
        """Schedule a batch. Returns one PlacementResult per pod, in input
        order. When assume=True, successful placements are assumed into the
        cache with pod.node_name set (the caller binds asynchronously)."""
        if not pods:
            return []
        infos = self.cache.node_infos()
        self.snapshot.refresh(infos, volume_ctx=self.volume_ctx)
        # PodBatch first: selector compilation may grow the label vocab and
        # rebuild the label matrix; upload happens after, dirty-arrays only
        batch = PodBatch(pods, self.snapshot)
        nodes = self._nodes_on_device()

        # Symmetry routing (predicates.go:1146): a pod with NO affinity of
        # its own can still be blocked by an EXISTING pod's required
        # anti-affinity (or by an affinity pod earlier in this batch). Pods
        # matching any such term take the exact host path — the device kernel
        # doesn't model the symmetry check yet.
        from kubernetes_tpu.ops.oracle_ext import term_matches_pod
        anti_terms = []
        for info in infos.values():
            for e in info.pods_with_affinity:
                if e.affinity and e.affinity.pod_anti_affinity:
                    for term in e.affinity.pod_anti_affinity.required_terms:
                        anti_terms.append((term, e))
        for p in pods:
            if p.affinity and p.affinity.pod_anti_affinity:
                for term in p.affinity.pod_anti_affinity.required_terms:
                    anti_terms.append((term, p))
        if anti_terms:
            for i in range(len(pods)):
                if not batch.needs_host_check[i] and any(
                        term_matches_pod(term, owner, pods[i])
                        for term, owner in anti_terms):
                    batch.needs_host_check[i] = True

        fast_idx = [i for i in range(len(pods)) if not batch.needs_host_check[i]]
        slow_idx = [i for i in range(len(pods)) if batch.needs_host_check[i]]
        results: List[Optional[PlacementResult]] = [None] * len(pods)

        if fast_idx:
            if len(fast_idx) == len(pods):
                fast_batch = batch
            else:
                fast_batch = PodBatch([pods[i] for i in fast_idx], self.snapshot)
            parr = pod_arrays(fast_batch)
            state = NodeState(nodes["requested"], nodes["nonzero"],
                              nodes["pod_count"], nodes["port_bitmap"],
                              nodes["vol_present"], nodes["vol_rw"],
                              nodes["pd_present"], nodes["pd_counts"])
            selected, fit_counts, _, rr_end = place_batch(
                parr, nodes, state, jnp.uint32(self.rr.counter),
                self.priorities)
            selected = np.asarray(selected)
            fit_counts = np.asarray(fit_counts)
            self.rr.counter = int(rr_end)
            for j, i in enumerate(fast_idx):
                sel = int(selected[j])
                name = self.snapshot.node_names[sel] if sel >= 0 else None
                results[i] = PlacementResult(pods[i], name, int(fit_counts[j]))
                if name is not None and assume:
                    self._assume(pods[i], name)

        # exact host path for over-approximated pods, AFTER device placements
        # so they see committed capacity (FIFO order within themselves)
        if slow_idx:
            from kubernetes_tpu.ops.oracle_ext import SchedulingContext
            infos = self.cache.node_infos()
            names = self.snapshot.node_names
            ctx = SchedulingContext(
                infos, self.workloads_provider(),
                hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                volume_ctx=self.volume_ctx)
            for i in slow_idx:
                name = oracle.schedule_one(pods[i], names, infos, self.rr,
                                           self.priorities, ctx)
                results[i] = PlacementResult(pods[i], name, 1 if name else 0)
                if name is not None and assume:
                    self._assume(pods[i], name)
                    infos = self.cache.node_infos()
                    ctx.infos = infos
                    ctx.invalidate()

        return results  # type: ignore[return-value]

    # ------------------------------------------------------------- internals

    def _assume(self, pod: Pod, node_name: str) -> None:
        pod.node_name = node_name
        self.cache.assume_pod(pod)

    _NODE_ARRAY_KEYS = ("alloc", "requested", "nonzero", "pod_count",
                        "allowed_pods", "schedulable", "mem_pressure",
                        "disk_pressure", "labels", "taints_sched",
                        "taints_pref", "port_bitmap", "valid", "avoid",
                        "image_sizes", "has_zone", "vol_present", "vol_rw",
                        "pd_present", "pd_counts", "pd_kind", "pd_max")

    def _nodes_on_device(self):
        """Incremental host->HBM sync: re-upload an array only when its shape
        changed or the snapshot marked it dirty. Steady-state rounds move only
        requested/nonzero/pod_count (~KBs), not the 40MB+ full snapshot."""
        snap = self.snapshot
        if self._device_nodes is None:
            self._device_nodes = {}
        for k in self._NODE_ARRAY_KEYS:
            host = getattr(snap, k)
            cur = self._device_nodes.get(k)
            if cur is None or cur.shape != host.shape or k in snap.dirty:
                self._device_nodes[k] = jnp.asarray(host)
        snap.dirty.clear()
        self._device_version = snap.version
        return self._device_nodes
