"""The always-on incremental scheduler loop (ISSUE 7).

BENCH_r09 exposed the shape of the old engine: a pre-loaded 30k-pod
backlog drained at 28.8k pods/s, but under a live 5k/s offered stream it
bound almost nothing while pods arrived (backlog 29k at offer end, p99
create->bound 2.2 s) — a batch drain wearing a streaming costume. A real
kube-scheduler is never drained; it runs forever against a churning
cluster. This module inverts the control flow: the LOOP owns the
scheduler (pop whatever is queued the moment the device frees up)
instead of a scenario owning rounds.

ScheduleLoop is the one engine for both shapes:

- FIXED mode (``budget_s=None``) is the pipelined drain of ISSUE 2,
  byte-for-byte: each step pops one fixed-size chunk, dispatches its
  fused wave eval without blocking, then harvests the previous chunk.
  ``Scheduler.pipeline()`` and ``run_until_drained`` ride this mode, so
  the pre-loaded drain scenarios (and their A/B tests) are unchanged.

- STREAMING mode (``budget_s`` set) admits MICRO-WAVES on a latency
  budget instead of fixed chunks: each step pops ``min(ready, quantum)``
  where the quantum is a power-of-2 admission cap adapted from the
  observed per-wave pop->bind-complete wall clock. The quantum doubles
  while full waves finish well under budget (amortizing per-wave fixed
  costs when the stream runs hot) and halves when a wave's latency
  crosses the budget (bounding what one wave can make the next arrival
  wait for). Pops pad to ``bucket(max(n, min_quantum))`` through the
  engine's ``wave_pad_floor`` machinery, so the compiled-shape set is
  the log2 ladder between min_quantum and max_quantum — a ragged
  arrival stream (345, 589, 100, ...) never mints a fresh XLA compile
  (the GL003 hazard the ladder exists to kill).

Between micro-waves only the delta touches the device (the Firmament
insight, PAPERS.md §Firmament: incremental re-solve over deltas turns a
fast batch solver into a low-latency online scheduler): the class
encoding is reused via the (vocab_gen, aff_seq) key, the snapshot
refresh rides the owner's changed_hint, and fence-accepted assumes fold
in through snapshot.apply_assume_delta — zero re-tensorization and zero
full node walks while the loop is live (tests/test_stream_loop.py pins
this through span counters). Correctness is unchanged from the drain:
wave k+1 is encoded blind to wave k's commits and the harvest fence
re-validates (capacity, topology, gang quorum) — admission control
changes WHEN waves run, never what a wave means.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from kubernetes_tpu.observability import recorder as flightrec
from kubernetes_tpu.observability.podtrace import TRACER
from kubernetes_tpu.observability.recorder import RECORDER
from kubernetes_tpu.ops.predicates import bucket
from kubernetes_tpu.utils.trace import COUNTERS, Trace


class ScheduleLoop:
    """A live two-stage scheduling pipeline, optionally self-pacing.

    step() pops one admission of pods, dispatches its fused wave eval
    WITHOUT blocking, then harvests the PREVIOUS admission — so wave
    k+1's device time overlaps wave k's host bookkeeping (assume, bulk
    bind, watch drain). overlap=False is the sequential debug mode:
    identical dataflow (same blind window, same fence), device forced to
    complete before the host tail — placements are bit-identical, only
    the wall-clock overlap is forfeited.

    budget_s=None (fixed mode) admits exactly ``chunk`` pods per step —
    the ISSUE 2 drain pipeline. budget_s set (streaming mode) admits up
    to the adaptive ``quantum`` (see module docstring); ``chunk`` then
    serves as the initial quantum when given.
    """

    def __init__(self, sched, chunk: int = 0, overlap: bool = True,
                 budget_s: Optional[float] = None,
                 min_quantum: int = 256, max_quantum: int = 16384,
                 fastlane=None):
        self.sched = sched
        self.overlap = overlap
        self.budget_s = budget_s
        self.inflight = None
        self._pending: Dict[str, int] = {}  # stats from interrupt flushes
        # Sparrow fast lane (ISSUE 17): when given an engine.fastlane
        # .FastLane, latency-critical pods route to the queue's fast tier
        # and are pumped between micro-waves (and while a harvest blocks
        # on the device). None = the tier is off and every step below is
        # shape-identical to the pre-fast-lane loop.
        self.fastlane = fastlane
        # per-STEP cap on critical-path fast pops: the bulk stream pays
        # the fast tier's host time out of its own budget, so one burst
        # of fast arrivals must not starve a quantum (harvest-overlap
        # pumps are exempt — the host would otherwise just be blocked on
        # the device)
        self.fast_budget = 256
        if fastlane is not None:
            sched.queue.fast_classifier = fastlane.classify
        sched._pipeline = self
        if budget_s is None:
            # fixed mode: one compiled wave shape per drain — ragged
            # arrival pops pad up to the chunk bucket instead of
            # compiling per power-of-2 size
            self.chunk = max(int(chunk or sched.pipeline_chunk), 1)
            self.min_quantum = self.max_quantum = self.quantum = self.chunk
            sched.engine.wave_pad_floor = self.chunk
        else:
            self.min_quantum = bucket(max(int(min_quantum), 1))
            self.max_quantum = max(bucket(max(int(max_quantum), 1)),
                                   self.min_quantum)
            q = bucket(max(int(chunk), 1)) if chunk else self.min_quantum
            self.quantum = min(max(q, self.min_quantum), self.max_quantum)
            self.chunk = 0
            # micro-waves share the bucket ladder: every pop pads to
            # bucket(max(n, min_quantum)), so the compiled-shape set is
            # bounded at log2(max_quantum / min_quantum) + 1
            sched.engine.wave_pad_floor = self.min_quantum
        # latency model (streaming mode): EWMA of per-wave pop ->
        # bind-complete wall clock, the exact span an arriving pod adds
        # to the next pod's worst case
        self._lat_ewma = 0.0
        self._grow_streak = 0
        # housekeeping under load (ISSUE 8): empty-round gating starved
        # backoff gc + assume-TTL expiry on a saturated stream — run them
        # on a wall-clock cadence regardless of load
        self.gc_interval_s = 2.0
        self._last_gc = time.monotonic()
        # DEGRADED MODE (ISSUE 8): when the fence keeps throwing waves
        # back (fence conflicts, liveness rejects, gang rollbacks breach
        # degrade_threshold of the attempts for degrade_window consecutive
        # pod-ful steps), the optimistic blind-wave pipeline is losing to
        # churn — drop to the classic SYNCHRONOUS round (every placement
        # sees every commit; no blind window to fence) for recover_steps
        # pod-ful steps, then re-try streaming. Re-entering is cheap and
        # the hysteresis window keeps one bad wave from flapping the mode.
        self.degraded = False
        self.degrade_threshold = 0.5
        self.degrade_window = 3
        self.recover_steps = 16
        self._breach_streak = 0
        self._degraded_left = 0
        # budget-breach tracing (ISSUE 13 satellite): a pod-ful streaming
        # step that outlives the latency budget dumps its step breakdown
        # (utils/trace.Trace.log_if_long — the reference's slow-Schedule
        # discipline at the micro-wave grain). trace_now/trace_sink are
        # the test seams (fake clock, captured sink); threshold 0
        # disables the trace construction entirely.
        self.trace_threshold_s = budget_s or 0.0
        self.trace_now = time.monotonic
        self.trace_sink = None
        # stream gauges into the owner's telemetry registry (ISSUE 13):
        # quantum/backlog/degraded are THE live-introspection answers to
        # "why is p99 moving" — re-registering under one key means a
        # replacement loop supersedes a closed one
        telemetry = getattr(sched, "telemetry", None)
        if telemetry is not None:
            telemetry.register_gauges("stream", self._gauges)

    # ------------------------------------------------------------- state

    def _gauges(self):
        """Live stream state for the telemetry registry: what every
        introspection transport reports next to the counters. A scrape
        races the loop thread, so the in-flight handle is read ONCE —
        re-reading after the None check could catch the flush swap
        mid-stride."""
        handle = self.inflight
        inflight = 0 if handle is None else len(handle.pods)
        return {"stream_quantum": self.quantum,
                "stream_backlog": self.sched.queue.ready_count() + inflight,
                "stream_inflight": inflight,
                "stream_degraded": int(self.degraded),
                "stream_budget_ms": (self.budget_s or 0.0) * 1e3,
                "stream_fast_pending": self.sched.queue.fast_count()}

    @property
    def idle(self) -> bool:
        return self.inflight is None

    def flush(self) -> None:
        """Harvest the in-flight wave NOW (watch-event interrupt, classic-
        path barrier, shutdown). Its stats fold into the next step."""
        h, self.inflight = self.inflight, None
        if h is not None:
            for k, v in self.sched._complete_wave(h).items():
                self._pending[k] = self._pending.get(k, 0) + v
            self._observe_wave(h)

    # --------------------------------------------------------- admission

    def _observe_wave(self, handle) -> None:
        """Feed one completed wave into the latency model and adapt the
        admission quantum (streaming mode only). The observed span is
        pop -> bind-complete — with the pipeline two deep it covers the
        residual device wait plus both host tails, which is exactly what
        the NEXT arrival's create->bound will inherit."""
        if self.budget_s is None:
            return
        lat = time.monotonic() - handle.pop_ts
        a = 0.3
        self._lat_ewma = lat if self._lat_ewma == 0.0 \
            else (1.0 - a) * self._lat_ewma + a * lat
        if self._lat_ewma > self.budget_s \
                and self.quantum > self.min_quantum:
            # one wave's latency crossed the budget: halve what the next
            # admission may make an arrival wait for
            self.quantum //= 2
            self._grow_streak = 0
            COUNTERS.inc("stream.quantum_shrink")
        elif len(handle.pods) >= self.quantum \
                and self._lat_ewma < 0.5 * self.budget_s \
                and self.quantum < self.max_quantum:
            # saturated waves finishing well under budget: the stream is
            # throughput-limited — grow to amortize per-wave fixed costs.
            # Two consecutive signals, so one lucky wave can't thrash the
            # quantum (each growth step is a fresh compiled shape).
            self._grow_streak += 1
            if self._grow_streak >= 2:
                self.quantum *= 2
                self._grow_streak = 0
                COUNTERS.inc("stream.quantum_grow")
        else:
            self._grow_streak = 0

    # ---------------------------------------------------------- degraded

    def _note_health(self, stats: Dict[str, int]) -> None:
        """Feed one completed step into the churn-health model (streaming
        mode only). Attempts = binds + requeues this step surfaced; a step
        that surfaced none leaves the window untouched (idle ticks must
        not decay a breach streak the next loaded step would continue)."""
        if self.budget_s is None:
            return
        requeues = (stats.get("fence_requeued", 0)
                    + stats.get("liveness_requeued", 0)
                    + stats.get("gang_requeued", 0)
                    # sustained preemption-fence rollbacks (ISSUE 14): a
                    # store that keeps refusing atomic evict+bind commits
                    # is the same signal class as fence churn — the
                    # optimistic wave path is losing, drop to classic
                    + stats.get("preempt_rollbacks", 0))
        attempts = (stats.get("bound", 0) + requeues
                    + stats.get("preemptions", 0))
        if self.degraded:
            if attempts > 0:
                self._degraded_left -= 1
                if self._degraded_left <= 0:
                    self.degraded = False
                    self._breach_streak = 0
                    COUNTERS.inc("stream.degraded_exit")
                    if RECORDER.enabled:
                        RECORDER.record(flightrec.DEGRADED, a=0)
            return
        if attempts <= 0:
            return
        if requeues >= self.degrade_threshold * attempts:
            self._breach_streak += 1
            if self._breach_streak >= self.degrade_window:
                self.degraded = True
                self._degraded_left = self.recover_steps
                COUNTERS.inc("stream.degraded_enter")
                if RECORDER.enabled:
                    RECORDER.record(flightrec.DEGRADED, a=1,
                                    b=self._breach_streak)
        else:
            self._breach_streak = 0

    # --------------------------------------------------------- fast lane

    def _pump_fast(self, stats: Dict[str, int], limit: int = 0,
                   busy=None) -> int:
        """Drain the queue's fast tier through the FastLane executor —
        the tier-aware pop interleaved between micro-waves (ISSUE 17).
        ``limit`` caps pods this pump (0 = all); ``busy`` is an extra
        WaveHandle still owning the device (the harvest-overlap poll
        passes the wave it is waiting out). Routing is latency policy:
        the sampled eval runs on the resident device arrays only while
        NO wave is in flight (the CPU backend executes device programs
        FIFO — a dispatch behind a wave inherits the wave's latency),
        else the bit-equal host twin."""
        fl = self.fastlane
        if fl is None:
            return 0
        q = self.sched.queue
        if not q.fast_count():
            return 0
        pods = q.pop_fast(max_n=limit)
        if not pods:
            return 0
        pop_ts = time.monotonic()
        device_ok = True
        for h in (self.inflight, busy):
            if h is not None and not h.packed.is_ready():
                device_ok = False
                break
        for p in pods:
            fl.schedule(p, pop_ts, device_ok=device_ok)
        stats["fast_popped"] = stats.get("fast_popped", 0) + len(pods)
        return len(pods)

    # -------------------------------------------------------------- step

    def step(self, wait: float = 0.0) -> Dict[str, int]:
        s = self.sched
        stats = {"popped": 0, "bound": 0, "unschedulable": 0,
                 "bind_errors": 0, "preemptions": 0, "fence_requeued": 0,
                 "liveness_requeued": 0, "degraded_steps": 0}
        # budget-breach tracing (streaming mode): narrate THIS step's
        # phases; dumped only when the step outlives the budget — the
        # scheduler's slow-Schedule discipline at the micro-wave grain
        trace = None
        if self.budget_s is not None and self.trace_threshold_s > 0:
            trace = Trace("micro-wave step", now=self.trace_now,
                          sink=self.trace_sink, quantum=self.quantum)
        s.sync()  # columnar; node/volume events flush the pipeline first
        if trace is not None:
            trace.step("informer sync done")
        if self.fastlane is not None:
            # fast tier first (ISSUE 17): a latency-critical pod that
            # arrived in the sync above binds BEFORE this step's bulk
            # quantum even pops — budgeted so a fast burst can't starve
            # the bulk stream
            self._pump_fast(stats, limit=self.fast_budget)
        now = time.monotonic()
        if now - self._last_gc >= self.gc_interval_s:
            # housekeeping regardless of load (ISSUE 8): a saturated
            # stream never sees an empty round, so the empty-round-gated
            # gc would let backoff stamps for long-bound pods and expired
            # assumes grow without bound over a long run
            s._idle_gc()
            self._last_gc = now
        pods = s.queue.pop_batch(max_n=self.quantum, wait=wait)
        stats["popped"] = len(pods)
        if trace is not None and pods:
            trace.field("popped", len(pods))
            trace.step("micro-wave popped")
        handle = None
        if not pods:
            # parked-gang sweep on empty steps only: a pod-ful step either
            # takes the wave path (no gang members by eligibility) and
            # sweeps below, or falls back to _process_batch which runs the
            # arrival-exempt sweep itself
            s._sweep_parked_gangs(())
        if pods and self.degraded:
            # degraded mode: churn is beating the blind-wave fence — run
            # the classic synchronous round (every placement sees every
            # commit; nothing to fence) until the health model recovers
            stats["degraded_steps"] = 1
        if pods:
            pop_ts = time.monotonic()
            chunk_pods = pods
            if not self.degraded and s._wave_eligible(pods):
                # quorum-ready gangs ride the wave path as ordinary
                # batches (ISSUE 5) — the harvest applies their
                # all-or-nothing fence; below-quorum members park here
                chunk_pods, gang_spans = s._release_gangs_for_wave(
                    pods, stats)
                if chunk_pods:
                    handle = s.engine.dispatch_waves(chunk_pods, pop_ts,
                                                     gangs=gang_spans)
                    if trace is not None and handle is not None:
                        trace.step("wave dispatched (async)")
            if handle is None and chunk_pods:
                # classic fallback (ISSUE 18: no chunk SHAPE lands here
                # anymore — host-check and Policy chunks ride the wave).
                # Remaining triggers: gangs with gang_pipeline off, a
                # gang whose quorum is unreachable from its wave-eligible
                # members, degraded mode. The counter is the no-flush
                # routing guard's observable.
                COUNTERS.inc("stream.chunk_flush")
                self.flush()
                sub = s._process_batch(chunk_pods, pop_ts)
                sub["popped"] = 0  # already counted
                for k, v in sub.items():
                    stats[k] = stats.get(k, 0) + v
                if trace is not None:
                    trace.step("classic fallback round done")
            elif handle is not None and not self.overlap:
                # sequential mode: forfeit the overlap only. The span is
                # the profiler's measure of RAW per-wave device time (no
                # host work runs between dispatch and this block)
                from kubernetes_tpu.utils.trace import timed_span
                with timed_span("pipeline.device_sync"):
                    handle.block()
        prev, self.inflight = self.inflight, handle
        if prev is not None:
            fl = self.fastlane
            if fl is not None and (s.queue.fast_count() or fl.hot()):
                # harvest-overlap poll (ISSUE 17): the host is about to
                # block on prev's device array anyway, so until it lands,
                # serve fast pods (host-twin evals — the device is busy)
                # and SIP the watch stream for newly created ones
                # (sync_pods_sip drains only the leading run of simple
                # pod events and can never flush/reorder the pipeline).
                # Exempt from fast_budget: these pops cost the bulk
                # stream nothing — the alternative was idle blocking.
                packed = prev.packed
                while not packed.is_ready():
                    if self._pump_fast(stats, busy=prev) == 0 \
                            and s.sync_pods_sip() == 0:
                        time.sleep(0.0002)
            for k, v in s._complete_wave(prev).items():
                stats[k] = stats.get(k, 0) + v
            self._observe_wave(prev)
            if self.fastlane is not None and \
                    (s.queue.fast_count() or self.fastlane.hot()):
                # post-harvest pump (ISSUE 17): the harvest above is the
                # one host section the overlap poll can't thread through
                # — a fast pod that arrived inside it binds NOW, not
                # after the next step's sync + bulk quantum (budgeted:
                # the bulk stream already got this step's wave)
                s.sync_pods_sip()
                self._pump_fast(stats, limit=self.fast_budget)
            if trace is not None:
                trace.step("previous wave harvested + bound")
        if self._pending:
            for k, v in self._pending.items():
                stats[k] = stats.get(k, 0) + v
            self._pending = {}
        if not pods:
            s._idle_gc()
        self._note_health(stats)
        if trace is not None and (pods or prev is not None):
            # only steps that did wave work can breach meaningfully; an
            # idle tick dumping its (empty) breakdown would be noise
            trace.field("bound", stats["bound"])
            trace.field("degraded", int(self.degraded))
            if TRACER.enabled and trace.total() >= self.trace_threshold_s:
                # the pod-level black box joins the step forensics
                # (ISSUE 15): a breaching step's dump names the window's
                # slowest exemplar so the per-pod timeline is one
                # /debug/pods lookup away
                ex = TRACER.snapshot()["exemplars"]
                if ex:
                    trace.field("slowest_pod", ex[0]["key"])
                    trace.field("slowest_span_ms",
                                round(ex[0]["span_ms"], 1))
            trace.log_if_long(self.trace_threshold_s)
        return stats

    # ------------------------------------------------------------ quiesce

    def settled(self) -> bool:
        """The ONE quiesce predicate (bench stop conditions, drain(),
        tests): pipeline idle AND watch stream drained AND ready queue
        empty AND backoff heap empty. The deferred check matters: a pod
        requeued after a transient error is RETRIABLE, and declaring the
        loop settled before it re-enters would report results over a
        silently partial population. Calling this consumes watch events
        (sync side effect), like every other quiesce check before it."""
        s = self.sched
        return (self.idle and s.sync() == 0
                and s.queue.ready_count() == 0
                and not s.queue._deferred)

    def drain(self, idle_wait: float = 0.005) -> Dict[str, int]:
        """Step until settled; returns accumulated stats. Termination is
        the CALLER's contract — truly-unschedulable pods re-enter the
        ready queue forever, so scenario drivers wrap this in a
        wall-clock deadline (bench.run_arrival) or feed only placeable
        pods (warm/prime phases, tests)."""
        total: Dict[str, int] = {}
        while True:
            stats = self.step()
            for k, v in stats.items():
                total[k] = total.get(k, 0) + v
            if stats["popped"] == 0 and self.settled():
                return total
            if stats["popped"] == 0 and self.idle and idle_wait > 0:
                # a deferred pod's backoff must elapse — park on the
                # watch instead of spinning the step loop dry
                self.sched.sync(wait=idle_wait)

    # --------------------------------------------------------------- run

    def run(self, should_stop: Callable[[Dict[str, int], "ScheduleLoop"],
                                        bool],
            idle_wait: float = 0.002,
            on_step: Optional[Callable[[Dict[str, int], "ScheduleLoop"],
                                       None]] = None) -> Dict[str, int]:
        """Run continuously until ``should_stop(stats, loop)`` answers
        True — the loop owns the scheduler; scenarios observe through
        ``on_step`` and the scheduler's wave_observer instead of driving
        rounds themselves. Idle iterations (nothing popped, nothing in
        flight) block on the apiserver watch for up to ``idle_wait``
        seconds instead of busy-spinning, so an arrival wakes the loop
        the moment its event lands. Returns accumulated totals
        (close() is still the caller's job — an in-flight wave survives
        a stop so a later loop can resume it)."""
        total: Dict[str, int] = {}
        while True:
            stats = self.step()
            for k, v in stats.items():
                total[k] = total.get(k, 0) + v
            if on_step is not None:
                on_step(stats, self)
            if should_stop(stats, self):
                return total
            if stats["popped"] == 0 and self.idle and idle_wait > 0:
                # block for arrivals on the watch condition, not a sleep:
                # sync(wait=) parks on the apiserver's lock and wakes on
                # the next event broadcast
                self.sched.sync(wait=idle_wait)

    def close(self) -> Dict[str, int]:
        """Drain the in-flight wave and detach from the scheduler; returns
        any stats not yet reported through step()."""
        self.flush()
        out, self._pending = self._pending, {}
        if self.sched._pipeline is self:
            self.sched._pipeline = None
        # drop OUR gauges from the owner's registry (a replacement loop's
        # registration already superseded them — leave that one alone):
        # a closed loop serving stale quantum/degraded answers would be
        # introspection lying, and the registered bound method would pin
        # this loop (and its WaveHandle fields) alive
        telemetry = getattr(self.sched, "telemetry", None)
        if telemetry is not None:
            telemetry.unregister_gauges("stream", only_if=self._gauges)
        return out
