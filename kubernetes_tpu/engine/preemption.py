"""Pod priority preemption — the PodPriority long-tail item.

The reference at v1.7 ships only the feature gate
(pkg/features/kube_features.go:122 PodPriority, alpha) — scheduler
preemption landed in 1.8 (plugin/pkg/scheduler/core/generic_scheduler.go
Preempt / pickOneNodeForPreemption / selectVictimsOnNode in that tree).
This implements that design against the batch engine, TPU-framework
style: a vectorized host-side pre-filter over ALL nodes (the numpy
analog of the device fits kernel, over "resources freeable below my
priority") narrows to candidate nodes, then the exact oracle predicate
chain verifies each candidate with its victims removed — the same
over-approximate-then-verify-exact pattern the snapshot kernels use
(SURVEY §7 hard part (e)).

Semantics kept from the 1.8 scheduler:
- only pods with LOWER priority than the preemptor are victims;
- candidate victims are reprieved highest-priority-first while the
  preemptor still fits (selectVictimsOnNode's reprieve loop);
- node choice minimizes (highest victim priority, sum of victim
  priorities, victim count) — pickOneNodeForPreemption's ordering;
- a node where the preemptor does not fit even with every lower-
  priority pod gone is infeasible.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.state.node_info import NodeInfo


# exact-verification budget per preemptor (the percentageOfNodesToScore
# idea): past this many candidate nodes, verify only the most promising
MAX_VERIFIED_CANDIDATES = 128


@dataclass
class PreemptionPlan:
    node_name: str
    victims: List[Pod]  # sorted lowest priority first (eviction order)


class PreemptionState:
    """Round-scoped arrays for the candidate pre-filter: built ONCE from
    the NodeInfo map (O(total pods) Python attribute access), then each
    preemptor's mask is pure numpy (bincount segment sums over the pod
    axis) and plan effects apply incrementally — a 200-preemptor burst
    costs one array build, not 200 (measured 80 ms/preemptor without
    this at 1k nodes / 4k pods)."""

    def __init__(self, infos: Dict[str, NodeInfo]):
        self.names = sorted(infos)
        self.infos = [infos[n] for n in self.names]
        n = len(self.infos)
        self.alloc_cpu = np.empty(n, dtype=np.int64)
        self.alloc_mem = np.empty(n, dtype=np.int64)
        self.alloc_pods = np.empty(n, dtype=np.int64)
        self.used_cpu = np.empty(n, dtype=np.int64)
        self.used_mem = np.empty(n, dtype=np.int64)
        self.used_count = np.empty(n, dtype=np.int64)
        node_idx, prio, cpu, mem = [], [], [], []
        keys = []
        for i, info in enumerate(self.infos):
            alloc = info.allocatable()
            self.alloc_cpu[i] = alloc.milli_cpu
            self.alloc_mem[i] = alloc.memory
            self.alloc_pods[i] = info.allowed_pod_number()
            self.used_cpu[i] = info.requested.milli_cpu
            self.used_mem[i] = info.requested.memory
            self.used_count[i] = len(info.pods)
            for vic in info.pods:
                r = vic.resource_request()
                node_idx.append(i)
                prio.append(vic.priority)
                cpu.append(r.milli_cpu)
                mem.append(r.memory)
                keys.append(vic.key())
        self.n = n
        self.pod_node = np.asarray(node_idx, dtype=np.int64)
        self.pod_prio = np.asarray(prio, dtype=np.int64)
        self.pod_cpu = np.asarray(cpu, dtype=np.int64)
        self.pod_mem = np.asarray(mem, dtype=np.int64)
        self.pod_keys = keys
        self.alive = np.ones(len(node_idx), dtype=bool)
        self._name_index = {name: i for i, name in enumerate(self.names)}
        # flat pod arrays sorted by (node, priority) + segment offsets —
        # the vectorized tight-bound pass reads priority-ordered prefixes
        # of every node at once (built lazily on first truncation)
        self._s_perm: Optional[np.ndarray] = None

    def _ensure_sorted(self) -> None:
        if self._s_perm is not None:
            return
        perm = np.lexsort((self.pod_prio, self.pod_node))
        self._s_perm = perm
        self._s_node = self.pod_node[perm]
        self._s_prio = self.pod_prio[perm]
        self._s_cpu = self.pod_cpu[perm]
        self._s_mem = self.pod_mem[perm]
        # first flat position of each node's segment
        self._seg_start = np.searchsorted(self._s_node, np.arange(self.n))

    def tight_bounds(self, pod: Pod) -> np.ndarray:
        """Per-node EXACT minimal max-victim-priority under the
        resources-only relaxation: evict pods ascending by priority until
        the preemptor fits; the bound is that prefix's max priority. A
        true achievable-key floor — neither the optimistic per-node MIN
        (a tiny pod that frees nothing ranks a node too well) nor the
        pessimistic MAX (one high-priority pod hides a cheap
        single-victim plan). One vectorized pass over the flat
        (node, priority)-sorted arrays; INT64_MAX = infeasible."""
        self._ensure_sorted()
        need = pod.resource_request()
        below = self.alive[self._s_perm] & (self._s_prio < pod.priority)
        freed_cpu = np.cumsum(np.where(below, self._s_cpu, 0))
        freed_mem = np.cumsum(np.where(below, self._s_mem, 0))
        # per-segment cumulative = global cumsum minus the segment base
        base_cpu = np.concatenate(([0], freed_cpu))[self._seg_start]
        base_mem = np.concatenate(([0], freed_mem))[self._seg_start]
        spare_cpu = (self.alloc_cpu - self.used_cpu)[self._s_node]
        spare_mem = (self.alloc_mem - self.used_mem)[self._s_node]
        ok = ((spare_cpu + freed_cpu - base_cpu[self._s_node]
               >= need.milli_cpu)
              & (spare_mem + freed_mem - base_mem[self._s_node]
                 >= need.memory) & below)
        big = np.iinfo(np.int64).max
        first_ok = np.full(self.n, len(ok), dtype=np.int64)
        flat_pos = np.flatnonzero(ok)
        np.minimum.at(first_ok, self._s_node[flat_pos], flat_pos)
        bounds = np.full(self.n, big, dtype=np.int64)
        has = first_ok < len(ok)
        bounds[has] = self._s_prio[first_ok[has]]
        return bounds

    def candidate_mask(self, pod: Pod) -> np.ndarray:
        need = pod.resource_request()
        below = self.alive & (self.pod_prio < pod.priority)
        idx = self.pod_node[below]
        free_cpu = np.bincount(idx, weights=self.pod_cpu[below],
                               minlength=self.n)
        free_mem = np.bincount(idx, weights=self.pod_mem[below],
                               minlength=self.n)
        free_count = np.bincount(idx, minlength=self.n)
        return ((self.used_cpu - free_cpu + need.milli_cpu
                 <= self.alloc_cpu)
                & (self.used_mem - free_mem + need.memory
                   <= self.alloc_mem)
                & (self.used_count - free_count + 1 <= self.alloc_pods)
                & (free_count > 0))  # no victims -> plain unschedulable,
                                     # not a preemption candidate

    def apply_plan(self, plan: "PreemptionPlan", pod: Pod) -> None:
        """Reflect a committed plan: victims leave the arrays (and the
        node totals), the preemptor's request is reserved. The preemptor
        itself is NOT added to the pod arrays: later preemptors in the
        round have lower priority (sorted desc), so it can never be
        their victim — its reservation lives only in used_*."""
        node_i = self._name_index[plan.node_name]
        victim_keys = {v.key() for v in plan.victims}
        for v in plan.victims:
            r = v.resource_request()
            self.used_cpu[node_i] -= r.milli_cpu
            self.used_mem[node_i] -= r.memory
            self.used_count[node_i] -= 1
        # mark victim entries dead by key — order-independent, so
        # multiple plans against the same node stay consistent even as
        # the caller mutates the NodeInfo between them
        for j in np.flatnonzero(self.pod_node == node_i):
            if self.pod_keys[int(j)] in victim_keys:
                self.alive[int(j)] = False
        need = pod.resource_request()
        self.used_cpu[node_i] += need.milli_cpu
        self.used_mem[node_i] += need.memory
        self.used_count[node_i] += 1


def _select_victims(pod: Pod, info: NodeInfo,
                    ctx=None, evictable=None) -> Optional[List[Pod]]:
    """selectVictimsOnNode: start from all lower-priority pods evicted;
    if the preemptor fits, reprieve highest-priority victims first while
    it keeps fitting. Returns the minimal victim set, or None if the
    node is infeasible even with everything gone.

    ``evictable`` (ISSUE 14): optional predicate narrowing the potential
    victim set — the wave path passes a store-confirmed-bound filter so
    an assumed-but-unconfirmed pod (unbound at the store; its eviction
    write would abort the atomic preempt commit) is never planned as a
    victim. None keeps the classic all-lower-priority semantics."""
    potential = [p for p in info.pods if p.priority < pod.priority
                 and (evictable is None or evictable(p))]
    if not potential:
        return None
    pot_keys = {p.key() for p in potential}
    keep = [p for p in info.pods if p.key() not in pot_keys]
    base = NodeInfo(info.node)
    for p in keep:
        base.add_pod(p)
    if not oracle.pod_fits(pod, base, ctx=ctx):
        return None
    # reprieve pass: highest priority first (then larger pods last so
    # small high-priority pods come back first)
    victims: List[Pod] = []
    for vic in sorted(potential,
                      key=lambda p: (-p.priority,
                                     p.resource_request().milli_cpu)):
        base.add_pod(vic)
        if oracle.pod_fits(pod, base, ctx=ctx):
            continue  # reprieved — stays
        base.remove_pod(vic)
        victims.append(vic)
    return sorted(victims, key=lambda p: p.priority)


def pick_preemption(pod: Pod, node_infos: Dict[str, NodeInfo],
                    ctx=None,
                    state: Optional[PreemptionState] = None
                    ) -> Optional[PreemptionPlan]:
    """generic_scheduler.Preempt: pre-filter all nodes vectorized, verify
    candidates exactly, choose by pickOneNodeForPreemption's ordering.
    Pass a round-scoped PreemptionState to amortize the array build over
    many preemptors (the caller then applies plans via
    state.apply_plan)."""
    if pod.priority <= 0:
        return None
    if state is None:
        state = PreemptionState(node_infos)
    mask = state.candidate_mask(pod)
    candidates = np.flatnonzero(mask)
    if len(candidates) > MAX_VERIFIED_CANDIDATES:
        # bound the exact phase the way the newer reference bounds
        # scoring (percentageOfNodesToScore), ranked by the TIGHT bound
        # (tight_bounds): the minimal max-victim-priority that actually
        # frees enough resources. This avoids both truncation
        # pathologies — a MAX ranking hides cheap single-victim plans on
        # mixed nodes, a bare MIN ranking promotes nodes whose tiny
        # low-priority pod frees nothing.
        bounds = state.tight_bounds(pod)
        order = np.argsort(bounds[candidates], kind="stable")
        candidates = candidates[order][:MAX_VERIFIED_CANDIDATES]
    best: Optional[Tuple[Tuple[int, int, int], str, List[Pod]]] = None
    for i in candidates:
        info = state.infos[int(i)]
        victims = _select_victims(pod, info, ctx=ctx)
        if victims is None or not victims:
            continue
        key = (max(v.priority for v in victims),
               sum(v.priority for v in victims),
               len(victims))
        if best is None or key < best[0]:
            best = (key, state.names[int(i)], victims)
    if best is None:
        return None
    return PreemptionPlan(node_name=best[1], victims=best[2])
