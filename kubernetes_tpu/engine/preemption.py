"""Pod priority preemption — the PodPriority long-tail item.

The reference at v1.7 ships only the feature gate
(pkg/features/kube_features.go:122 PodPriority, alpha) — scheduler
preemption landed in 1.8 (plugin/pkg/scheduler/core/generic_scheduler.go
Preempt / pickOneNodeForPreemption / selectVictimsOnNode in that tree).
This implements that design against the batch engine, TPU-framework
style: a vectorized host-side pre-filter over ALL nodes (the numpy
analog of the device fits kernel, over "resources freeable below my
priority") narrows to candidate nodes, then the exact oracle predicate
chain verifies each candidate with its victims removed — the same
over-approximate-then-verify-exact pattern the snapshot kernels use
(SURVEY §7 hard part (e)).

Semantics kept from the 1.8 scheduler:
- only pods with LOWER priority than the preemptor are victims;
- candidate victims are reprieved highest-priority-first while the
  preemptor still fits (selectVictimsOnNode's reprieve loop);
- node choice minimizes (highest victim priority, sum of victim
  priorities, victim count) — pickOneNodeForPreemption's ordering;
- a node where the preemptor does not fit even with every lower-
  priority pod gone is infeasible.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.state.node_info import NodeInfo


@dataclass
class PreemptionPlan:
    node_name: str
    victims: List[Pod]  # sorted lowest priority first (eviction order)


def _candidate_mask(pod: Pod, infos: List[NodeInfo]) -> np.ndarray:
    """Vectorized pre-filter: could the preemptor fit on node n if every
    pod with lower priority were evicted? Over-approximates (resources +
    pod-count only) — exact verification follows per candidate."""
    need = pod.resource_request()
    n = len(infos)
    alloc_cpu = np.empty(n, dtype=np.int64)
    alloc_mem = np.empty(n, dtype=np.int64)
    alloc_pods = np.empty(n, dtype=np.int64)
    used_cpu = np.empty(n, dtype=np.int64)
    used_mem = np.empty(n, dtype=np.int64)
    used_count = np.empty(n, dtype=np.int64)
    free_cpu = np.empty(n, dtype=np.int64)
    free_mem = np.empty(n, dtype=np.int64)
    free_count = np.empty(n, dtype=np.int64)
    for i, info in enumerate(infos):
        alloc = info.allocatable()
        alloc_cpu[i] = alloc.milli_cpu
        alloc_mem[i] = alloc.memory
        alloc_pods[i] = info.allowed_pod_number()
        used_cpu[i] = info.requested.milli_cpu
        used_mem[i] = info.requested.memory
        used_count[i] = len(info.pods)
        fc = fm = fn_ = 0
        for vic in info.pods:
            if vic.priority < pod.priority:
                r = vic.resource_request()
                fc += r.milli_cpu
                fm += r.memory
                fn_ += 1
        free_cpu[i] = fc
        free_mem[i] = fm
        free_count[i] = fn_
    return ((used_cpu - free_cpu + need.milli_cpu <= alloc_cpu)
            & (used_mem - free_mem + need.memory <= alloc_mem)
            & (used_count - free_count + 1 <= alloc_pods)
            & (free_count > 0))  # no victims -> plain unschedulable, not
                                 # a preemption candidate


def _select_victims(pod: Pod, info: NodeInfo,
                    ctx=None) -> Optional[List[Pod]]:
    """selectVictimsOnNode: start from all lower-priority pods evicted;
    if the preemptor fits, reprieve highest-priority victims first while
    it keeps fitting. Returns the minimal victim set, or None if the
    node is infeasible even with everything gone."""
    potential = [p for p in info.pods if p.priority < pod.priority]
    if not potential:
        return None
    keep = [p for p in info.pods if p.priority >= pod.priority]
    base = NodeInfo(info.node)
    for p in keep:
        base.add_pod(p)
    if not oracle.pod_fits(pod, base, ctx=ctx):
        return None
    # reprieve pass: highest priority first (then larger pods last so
    # small high-priority pods come back first)
    victims: List[Pod] = []
    for vic in sorted(potential,
                      key=lambda p: (-p.priority,
                                     p.resource_request().milli_cpu)):
        base.add_pod(vic)
        if oracle.pod_fits(pod, base, ctx=ctx):
            continue  # reprieved — stays
        base.remove_pod(vic)
        victims.append(vic)
    return sorted(victims, key=lambda p: p.priority)


def pick_preemption(pod: Pod, node_infos: Dict[str, NodeInfo],
                    ctx=None) -> Optional[PreemptionPlan]:
    """generic_scheduler.Preempt: pre-filter all nodes vectorized, verify
    candidates exactly, choose by pickOneNodeForPreemption's ordering."""
    if pod.priority <= 0:
        return None
    names = sorted(node_infos)
    infos = [node_infos[n] for n in names]
    mask = _candidate_mask(pod, infos)
    best: Optional[Tuple[Tuple[int, int, int], str, List[Pod]]] = None
    for i in np.flatnonzero(mask):
        info = infos[int(i)]
        victims = _select_victims(pod, info, ctx=ctx)
        if victims is None or not victims:
            continue
        key = (max(v.priority for v in victims),
               sum(v.priority for v in victims),
               len(victims))
        if best is None or key < best[0]:
            best = (key, names[int(i)], victims)
    if best is None:
        return None
    return PreemptionPlan(node_name=best[1], victims=best[2])
