"""Batch placement: the whole pending queue scheduled in one device program.

The reference schedules strictly one pod at a time (scheduler.go:253
scheduleOne; SURVEY.md §2.3 — the single-goroutine serialization point), with
each decision visible to the next via SchedulerCache.AssumePod. This module
reproduces those *exact* sequential semantics on device: a lax.scan over the
pending pods where the carry is the mutable node state (requested resources,
nonzero sums, pod counts, port bitmaps) and each step re-evaluates the
capacity-dependent predicates/priorities against the carry before committing
the chosen node — i.e. assume/decrement happens on device, solving the
batch-staleness problem (SURVEY.md §7 hard part (c)) without host round-trips.

Work split per SURVEY.md §7 step 2:
  - capacity-INdependent masks (selector/taints/host/conditions) and score
    components (taint-toleration counts) are batched MXU matmuls computed ONCE
    for the whole chunk *outside* the scan (ops/predicates.static_fits);
  - the per-pod scan step is cheap VPU work: O(N*R) compares + one argmax.

selectHost parity (generic_scheduler.go:88-160):
  - 0 fitting nodes  -> selected = -1 (FitError host-side), counter unchanged
  - 1 fitting node   -> early return (schedule() skips PrioritizeNodes), RR
                        counter NOT incremented (generic_scheduler.go:110-117)
  - >1 fitting nodes -> max-score tie set, index = counter % ties (counter++),
                        tie order = ascending node index (the reference's
                        unstable-sort order is implementation-defined).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from kubernetes_tpu.ops import affinity as aff_ops
from kubernetes_tpu.ops import predicates as preds
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.api.types import MAX_PRIORITY

Arrays = Dict[str, jnp.ndarray]


class NodeState(NamedTuple):
    """The mutable (carry) slice of node state. Static facts (alloc, labels,
    taints, allowed_pods, conditions) stay outside the carry. Volume
    presence/counts are carried because NoDiskConflict / MaxPDVolumeCount
    are capacity-dependent: a pod committing its volumes must be visible to
    the next pod in the batch (assume semantics)."""

    requested: jnp.ndarray  # int32 [N,R]
    nonzero: jnp.ndarray  # int32 [N,2]
    pod_count: jnp.ndarray  # int32 [N]
    port_bitmap: jnp.ndarray  # uint32 [N,W]
    vol_present: jnp.ndarray  # int8 [N,Vc] conflict-key presence
    vol_rw: jnp.ndarray  # int8 [N,Vc] read-write presence
    pd_present: jnp.ndarray  # int8 [N,Vpd]
    pd_counts: jnp.ndarray  # int32 [N,3] distinct filtered vols per kind


def node_state(nodes: Arrays) -> NodeState:
    return NodeState(nodes["requested"], nodes["nonzero"], nodes["pod_count"],
                     nodes["port_bitmap"], nodes["vol_present"],
                     nodes["vol_rw"], nodes["pd_present"], nodes["pd_counts"])


# priorities whose per-node score depends only on node spec + pod (no carry,
# no filtered-set reduce): computed once for the whole batch outside the scan
_STATIC_PRIORITIES = ("NodePreferAvoidPodsPriority", "ImageLocalityPriority",
                      "EqualPriority")
# carry-dependent (capacity evolves as pods commit)
_DYNAMIC_PRIORITIES = ("LeastRequestedPriority", "MostRequestedPriority",
                       "BalancedResourceAllocation")
# filtered-set-normalized reduces, recomputed per pod against current fits
_REDUCE_PRIORITIES = ("TaintTolerationPriority", "NodeAffinityPriority")


def _step_scores(pod_nonzero: jnp.ndarray, state: NodeState, alloc: jnp.ndarray,
                 tt_cnt: jnp.ndarray, na_cnt: jnp.ndarray,
                 static_score: jnp.ndarray, fits: jnp.ndarray,
                 priorities: Tuple[Tuple[str, int], ...]) -> jnp.ndarray:
    """Per-pod priority sum against the evolving carry. [N] int32."""
    pz = pod_nonzero[None, :]  # [1,2]
    total = static_score
    for name, weight in priorities:
        if name == "LeastRequestedPriority":
            s = prio.least_requested(pz, state.nonzero, alloc)[0]
        elif name == "MostRequestedPriority":
            s = prio.most_requested(pz, state.nonzero, alloc)[0]
        elif name == "BalancedResourceAllocation":
            s = prio.balanced_allocation(pz, state.nonzero, alloc)[0]
        elif name == "TaintTolerationPriority":
            # normalizing reduce over the pod's CURRENT filtered set
            masked = jnp.where(fits, tt_cnt, 0)
            mx = masked.max()
            s = jnp.where(mx == 0, MAX_PRIORITY,
                          (MAX_PRIORITY * (mx - tt_cnt)) // jnp.maximum(mx, 1))
        elif name == "NodeAffinityPriority":
            masked = jnp.where(fits, na_cnt, 0)
            mx = masked.max()
            s = jnp.where(mx > 0, (MAX_PRIORITY * na_cnt) // jnp.maximum(mx, 1), 0)
        elif name in _STATIC_PRIORITIES:
            continue  # folded into static_score
        elif name in ("SelectorSpreadPriority", "InterPodAffinityPriority"):
            continue  # computed by the caller from the affinity carry
        else:
            raise KeyError(name)  # unknown priorities are a hard error,
            # never a silent zero (VERDICT r1 weak #5)
        total = total + s * weight
    return total


def _commit(state: NodeState, sel: jnp.ndarray, ok: jnp.ndarray,
            pod_req: jnp.ndarray, pod_nonzero: jnp.ndarray,
            pod_ports: jnp.ndarray, pod_vol_hard: jnp.ndarray,
            pod_vol_ro: jnp.ndarray, pod_pd_req: jnp.ndarray,
            pd_new_sel: jnp.ndarray) -> NodeState:
    """Decrement capacity at the selected node (the on-device AssumePod)."""
    safe = jnp.where(ok, sel, 0)
    gain = ok.astype(jnp.int32)
    requested = state.requested.at[safe].add(pod_req * gain)
    nonzero = state.nonzero.at[safe].add(pod_nonzero * gain)
    pod_count = state.pod_count.at[safe].add(gain)
    # OR the pod's host-port bits into the node's bitmap. Ports are deduped
    # host-side (Pod.used_ports), so bits landing in the same word are
    # distinct and a scatter-ADD is an exact OR (the pod only commits to a
    # node where none of its bits were set).
    want = pod_ports >= 0
    wsafe = jnp.maximum(pod_ports, 0)
    words = wsafe // 32
    bits = jnp.where(want & ok, jnp.uint32(1) << (wsafe % 32).astype(jnp.uint32),
                     jnp.uint32(0))
    row = state.port_bitmap[safe].at[words].add(bits)
    port_bitmap = state.port_bitmap.at[safe].set(
        jnp.where(ok, row, state.port_bitmap[safe]))
    # volume commit: presence is an OR (int8 max); pd_counts grows by the
    # number of distinct new ids the pod brought to this node
    zero8 = jnp.zeros_like(pod_vol_hard)
    presence = jnp.where(ok, pod_vol_hard | pod_vol_ro, zero8)
    vol_present = state.vol_present.at[safe].max(presence)
    vol_rw = state.vol_rw.at[safe].max(jnp.where(ok, pod_vol_hard, zero8))
    pd_present = state.pd_present.at[safe].max(
        jnp.where(ok, pod_pd_req, jnp.zeros_like(pod_pd_req)))
    pd_counts = state.pd_counts.at[safe].add(pd_new_sel * gain)
    return NodeState(requested, nonzero, pod_count, port_bitmap,
                     vol_present, vol_rw, pd_present, pd_counts)


def _batch_pre(pods: Arrays, nodes: Arrays,
               priorities) -> Tuple[jnp.ndarray, ...]:
    """The [*, N] capacity-independent tensors place_batch consumes:
    static predicate mask, reduce-priority count matrices, static priority
    score. Shape-generic over the leading axis — gather_place_batch calls
    this at CLASS level and gathers per-pod rows, because a strict tail of
    P pods over C << P classes repeats each class row P/C times and the
    label-axis matmuls in here (selector_fit, node_affinity_counts) scale
    with the cluster once hostname domains are interned: computing them
    per POD was the dominant hidden cost of the r08 affinity tail
    (PROFILE_r08.md §3)."""
    static_fit = preds.static_fits(pods, nodes) \
        & preds.node_condition_fit(pods, nodes)
    tt_cnt = jnp.einsum("pt,nt->pn", pods["intolerated_pref"],
                        nodes["taints_pref"].astype(jnp.int8),
                        preferred_element_type=jnp.int32)
    na_cnt = prio.node_affinity_counts(pods, nodes["labels"]) \
        if any(nm == "NodeAffinityPriority" for nm, _ in priorities) \
        else jnp.zeros(static_fit.shape, dtype=jnp.int32)
    static_score = jnp.zeros(static_fit.shape, dtype=jnp.int32)
    for name, weight in priorities:
        if name in _STATIC_PRIORITIES:
            static_score = static_score + \
                prio.PRIORITY_REGISTRY[name](pods, nodes, None) * weight
    if "policy_score" in pods:
        # Policy-configured NodeLabel / ServiceAntiAffinity priorities
        # (weights pre-folded; ops/policy_algos.py)
        static_score = static_score + pods["policy_score"]
    return static_fit, tt_cnt, na_cnt, static_score


def check_affinity_priorities(priorities, aff, extra_score) -> None:
    """Affinity-priority guard shared by every batch-placement entry point
    (place_batch scan, waves.tail_rounds_loop): SelectorSpread/
    InterPodAffinity in the priority set without class data or a frozen
    extra_score would contribute silent zeros — a parity bug, never a
    fallback."""
    for nm, _w in priorities:
        if nm in ("SelectorSpreadPriority", "InterPodAffinityPriority") \
                and aff is None and extra_score is None:
            raise ValueError(
                f"{nm} in the priority set requires affinity/spread class "
                "data (pass aff= from ops.affinity.AffinityData, or a "
                "frozen extra_score) — silent zero contributions are a "
                "parity bug, not a fallback")


@functools.partial(jax.jit, static_argnames=("priorities", "aff_mode"))
def gather_place_batch(cls_arr: Arrays, pc: jnp.ndarray, nodes: Arrays,
                       state: "NodeState", rr: jnp.ndarray, priorities,
                       aff: Arrays = None,
                       aff_mode: Tuple[bool, bool, bool] = (False, False, False),
                       aff_init=None, extra_score: jnp.ndarray = None):
    """place_batch over per-pod rows gathered from class rows (pc = class
    index per pod). The gather runs inside the jit so padding/bucketed
    shapes cost no standalone eager-op compiles. `aff` stays class-level
    (the scan indexes it by pc per step — gathering [P, S, L] per-pod rows
    would blow memory at 30k pods); `extra_score` is class-level [C, N].
    The capacity-independent [C, N] tensors are computed ONCE at class
    level and gathered — identical rows, a fraction of the matmuls."""
    parr = jax.tree.map(lambda a: a[pc], cls_arr)
    ex = extra_score[pc] if extra_score is not None else None
    pre_c = _batch_pre(cls_arr, nodes, priorities)
    pre = tuple(a[pc] for a in pre_c)
    return place_batch(parr, nodes, state, rr, priorities, aff=aff, pc=pc,
                       aff_mode=aff_mode, aff_init=aff_init, extra_score=ex,
                       pre=pre)


@functools.partial(jax.jit, static_argnames=("priorities", "aff_mode"))
def place_batch(pods: Arrays, nodes: Arrays, state: NodeState,
                rr_counter: jnp.ndarray,
                priorities: Tuple[Tuple[str, int], ...] = prio.DEFAULT_PRIORITIES,
                aff: Arrays = None, pc: jnp.ndarray = None,
                aff_mode: Tuple[bool, bool, bool] = (False, False, False),
                aff_init=None, extra_score: jnp.ndarray = None,
                pre: Tuple[jnp.ndarray, ...] = None,
                ) -> Tuple[jnp.ndarray, jnp.ndarray, NodeState, jnp.ndarray]:
    """Place every pod in the batch sequentially on device.

    `aff`/`pc`/`aff_mode` switch on the inter-pod affinity + selector-spread
    machinery (ops/affinity.py): aff holds the CLASS-level static arrays,
    pc [P] maps each pod to its class, and aff_mode = (fits_on, prio_on,
    spread_on) statically gates which parts trace. The scan carry then grows
    per-class domain occupancy (commdom), per-class-per-node commit counts
    (committed) and totals (comm_cnt) — the on-device mirror of what the
    reference's sequential loop sees through the scheduler cache.

    Returns (selected [P] int32 node index or -1,
             fit_count [P] int32 (diagnostics / FitError),
             final NodeState,
             final rr_counter).
    """
    fits_on, prio_on, spread_on = aff_mode
    any_aff = aff is not None and (fits_on or prio_on or spread_on)
    check_affinity_priorities(priorities, aff, extra_score)
    w_ip = sum(w for nm, w in priorities
               if nm == "InterPodAffinityPriority") if prio_on else 0
    w_sp = sum(w for nm, w in priorities
               if nm == "SelectorSpreadPriority") if spread_on else 0
    if pre is None:
        pre = _batch_pre(pods, nodes, priorities)
    static_fit, tt_cnt, na_cnt, static_score = pre  # [P,N] — MXU batch
    alloc = nodes["alloc"]
    allowed = nodes["allowed_pods"]
    n = alloc.shape[0]
    p_count = pods["req"].shape[0]
    idx_n = jnp.arange(n, dtype=jnp.int32)
    if any_aff:
        c_dim = aff["m_aff"].shape[0]
        # labels_aff (when present) is the PROJECTED domain incidence the
        # aff arrays' domain axes are sliced to (the pipelined tail's
        # column projection, engine/scheduler_engine._aff_tail_arrays) —
        # the occupancy contractions then run at Lp = O(referenced
        # domains) instead of the full label width. The predicate/priority
        # arrays in `pods`/`nodes` keep the full label matrix.
        labels = aff["labels_aff"] if "labels_aff" in aff \
            else nodes["labels"]
        l_dim = labels.shape[1]
        # deliberately the jnp einsum, NOT the Pallas incidence kernel
        # (ops/pallas_kernels.precompute_static_fast): this path also runs
        # with the node axis SHARDED over a mesh (dryrun_multichip,
        # tests/test_mesh.py), and a pallas_call is an opaque custom call
        # XLA's SPMD partitioner cannot split — the einsum it CAN
        pre_aff = aff_ops.precompute_static(aff, labels)
    else:
        c_dim, l_dim = 1, 1
        labels = jnp.zeros((n, 1), dtype=jnp.int8)
        pre_aff = None
    if pc is None:
        pc = jnp.zeros(p_count, dtype=jnp.int32)
    if aff_init is not None:
        # pods this batch already committed through another engine (wave
        # mode places plain classes first): their topology occupancy must
        # be visible here, exactly as the reference's sequential loop would
        # have seen them in the scheduler cache
        commdom0, committed0, comm_cnt0 = aff_init
    else:
        commdom0 = jnp.zeros((c_dim, l_dim), dtype=jnp.int32)
        committed0 = jnp.zeros((c_dim, n), dtype=jnp.int32)
        comm_cnt0 = jnp.zeros(c_dim, dtype=jnp.int32)
    pd_kind = nodes["pd_kind"]
    pd_max = nodes["pd_max"]

    def step(carry, xs):
        state, counter, commdom, committed, comm_cnt = carry
        p_static, p_tt, p_na, p_sscore = (xs["static"], xs["tt"], xs["na"],
                                          xs["sscore"])
        p_req, p_zero, p_nonzero, p_ports = (xs["req"], xs["zero"],
                                             xs["nonzero"], xs["ports"])
        p_vol_hard, p_vol_ro, p_pd_req, p_pd_count = (
            xs["vol_hard"], xs["vol_ro"], xs["pd_req"], xs["pd_count"])
        pc_i = xs["pc"]
        p_extra = xs.get("extra")
        # NoDiskConflict against the evolving presence (int8 matvecs)
        hard_hit = jnp.einsum("nv,v->n", state.vol_present, p_vol_hard,
                              preferred_element_type=jnp.int32)
        ro_hit = jnp.einsum("nv,v->n", state.vol_rw, p_vol_ro,
                            preferred_element_type=jnp.int32)
        disk_ok = (hard_hit == 0) & (ro_hit == 0)
        # MaxPDVolumeCount per filter kind against evolving counts
        pd_ok = jnp.ones_like(disk_ok)
        pd_new = []
        for k in range(3):
            req_k = p_pd_req * pd_kind[k]
            overlap = jnp.einsum("nv,v->n", state.pd_present, req_k,
                                 preferred_element_type=jnp.int32)
            new_k = p_pd_count[k] - overlap
            pd_new.append(new_k)
            pd_ok = pd_ok & ((p_pd_count[k] == 0)
                             | (state.pd_counts[:, k] + new_k <= pd_max[k]))
        dyn = (
            preds.resources_fit(p_req[None], p_zero[None], alloc, state.requested)[0]
            & preds.pod_count_fit(state.pod_count, allowed)
            & preds.ports_fit(p_ports[None], state.port_bitmap)[0]
            & disk_ok & pd_ok
        )
        fits = p_static & dyn
        if fits_on:
            fits = fits & aff_ops.step_fits(aff, pre_aff, pc_i, commdom,
                                            comm_cnt, labels)
        fit_count = fits.sum().astype(jnp.int32)
        scores = _step_scores(p_nonzero, state, alloc, p_tt, p_na, p_sscore,
                              fits, priorities)
        if extra_score is not None:
            scores = scores + p_extra
        if prio_on:
            cnt_ip = aff_ops.step_prio_counts(aff, pre_aff, pc_i, commdom,
                                              labels)
            scores = scores + w_ip * aff_ops.interpod_score(cnt_ip, fits)
        if spread_on:
            cnt_sp = aff_ops.step_spread_counts(aff, pc_i, committed)
            scores = scores + w_sp * aff_ops.spread_score(
                aff, aff["sp_has"][pc_i], cnt_sp, fits)
        masked = jnp.where(fits, scores, jnp.int32(-1))
        best = masked.max()
        ties = masked == best  # only fitting nodes can equal best when best>=0
        num_ties = ties.sum().astype(jnp.uint32)
        k = jnp.where(num_ties > 0, counter % jnp.maximum(num_ties, 1), 0)
        # k-th fitting max-score node in ascending index order
        rank = jnp.cumsum(ties.astype(jnp.uint32)) - 1
        cand = jnp.where(ties & (rank == k), idx_n, n)
        rr_sel = cand.min().astype(jnp.int32)
        one_sel = jnp.argmax(fits).astype(jnp.int32)  # the single fitting node
        sel = jnp.where(fit_count == 0, jnp.int32(-1),
                        jnp.where(fit_count == 1, one_sel, rr_sel))
        ok = fit_count > 0
        counter = counter + jnp.where(fit_count > 1, jnp.uint32(1), jnp.uint32(0))
        safe_sel = jnp.where(ok, sel, 0)
        pd_new_sel = jnp.stack([n[safe_sel] for n in pd_new])  # [3]
        new_state = _commit(state, sel, ok, p_req, p_nonzero, p_ports,
                            p_vol_hard, p_vol_ro, p_pd_req, pd_new_sel)
        # affinity/spread carry: the committed pod's node-domain row joins
        # its class's occupancy (the on-device AssumePod for topology state)
        gain = ok.astype(jnp.int32)
        commdom = commdom.at[pc_i].add(labels[safe_sel].astype(jnp.int32)
                                       * gain)
        committed = committed.at[pc_i, safe_sel].add(gain)
        comm_cnt = comm_cnt.at[pc_i].add(gain)
        return (new_state, counter, commdom, committed, comm_cnt), \
            (sel, fit_count)

    xs = {"static": static_fit, "tt": tt_cnt, "na": na_cnt,
          "sscore": static_score, "req": pods["req"],
          "zero": pods["zero_req"], "nonzero": pods["nonzero"],
          "ports": pods["ports"], "vol_hard": pods["vol_hard"],
          "vol_ro": pods["vol_ro"], "pd_req": pods["pd_req"],
          "pd_count": pods["pd_req_count"], "pc": pc}
    if extra_score is not None:
        xs["extra"] = extra_score
    (state, rr_counter, _, _, _), (selected, fit_counts) = lax.scan(
        step, (state, rr_counter, commdom0, committed0, comm_cnt0), xs)
    return selected, fit_counts, state, rr_counter
