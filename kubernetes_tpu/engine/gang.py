"""Gang (coscheduling) placement: whole-group all-or-nothing assignment.

BASELINE.json config 4 — a NEW capability relative to the reference (the
only in-tree batching notion is the strictly-sequential one-pod loop,
SURVEY §2.3): pods carrying the group annotations

    scheduling.k8s.io/group-name          gang identity
    scheduling.k8s.io/group-min-available member quorum (default: observed)

schedule atomically. The device batch engine is the relaxation solver —
the wave kernel assigns the whole gang against evolving capacity in one
program — and the host wraps it in speculative-assume transactionality:

  1. a gang becomes ELIGIBLE only when >= min-available members are in
     the ready queue (the PodGroup quorum gate);
  2. the eligible members run through the normal engine with assume=True
     (wave or strict — the gang is just a batch);
  3. if EVERY member placed, the placements commit (bind as usual);
     otherwise the whole gang rolls back — every assumed member is
     forgotten and re-queued with backoff, leaving zero partial residue
     (no deadlock-by-fragment, the failure mode gang scheduling exists
     to prevent).

A fast total-capacity pre-check rejects obviously infeasible gangs
without touching the device: if the gang's aggregate cpu/memory demand
exceeds the cluster's aggregate free capacity, nothing can place it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod

GANG_NAME_ANNOTATION = "scheduling.k8s.io/group-name"
GANG_MIN_AVAILABLE_ANNOTATION = "scheduling.k8s.io/group-min-available"


def gang_name(pod: Pod) -> Optional[str]:
    return pod.annotations.get(GANG_NAME_ANNOTATION)


def min_available(pods: Sequence[Pod]) -> int:
    """The gang's quorum: max annotated value, else full observed size."""
    best = 0
    for p in pods:
        raw = p.annotations.get(GANG_MIN_AVAILABLE_ANNOTATION)
        if raw:
            try:
                best = max(best, int(raw))
            except ValueError:
                pass
    return best or len(pods)


def partition(pods: Sequence[Pod]) -> Tuple[List[Pod], Dict[str, List[Pod]]]:
    """(plain pods, gang-name -> members) preserving FIFO order."""
    plain: List[Pod] = []
    gangs: Dict[str, List[Pod]] = {}
    for p in pods:
        g = gang_name(p)
        if g is None:
            plain.append(p)
        else:
            gangs.setdefault(g, []).append(p)
    return plain, gangs


def capacity_precheck(members: Sequence[Pod], infos) -> bool:
    """Cheap aggregate feasibility: total gang cpu/mem demand must fit the
    cluster's total free capacity (necessary, not sufficient). False =
    provably unplaceable, skip the device entirely."""
    need_cpu = need_mem = 0
    for p in members:
        r = p.resource_request()
        need_cpu += r.milli_cpu
        need_mem += r.memory
    free_cpu = free_mem = 0
    for info in infos.values():
        node = info.node
        if node is None or not node.is_ready() or node.unschedulable:
            continue
        free_cpu += max(node.allocatable.milli_cpu
                        - info.requested.milli_cpu, 0)
        free_mem += max(node.allocatable.memory - info.requested.memory, 0)
    return need_cpu <= free_cpu and need_mem <= free_mem


class GangResult:
    __slots__ = ("name", "placed", "placed_members", "unplaced_members",
                 "reason")

    def __init__(self, name: str, placed: bool,
                 placed_members: List[Pod], unplaced_members: List[Pod],
                 reason: str = ""):
        self.name = name
        self.placed = placed  # quorum reached, placed_members commit
        self.placed_members = placed_members
        self.unplaced_members = unplaced_members
        self.reason = reason


def schedule_gangs(engine, ready: List[Tuple[str, List[Pod], int]],
                   mode: str = "wave") -> List[GangResult]:
    """Atomic placement of MANY gangs in ONE device pass: members run
    through the engine as a single FIFO batch (a per-gang dispatch would
    pay a device round trip per job), then each gang commits or rolls
    back independently. A rolled-back gang only FREES capacity later
    gangs already accounted for, so surviving placements stay valid —
    they saw a conservative (smaller) cluster.

    Quorum semantics (the coscheduling PodGroup contract): a gang COMMITS
    when at least `quorum` members placed — those bind, the rest re-queue
    and retry individually (the gang is past its atomicity point). Below
    quorum the whole gang rolls back to zero residue.

    Atomicity covers PLACEMENT (assumed capacity). Binds are per-pod API
    writes, as in the reference; a bind failure after commit is a
    per-member retry, not a gang rollback — the caller marks the gang
    degraded so retries bypass quorum gating instead of parking forever."""
    results: List[GangResult] = []
    infos = engine.cache.node_infos()
    batched: List[Tuple[str, List[Pod], int]] = []
    members_all: List[Pod] = []
    for name, members, quorum in ready:
        if not capacity_precheck(members, infos):
            results.append(GangResult(name, False, [], members,
                                      "InsufficientClusterCapacity"))
            continue
        batched.append((name, members, quorum))
        members_all.extend(members)
    if not members_all:
        return results
    placed = engine.schedule(members_all, assume=True, mode=mode)
    by_pod = {r.pod.key(): r for r in placed}
    for name, members, quorum in batched:
        rs = [by_pod[m.key()] for m in members]
        ok = [r for r in rs if r.node_name is not None]
        unplaced = [r.pod for r in rs if r.node_name is None]
        if len(ok) >= quorum:
            results.append(GangResult(
                name, True, [r.pod for r in ok], unplaced,
                "" if not unplaced else
                f"{len(unplaced)} stragglers past quorum retry solo"))
            continue
        # below quorum: rollback to zero residue (scheduler.go:234's
        # ForgetPod, applied transactionally across the group — ONE lock
        # for the whole gang via the cache's bulk rollback)
        engine.cache.forget_pods_bulk([r.pod for r in ok])
        for r in ok:
            engine.note_node_dirty(r.pod.node_name)
            r.pod.node_name = ""
        results.append(GangResult(
            name, False, [], members,
            f"only {len(ok)}/{len(members)} members placeable "
            f"(quorum {quorum})"))
    return results
