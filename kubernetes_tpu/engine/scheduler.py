"""The scheduler daemon: informer sync -> queue -> batch engine -> bind.

Structural mirror of the reference's scheduler loop
(plugin/pkg/scheduler/scheduler.go:149 Run / :253 scheduleOne and the
factory's informer wiring, factory.go:120-601), TPU-batched: instead of a
single-goroutine one-pod loop, each round drains the ready queue and places
the whole batch in one device program (engine/batch.py), then binds each
placement through the apiserver. Error paths preserved:

- no fitting node -> FailedScheduling event + backoff requeue
  (scheduler.go:174-181; factory.go:897 MakeDefaultErrorFunc)
- bind Conflict/NotFound -> ForgetPod + backoff requeue (scheduler.go:234-249)
- bind success -> FinishBinding starts the assumed-pod TTL; the watch-stream
  confirmation (MODIFIED pod with node_name) calls cache.AddPod
  (cache.go:130,214), closing the optimistic-concurrency loop.

Watch handling mirrors client-go reflector semantics: initial List+Watch from
the returned resourceVersion; TooOldResourceVersion -> full relist rebuild.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Binding, Event, Node, Pod
from kubernetes_tpu.api.workloads import to_workload_object
from kubernetes_tpu.engine import gang as gangmod
from kubernetes_tpu.engine.queue import SchedulingQueue
from kubernetes_tpu.engine.scheduler_engine import (
    PlacementResult,
    SchedulingEngine,
)
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    NotFound,
    TooOldResourceVersion,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.utils import features
from kubernetes_tpu.utils.metrics import SchedulerMetrics
from kubernetes_tpu.utils.trace import SCHEDULE_TRACE_THRESHOLD_S, Trace

DEFAULT_SCHEDULER_NAME = "default-scheduler"


class Scheduler:
    def __init__(self, api: ApiServerLite,
                 scheduler_name: str = DEFAULT_SCHEDULER_NAME,
                 priorities: Tuple[Tuple[str, int], ...] = prio.DEFAULT_PRIORITIES,
                 assumed_ttl: float = 30.0,
                 record_events: bool = True,
                 batch_mode: str = "wave",
                 policy=None,
                 now=time.monotonic):
        self.api = api
        self.scheduler_name = scheduler_name
        # "wave" = wave-parallel throughput mode (engine/waves.py, default);
        # "strict" = bit-exact sequential scheduleOne parity (engine/batch.py)
        self.batch_mode = batch_mode
        self._now = now
        self.cache = SchedulerCache(ttl_seconds=assumed_ttl, now=now)
        # Service/RC/RS/StatefulSet mirror for spreading & service affinity —
        # the extra informers of factory.go:120-140
        self._workloads: Dict[str, object] = {}
        # --policy-config-file (factory.go:619 CreateFromConfig): priority
        # set + parameterized algorithm args come from the Policy when given
        self._policy_algos = None
        if policy is not None:
            from kubernetes_tpu.ops.policy_algos import algorithms_from_policy
            kernel_prios, self._policy_algos = algorithms_from_policy(policy)
            if policy.priorities is not None:
                priorities = kernel_prios
        self.engine = SchedulingEngine(
            self.cache, priorities=priorities,
            workloads_provider=lambda: list(self._workloads.values()),
            policy_algos=self._policy_algos)
        self.queue = SchedulingQueue(now=now)
        self.metrics = SchedulerMetrics()
        self.record_events = record_events
        self.events: List[Event] = []
        # gangs parked below quorum: name -> {pod key: pod} (engine/gang.py)
        self._gang_waiting: Dict[str, Dict[str, Pod]] = {}
        # gangs whose quorum committed: members now schedule individually
        # (insertion-ordered; trimmed so unbounded gang churn can't leak)
        self._gang_degraded: Dict[str, None] = {}
        self._gang_parked_at: Dict[str, float] = {}
        self._rv = 0
        self._pods: Dict[str, Pod] = {}  # last-seen apiserver pod state
        # pod key -> wall-clock instant first seen unscheduled: the start
        # of the honest create->bound latency (always time.monotonic, even
        # when self._now is a fake test clock — latency is wall time)
        self._first_queued: Dict[str, float] = {}
        self._started = False

    # ------------------------------------------------------------ lifecycle

    WORKLOAD_KINDS = ("Service", "ReplicationController", "ReplicaSet",
                      "StatefulSet")
    VOLUME_KINDS = ("PersistentVolume", "PersistentVolumeClaim")

    def start(self) -> None:
        """Initial List (reflector handshake): nodes + pods into cache/queue."""
        nodes, _ = self.api.list("Node")
        for n in nodes:
            self.cache.add_node(n)
        for kind in self.WORKLOAD_KINDS:
            for w in self.api.list(kind)[0]:
                self._workloads[kind + "/" + getattr(w, "namespace", "")
                                + "/" + w.name] = to_workload_object(kind, w)
        vctx = self.engine.volume_ctx
        for pv in self.api.list("PersistentVolume")[0]:
            vctx.pvs[pv.name] = pv
        for pvc in self.api.list("PersistentVolumeClaim")[0]:
            vctx.pvcs[(pvc.namespace, pvc.name)] = pvc
        vctx.version += 1
        pods, rv = self.api.list("Pod")
        listed_at = time.monotonic()  # one instant for the whole List —
        # 30k per-pod clock reads would be pure accounting overhead
        for p in pods:
            self._pods[p.key()] = p
            if p.node_name:
                self.cache.add_pod(p)
            elif self._responsible_for(p):
                self._first_queued.setdefault(p.key(), listed_at)
                self.queue.add(dataclasses.replace(p))
        self._rv = rv
        self._started = True

    def sync(self, wait: float = 0.0) -> int:
        """Drain watch events into cache + queue (the informer event handlers
        of factory.go:188-260). Returns number of events processed."""
        if not self._started:
            self.start()
            return 0
        try:
            events = self.api.watch_since(
                ("Pod", "Node") + self.WORKLOAD_KINDS + self.VOLUME_KINDS,
                self._rv, timeout=wait)
        except TooOldResourceVersion:
            self._relist()
            return 0
        for ev in events:
            self._rv = ev.rv
            if ev.kind == "Node":
                self._on_node_event(ev.type, ev.obj)
            elif ev.kind == "Pod":
                self._on_pod_event(ev.type, ev.obj)
            elif ev.kind in self.VOLUME_KINDS:
                self._on_volume_event(ev.kind, ev.type, ev.obj)
            else:
                key = (ev.kind + "/" + getattr(ev.obj, "namespace", "")
                       + "/" + ev.obj.name)
                if ev.type == "DELETED":
                    self._workloads.pop(key, None)
                else:
                    self._workloads[key] = to_workload_object(ev.kind, ev.obj)
        return len(events)

    # ------------------------------------------------------------ scheduling

    def schedule_round(self, max_batch: int = 0, wait: float = 0.0) -> Dict[str, int]:
        """One batch round: pop ready pods, place on device, bind. Mirrors
        scheduleOne (scheduler.go:253) over a whole batch, wrapped in a
        slow-schedule trace (generic_scheduler.go:89-90's 100ms utiltrace)."""
        trace = Trace("Scheduling round")
        self.sync()
        trace.step("informer sync done")
        pods = self.queue.pop_batch(max_n=max_batch, wait=wait)
        pop_ts = time.monotonic()  # NextPod-pop instant (scheduler.go:289)
        stats = {"popped": len(pods), "bound": 0, "unschedulable": 0,
                 "bind_errors": 0, "preemptions": 0}
        # gang (coscheduling) gating: pods in a group schedule atomically
        # once their quorum is in the queue (engine/gang.py); incomplete
        # gangs park in _gang_waiting until members arrive
        plain, gangs = gangmod.partition(pods)
        # parked-too-long gangs surface even on empty rounds — a gang below
        # quorum with no new arrivals would otherwise never reach the sweep
        # (quorum may never come: members deleted, minAvailable typo);
        # members re-queue with backoff — retried AND visible via events.
        # A gang receiving members THIS round is exempt: the arrival may
        # complete its quorum below, and evicting it first would turn an
        # on-time completion into a spurious backoff cycle.
        now = self._now()
        for gname in [g for g, t0_ in self._gang_parked_at.items()
                      if now - t0_ > self.GANG_WAIT_TIMEOUT_S
                      and g not in gangs]:
            waiting = self._gang_waiting.pop(gname, {})
            self._gang_parked_at.pop(gname, None)
            for m in waiting.values():
                self._event(m, "Warning", "FailedScheduling",
                            f"gang {gname} below quorum for "
                            f"{self.GANG_WAIT_TIMEOUT_S:.0f}s")
                self.queue.add_backoff(m)
        if not pods:
            self.cache.cleanup_assumed()
            self.queue.backoff.gc()
            return stats
        trace.field("pods", len(pods))
        ready_gangs = []
        for gname, members in gangs.items():
            if gname in self._gang_degraded:
                # past the gang's atomicity point (quorum already bound):
                # stragglers and bind-retries schedule individually instead
                # of parking below quorum forever
                plain.extend(members)
                continue
            waiting = self._gang_waiting.setdefault(gname, {})
            if gname not in self._gang_parked_at:
                self._gang_parked_at[gname] = self._now()
            for m in members:
                waiting[m.key()] = m
            quorum = gangmod.min_available(list(waiting.values()))
            if len(waiting) >= quorum:
                ready_gangs.append((gname, list(waiting.values()), quorum))
                del self._gang_waiting[gname]
                self._gang_parked_at.pop(gname, None)
        t0 = time.monotonic()
        scheduled_count = len(plain) + sum(len(m) for _g, m, _q in
                                           ready_gangs)
        results = []
        # ready gangs place FIRST: their members were necessarily queued at
        # or before this round's plain pods, and placing plain first would
        # let a sustained plain stream starve contended gangs (each retry
        # seeing capacity already consumed)
        if ready_gangs:
            for gr in gangmod.schedule_gangs(self.engine, ready_gangs,
                                             mode=self.batch_mode):
                if gr.placed:
                    # quorum committed: the gang is past its atomicity
                    # point — later members/retries go solo
                    self._mark_gang_degraded(gr.name)
                    results.extend(PlacementResult(m, m.node_name, 1)
                                   for m in gr.placed_members)
                unschedulable = gr.unplaced_members
                stats["unschedulable"] += len(unschedulable)
                if unschedulable:
                    self.metrics.failed.inc(len(unschedulable))
                for m in unschedulable:
                    self._event(m, "Warning", "FailedScheduling",
                                f"gang {gr.name}: {gr.reason}")
                    self.queue.add_backoff(
                        dataclasses.replace(m, node_name=""))
        if plain:
            results.extend(self.engine.schedule(plain, assume=True,
                                                mode=self.batch_mode))
        t_alg = time.monotonic() - t0
        trace.step("batch placement computed (device)")
        placed = []
        unschedulable_pods = []
        for r in results:
            if r.node_name is None:
                stats["unschedulable"] += 1
                self.metrics.failed.inc()
                self._event(r.pod, "Warning", "FailedScheduling",
                            f"0/{len(self.engine.snapshot.node_names)} nodes "
                            f"available (fit_count={r.fit_count})")
                unschedulable_pods.append(r.pod)
                self.queue.add_backoff(r.pod)
            else:
                placed.append(r)
        # one batched /binding pass (per-binding semantics identical to the
        # per-pod POST; scheduler.go:224-250 error paths preserved per pod)
        tb0 = time.monotonic()
        errs = self.api.bind_many(
            [Binding(r.pod.name, r.pod.namespace, r.pod.uid, r.node_name)
             for r in placed])
        bind_done = time.monotonic()
        t_bind = bind_done - tb0
        bound_pods = []
        for r, err in zip(placed, errs):
            if err is not None:
                # undo the optimistic assume (scheduler.go:234-245)
                stats["bind_errors"] += 1
                self.cache.forget_pod(r.pod)
                self._event(r.pod, "Warning", "FailedBinding", err)
                retry = dataclasses.replace(r.pod, node_name="")
                self.queue.add_backoff(retry)
                continue
            bound_pods.append(r.pod)
            stats["bound"] += 1
            self._event(r.pod, "Normal", "Scheduled",
                        f"Successfully assigned {r.pod.key()} to {r.node_name}")
        trace.step("bindings written")
        self.cache.finish_bindings_bulk(bound_pods)
        if unschedulable_pods and features.enabled("PodPriority"):
            # after the binding pass, so a victim choice can never race a
            # not-yet-posted Binding from this same round
            stats["preemptions"] = self._preempt_round(unschedulable_pods)
        n = len(bound_pods)
        self.metrics.scheduled.inc(n)
        # honest spans (not amortized t/n): every pod in the batch really
        # waited the FULL algorithm span and the FULL binding span — its
        # placement was not done until the round's was. e2e matches the
        # reference's pop->bind-complete window (scheduler.go:289)
        self.metrics.algorithm_latency.observe_many(t_alg, n)
        self.metrics.binding_latency.observe_many(t_bind, n)
        self.metrics.e2e_latency.observe_many(bind_done - pop_ts, n)
        # per-pod create->bound, queue wait + backoff rounds included:
        # distinct value per pod, the distribution the SLO check reads
        self.metrics.create_to_bound.observe_batch(
            [bind_done - self._first_queued.pop(p.key(), pop_ts)
             for p in bound_pods])
        self.cache.cleanup_assumed()
        self.queue.backoff.gc()
        # per-pod amortized threshold: a 30k-pod round is not "slow" the way
        # a 30k-pod-long one-pod trace would be; scale like the reference's
        # per-Schedule-call threshold
        trace.log_if_long(SCHEDULE_TRACE_THRESHOLD_S
                          * max(scheduled_count, 1))
        return stats

    def _preempt_round(self, unschedulable: List[Pod]) -> int:
        """Preemption pass (1.8 generic_scheduler.Preempt, feature-gated
        behind PodPriority like kube_features.go:122): for each
        unschedulable pod, highest priority first, pick a node + minimal
        victim set (engine/preemption.py) and evict the victims. The
        preemptor is already requeued; once the victims' DELETED events
        drain through sync(), the freed capacity places it in a following
        round (the nominate-then-reschedule flow)."""
        from kubernetes_tpu.engine import preemption as preemptmod
        from kubernetes_tpu.ops.oracle_ext import SchedulingContext
        # clones: the victim bookkeeping below must not mutate the live
        # cache (the DELETED watch events do that authoritatively)
        infos = self.cache.snapshot_infos()
        # full predicate context: without it the feasibility check would
        # ignore inter-pod affinity / volumes / policy algorithms and
        # evict victims that free nothing for the preemptor. Victims stay
        # in ctx.infos during the check — conservative: a node whose
        # feasibility depends on a victim's own anti-affinity going away
        # is skipped rather than over-evicted.
        ctx = SchedulingContext(
            infos, self.engine.workloads_provider(),
            hard_pod_affinity_weight=self.engine.hard_pod_affinity_weight,
            volume_ctx=self.engine.volume_ctx,
            policy_algos=self.engine.policy_algos)
        count = 0
        # lazy: a round whose unschedulable pods are all priority 0 (the
        # default) must not pay the O(total pods) array build
        state = None
        for pod in sorted(unschedulable, key=lambda p: -p.priority):
            if pod.priority <= 0:
                break  # sorted desc: nothing below can preempt either
            if state is None:
                state = preemptmod.PreemptionState(infos)
            plan = preemptmod.pick_preemption(pod, infos, ctx=ctx,
                                              state=state)
            if plan is None:
                continue
            for vic in plan.victims:
                try:
                    self.api.delete("Pod", vic.namespace, vic.name)
                except NotFound:
                    pass
                self._event(vic, "Normal", "Preempted",
                            f"by {pod.key()} on node {plan.node_name}")
                # reflect the eviction in the local view immediately so a
                # second preemptor this round does not double-count the
                # same victims
                info = infos.get(plan.node_name)
                if info is not None:
                    info.remove_pod(vic)
            # reserve the freed capacity for THIS preemptor in the local
            # view (the 1.8 nominated-pod reservation): a second
            # preemptor this round must not plan into the same hole and
            # over-evict
            info = infos.get(plan.node_name)
            if info is not None:
                info.add_pod(pod)
            state.apply_plan(plan, pod)
            self._event(pod, "Normal", "TriggeredPreemption",
                        f"{len(plan.victims)} lower-priority pod(s) on "
                        f"{plan.node_name} evicted")
            count += 1
        return count

    def run_until_drained(self, max_rounds: int = 10_000,
                          max_batch: int = 0) -> Dict[str, int]:
        """Bench helper: rounds until queue is empty and no watch events."""
        total = {"popped": 0, "bound": 0, "unschedulable": 0,
                 "bind_errors": 0, "preemptions": 0}
        for _ in range(max_rounds):
            stats = self.schedule_round(max_batch=max_batch)
            for k in total:
                total[k] += stats[k]
            if stats["popped"] == 0 and self.sync() == 0 \
                    and self.queue.ready_count() == 0:
                break
        return total

    # ------------------------------------------------------------- handlers

    _GANG_DEGRADED_MAX = 10_000
    GANG_WAIT_TIMEOUT_S = 60.0  # parked-below-quorum visibility timeout

    def _mark_gang_degraded(self, name: str) -> None:
        # re-marking refreshes recency so an active gang's entry is never
        # the one evicted
        self._gang_degraded.pop(name, None)
        self._gang_degraded[name] = None
        while len(self._gang_degraded) > self._GANG_DEGRADED_MAX:
            self._gang_degraded.pop(next(iter(self._gang_degraded)))

    def _responsible_for(self, pod: Pod) -> bool:
        return (pod.scheduler_name or DEFAULT_SCHEDULER_NAME) == self.scheduler_name

    def _on_volume_event(self, kind: str, etype: str, obj) -> None:
        """PV/PVC informer handlers (factory.go:120-140 wires both; events
        invalidate the equivalence cache there — here they bump the
        VolumeContext version so the snapshot re-resolves PD rows)."""
        vctx = self.engine.volume_ctx
        if kind == "PersistentVolume":
            if etype == "DELETED":
                vctx.pvs.pop(obj.name, None)
            else:
                vctx.pvs[obj.name] = obj
        else:
            key = (obj.namespace, obj.name)
            if etype == "DELETED":
                vctx.pvcs.pop(key, None)
            else:
                vctx.pvcs[key] = obj
        vctx.version += 1

    def _on_node_event(self, etype: str, node: Node) -> None:
        if etype == "DELETED":
            self.cache.remove_node(node.name)
        else:
            self.cache.update_node(node)

    def _on_pod_event(self, etype: str, pod: Pod) -> None:
        key = pod.key()
        prev = self._pods.get(key)
        # any event invalidates a parked gang copy: the pod either left
        # (DELETED/bound) or changed spec — it re-enters via the queue and
        # re-partitions fresh, never schedules from a stale parked object
        for waiting in self._gang_waiting.values():
            waiting.pop(key, None)
        if etype == "DELETED":
            self._pods.pop(key, None)
            self._first_queued.pop(key, None)
            self.queue.remove(key)
            if prev is not None and prev.node_name:
                self.cache.remove_pod(prev)
            return
        self._pods[key] = pod
        if etype == "ADDED":
            if pod.node_name:
                self.cache.add_pod(pod)
            elif self._responsible_for(pod):
                self._first_queued.setdefault(key, time.monotonic())
                self.queue.add(dataclasses.replace(pod))
            return
        # MODIFIED
        was_bound = prev is not None and bool(prev.node_name)
        if not was_bound and pod.node_name:
            self.queue.remove(key)
            self._first_queued.pop(key, None)  # bound (possibly by a
            # foreign scheduler); our own binds already harvested it
            self.cache.add_pod(pod)  # confirms our assume, or records a
            # foreign scheduler's bind (cache.go:214)
        elif was_bound and pod.node_name:
            self.cache.update_pod(prev, pod)
        elif was_bound and not pod.node_name:
            self.cache.remove_pod(prev)
            if self._responsible_for(pod):
                self._first_queued.setdefault(key, time.monotonic())
                self.queue.add(dataclasses.replace(pod))
        else:
            self.queue.remove(key)
            if self._responsible_for(pod):
                self._first_queued.setdefault(key, time.monotonic())
                self.queue.add(dataclasses.replace(pod))

    def _relist(self) -> None:
        """Watch fell behind the event log — rebuild everything from a fresh
        List, like a reflector restart. Assumed pods still pending
        confirmation are preserved by re-adding only confirmed state."""
        self.cache = SchedulerCache(ttl_seconds=self.cache._ttl, now=self._now)
        self._workloads = {}
        self.engine = SchedulingEngine(
            self.cache, priorities=self.engine.priorities,
            workloads_provider=lambda: list(self._workloads.values()),
            policy_algos=self._policy_algos)
        self.queue = SchedulingQueue(now=self._now)
        self._pods = {}
        self._gang_waiting = {}
        self._gang_degraded = {}
        self._gang_parked_at = {}
        self._started = False
        self.start()
        # prune create->bound stamps for pods that bound or vanished
        # during the watch blackout (their terminal event is exactly what
        # the log compaction lost) — a stale stamp would otherwise inflate
        # a later reschedule's sample, or leak forever
        self._first_queued = {
            k: t for k, t in self._first_queued.items()
            if k in self._pods and not self._pods[k].node_name}

    def _event(self, pod: Pod, etype: str, reason: str, message: str) -> None:
        if not self.record_events:
            return
        self.events.append(Event(pod.key(), reason, message, etype))
