"""The scheduler daemon: informer sync -> queue -> batch engine -> bind.

Structural mirror of the reference's scheduler loop
(plugin/pkg/scheduler/scheduler.go:149 Run / :253 scheduleOne and the
factory's informer wiring, factory.go:120-601), TPU-batched: instead of a
single-goroutine one-pod loop, each round drains the ready queue and places
the whole batch in one device program (engine/batch.py), then binds each
placement through the apiserver.

Two drain modes:

- schedule_round: the classic SYNCHRONOUS round (device placement blocks
  before host bookkeeping); still the path for gangs, preemption, policy
  algorithms, and any batch the wave engine can't take.
- run_until_drained / pipeline() / stream(): the continuously-running
  scheduler loop (engine/streaming.py ScheduleLoop, ISSUE 7) — the
  pipelined drain of ISSUE 2 is its fixed-chunk mode, and stream() is
  the always-on mode that admits MICRO-WAVES on a latency budget —
  wave k+1's fused device eval is dispatched (JAX async) before wave k's
  device→host sync, so assume/bind/watch-drain of wave k overlap device
  time of wave k+1. Wave k+1 is therefore encoded blind to wave k's
  commits; harvest re-validates against post-k occupancy (the fence in
  engine/scheduler_engine.harvest_waves) and capacity losers requeue —
  the same optimistic-concurrency shape as assume/expire. Host phases are
  columnar: the watch drain batches bind confirmations, assumes are
  grouped per (node, class), binds go through one bulk write, and the
  snapshot refresh rides the changed_hint / raw-delta fast paths.
  Required (anti-)affinity chunks are wave-eligible since ISSUE 3: the
  engine evaluates their masks per wave from device-resident topology
  occupancy, routes counter-inexpressible shapes to a seeded strict tail
  inside the harvest (a conflict-round loop since ISSUE 5), and the
  fence re-validates topology occupancy the same way it re-validates
  capacity. Quorum-ready GANGS are wave-eligible since ISSUE 5: they
  dispatch as ordinary batch rows and the harvest applies an
  all-or-nothing gang fence — below quorum, every member is dropped
  BEFORE anything is assumed (atomic rollback, zero residue) and
  requeues with backoff. Only Policy algorithms, workload spreading,
  and host-check/slot-overflow classes still flush to the classic
  round.

Error paths preserved:

- no fitting node -> FailedScheduling event + backoff requeue
  (scheduler.go:174-181; factory.go:897 MakeDefaultErrorFunc)
- bind Conflict/NotFound -> ForgetPod + backoff requeue (scheduler.go:234-249)
- bind success -> FinishBinding starts the assumed-pod TTL; the watch-stream
  confirmation (MODIFIED pod with node_name) calls cache.AddPod
  (cache.go:130,214), closing the optimistic-concurrency loop.

Watch handling mirrors client-go reflector semantics: initial List+Watch from
the returned resourceVersion; TooOldResourceVersion -> full relist rebuild.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Binding, Event, Node, Pod
from kubernetes_tpu.api.workloads import to_workload_object
from kubernetes_tpu.engine import gang as gangmod
from kubernetes_tpu.engine.preempt_wave import (
    DisruptionBudget,
    plan_wave_preemptions,
)
from kubernetes_tpu.engine.queue import SchedulingQueue
from kubernetes_tpu.engine.scheduler_engine import (
    PlacementResult,
    SchedulingEngine,
)
from kubernetes_tpu.engine.streaming import ScheduleLoop
from kubernetes_tpu.observability import podtrace
from kubernetes_tpu.observability import recorder as flightrec
from kubernetes_tpu.observability.podtrace import TRACER
from kubernetes_tpu.observability.recorder import RECORDER
from kubernetes_tpu.observability.registry import TelemetryRegistry
from kubernetes_tpu.observability.slo import SLO
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    NotFound,
    TooOldResourceVersion,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.utils import features
from kubernetes_tpu.utils.metrics import SchedulerMetrics
from kubernetes_tpu.utils.trace import SCHEDULE_TRACE_THRESHOLD_S, Trace

DEFAULT_SCHEDULER_NAME = "default-scheduler"


def _queue_copy(pod: Pod) -> Pod:
    """Shallow queue-admission copy — the isolation dataclasses.replace
    gave (both are shallow) at a fraction of the construction cost, which
    the 20k+/s arrival path pays per pod. The Pod.key memo travels
    deliberately (name/namespace are immutable identity), but the CLASS-
    KEY memo is dropped so the state/classes.py contract stays intact:
    spec mutations on one object can never carry a stale class key onto
    another across the watch→queue hop."""
    c = copy.copy(pod)
    c.__dict__.pop("_class_key", None)
    return c


class Scheduler:
    def __init__(self, api: ApiServerLite,
                 scheduler_name: str = DEFAULT_SCHEDULER_NAME,
                 priorities: Tuple[Tuple[str, int], ...] = prio.DEFAULT_PRIORITIES,
                 assumed_ttl: float = 30.0,
                 record_events: bool = True,
                 batch_mode: str = "wave",
                 policy=None,
                 now=time.monotonic,
                 mesh=None):
        self.api = api
        self.scheduler_name = scheduler_name
        # "wave" = wave-parallel throughput mode (engine/waves.py, default);
        # "strict" = bit-exact sequential scheduleOne parity (engine/batch.py)
        self.batch_mode = batch_mode
        self._now = now
        self.cache = SchedulerCache(ttl_seconds=assumed_ttl, now=now)
        # Service/RC/RS/StatefulSet mirror for spreading & service affinity —
        # the extra informers of factory.go:120-140
        self._workloads: Dict[str, object] = {}
        # --policy-config-file (factory.go:619 CreateFromConfig): priority
        # set + parameterized algorithm args come from the Policy when given
        self._policy_algos = None
        if policy is not None:
            from kubernetes_tpu.ops.policy_algos import algorithms_from_policy
            kernel_prios, self._policy_algos = algorithms_from_policy(policy)
            if policy.priorities is not None:
                priorities = kernel_prios
        # mesh (ISSUE 12): a 1-D node-axis jax.sharding.Mesh makes every
        # node-indexed device tensor RESIDENT-SHARDED across its devices
        # and routes waves_loop through the two-stage SPMD reduce;
        # placements stay bit-identical to the unsharded engine
        self.engine = SchedulingEngine(
            self.cache, priorities=priorities,
            workloads_provider=lambda: list(self._workloads.values()),
            policy_algos=self._policy_algos, mesh=mesh)
        # this Scheduler owns its cache exclusively and routes every
        # mutation through the engine's dirty notes, so refreshes may take
        # the targeted changed_hint path instead of walking all N nodes
        self.engine.track_dirty = True
        self.queue = SchedulingQueue(now=now)
        # pipelined drain knobs (run_until_drained/run_arrival): chunk =
        # pods per wave (double-buffered), set by PIPELINE_CHUNK-style
        # callers; _pipeline is the live pipeline whose in-flight wave a
        # capacity-unsafe watch event must flush before applying
        self.pipeline_chunk = 4096
        self._pipeline = None
        # gangs ride the pipelined wave path (ISSUE 5): quorum-eligible
        # gangs dispatch as ordinary wave batches with an all-or-nothing
        # gang fence at harvest. False restores the r07/r08 behavior —
        # every gang-bearing chunk flushes the pipeline into the classic
        # synchronous round — kept reachable as the A/B baseline
        # (bench.measure_gang_mix flips this attribute for the
        # gangmix_flush_elapsed_s measurement).
        self.gang_pipeline = True
        # wave-path preemption (ISSUE 14): with the PodPriority gate on,
        # a harvest's unschedulable preemptors plan displacements against
        # the snapshot's priority bands and commit through the store's
        # ATOMIC evict+bind — the pipeline never flushes for priority.
        # False keeps the classic nominate-then-reschedule rounds as the
        # only preemption path (and run_until_drained's auto-select
        # still routes PodPriority drains classic regardless).
        self.wave_preemption = True
        # PodDisruptionBudget-shaped eviction rate limit: sliding
        # max-evictions-per-minute window plus optional per-band floors;
        # denied plans count budget_deferred and wait out their backoff.
        self.disruption_budget = DisruptionBudget(now=now)
        # bench hook: preempt_observer(commit_monotonic, latency_s,
        # victim_count) after every committed preemption. None = off.
        self.preempt_observer = None
        self.metrics = SchedulerMetrics()
        # unified telemetry (ISSUE 13): this scheduler's histograms +
        # counters in the one labeled namespace; a live ScheduleLoop
        # registers its stream gauges (quantum/backlog/degraded) here
        self.telemetry = TelemetryRegistry()
        self.telemetry.register_metrics("scheduler", self.metrics)
        self.record_events = record_events
        self.events: List[Event] = []
        # per-wave bind telemetry for loop owners (bench.run_arrival's
        # honest create->bound accounting): called as
        # wave_observer(bind_done_monotonic, bound_pod_keys) after every
        # successful bulk bind — classic rounds and pipelined harvests
        # alike — so a scenario can join bind instants against its own
        # creation stamps without touching scheduler internals. None = off.
        self.wave_observer = None
        # federation spill hook (ISSUE 20): when set, a pod whose
        # unschedulable verdicts reach spill_after_attempts LEAVES this
        # cell — handed to spill_handler(pods) instead of backoff-
        # requeued, so the front-door router can re-admit it to a
        # sibling cell with spare capacity (PAPERS.md §Borg spillover).
        # Gang members never spill individually: gangs route whole-cell
        # and their below-quorum retries stay on the backoff path. None
        # (the default) keeps single-cell behavior bit-identical.
        self.spill_handler = None
        self.spill_after_attempts = 3
        self._unsched_attempts: Dict[str, int] = {}
        # gangs parked below quorum: name -> {pod key: pod} (engine/gang.py)
        self._gang_waiting: Dict[str, Dict[str, Pod]] = {}
        # gangs whose quorum committed: members now schedule individually
        # (insertion-ordered; trimmed so unbounded gang churn can't leak)
        self._gang_degraded: Dict[str, None] = {}
        self._gang_parked_at: Dict[str, float] = {}
        self._rv = 0
        self._pods: Dict[str, Pod] = {}  # last-seen apiserver pod state
        # pod key -> wall-clock instant first seen unscheduled: the start
        # of the honest create->bound latency (always time.monotonic, even
        # when self._now is a fake test clock — latency is wall time)
        self._first_queued: Dict[str, float] = {}
        self._started = False

    # ------------------------------------------------------------ lifecycle

    WORKLOAD_KINDS = ("Service", "ReplicationController", "ReplicaSet",
                      "StatefulSet")
    VOLUME_KINDS = ("PersistentVolume", "PersistentVolumeClaim")

    def start(self) -> None:
        """Initial List (reflector handshake): nodes + pods into cache/queue."""
        nodes, _ = self.api.list("Node")
        for n in nodes:
            self.cache.add_node(n)
        for kind in self.WORKLOAD_KINDS:
            for w in self.api.list(kind)[0]:
                self._workloads[kind + "/" + getattr(w, "namespace", "")
                                + "/" + w.name] = to_workload_object(kind, w)
        vctx = self.engine.volume_ctx
        for pv in self.api.list("PersistentVolume")[0]:
            vctx.pvs[pv.name] = pv
        for pvc in self.api.list("PersistentVolumeClaim")[0]:
            vctx.pvcs[(pvc.namespace, pvc.name)] = pvc
        vctx.version += 1
        pods, rv = self.api.list("Pod")
        listed_at = time.monotonic()  # one instant for the whole List —
        # 30k per-pod clock reads would be pure accounting overhead
        for p in pods:
            self._pods[p.key()] = p
            if p.node_name:
                self.cache.add_pod(p)
            elif self._responsible_for(p):
                self._first_queued.setdefault(p.key(), listed_at)
                self.queue.add(_queue_copy(p))
        self._rv = rv
        self._started = True

    def sync(self, wait: float = 0.0) -> int:
        """Drain watch events into cache + queue (the informer event handlers
        of factory.go:188-260). Returns number of events processed.

        Columnar drain: a bind storm's confirmation events (MODIFIED pod,
        unbound -> bound — 30k of them per headline round) batch into ONE
        queue sweep + ONE cache lock pass, and an ARRIVAL storm's fresh
        unbound pods (ADDED, no node — 20k+/s offered under the always-on
        loop, ISSUE 7) batch into ONE queue admission, instead of a
        per-event dispatch loop. Events that can invalidate an in-flight
        pipelined wave's static assumptions (node spec/membership, PV/PVC)
        flush the pipeline BEFORE being applied, so the wave's fence only
        ever needs the capacity re-check."""
        if not self._started:
            self.start()
            return 0
        try:
            events = self.api.watch_since(
                ("Pod", "Node") + self.WORKLOAD_KINDS + self.VOLUME_KINDS,
                self._rv, timeout=wait)
        except TooOldResourceVersion:
            self._interrupt_pipeline()  # the in-flight wave belongs to the
            # pre-relist engine; harvest it against that state first
            self._relist()
            return 0
        if not events:
            return 0
        confirms: List[Pod] = []
        fresh: List[Pod] = []  # ADDED unbound pods we are responsible for:
        # admitted columnar (one queue lock), flushed BEFORE confirms at
        # every flush point so an add->bind pair inside one batch lands in
        # event order
        buffered: Dict[str, Pod] = {}  # key -> newest BUFFERED pod: the
        # confirm gate must see pods buffered earlier in this batch, but
        # self._pods only updates at flush so a mid-batch exception leaves
        # it consistent with what was actually applied
        simple_ok = not self._gang_waiting
        pods_map = self._pods
        # the cursor advances per PROCESSED event via a cheap local (an
        # attribute store per event is measurable at 30k confirmations per
        # round): buffered-but-unflushed confirms do NOT advance it, so a
        # handler exception mid-batch rolls the cursor back to the last
        # applied event and a retried sync() re-fetches the rest —
        # re-applying a flushed confirm is idempotent, skipping one is not
        last_rv = self._rv
        try:
            for ev in events:
                kind = ev.kind
                obj = ev.obj
                if simple_ok and kind == "Pod" and ev.type == "MODIFIED" \
                        and obj.node_name:
                    key = obj.key()
                    prev = buffered.get(key)
                    if prev is None:
                        prev = pods_map.get(key)
                    if prev is not None and not prev.node_name:
                        # unbound -> bound: a bind confirmation (ours or a
                        # foreign scheduler's). Capacity effects are noted
                        # by the bulk flush; no in-flight flush needed.
                        buffered[key] = obj
                        confirms.append(obj)
                        continue
                if simple_ok and kind == "Pod" and ev.type == "ADDED" \
                        and not obj.node_name \
                        and self._responsible_for(obj):
                    # fresh pending pod (the arrival-storm shape): buffer
                    # for one columnar queue admission. Mirrors
                    # _on_pod_event's ADDED-unbound branch exactly.
                    buffered[obj.key()] = obj
                    fresh.append(obj)
                    continue
                # slow path: apply buffered fresh adds then confirms FIRST
                # (per-pod event order preserved — a fresh add and its own
                # bind confirmation can only appear in that order without
                # a slow event between them), then dispatch the handler
                if fresh or confirms:
                    self._flush_fresh(fresh)
                    if confirms:
                        self._flush_confirms(confirms, buffered)
                    last_rv = ev.rv - 1
                if kind == "Pod":
                    self._on_pod_event(ev.type, obj)
                elif kind == "Node":
                    # liveness fence (ISSUE 8): a dying node (deletion,
                    # cordon, NotReady flap) is marked doomed BEFORE any
                    # pipeline flush, so a wave harvested against the
                    # pre-event cache requeues rows targeting it instead
                    # of binding into a ghost. Cleared after the event
                    # applies: the refreshed snapshot then carries the
                    # verdict itself.
                    dying = (ev.type == "DELETED" or obj.unschedulable
                             or not obj.is_ready())
                    if dying:
                        self.engine.note_node_doomed(obj.name)
                    if self._node_event_needs_flush(ev.type, obj):
                        self._interrupt_pipeline()
                    self._on_node_event(ev.type, obj)
                    if dying:
                        self.engine.clear_node_doomed(obj.name)
                elif kind in self.VOLUME_KINDS:
                    self._interrupt_pipeline()
                    self._on_volume_event(kind, ev.type, obj)
                else:
                    key = (kind + "/" + getattr(obj, "namespace", "")
                           + "/" + obj.name)
                    if ev.type == "DELETED":
                        self._workloads.pop(key, None)
                    else:
                        self._workloads[key] = to_workload_object(kind, obj)
                last_rv = ev.rv
            self._flush_fresh(fresh)
            if confirms:
                self._flush_confirms(confirms, buffered)
            self._rv = events[-1].rv
        except BaseException:
            self._rv = last_rv
            raise
        return len(events)

    def sync_pods_sip(self) -> int:
        """Drain ONLY the leading run of simple pod events — fresh
        pending ADDs and bind confirmations — from the watch stream: the
        fast lane's poll-during-harvest sip (ISSUE 17). While the
        streaming loop blocks on a wave's device array, this lets newly
        created latency-critical pods reach the queue WITHOUT running a
        full sync(): the first event the columnar fast paths can't
        absorb (node, volume, workload, deletes, spec mods) stops the
        sip with the cursor parked BEFORE it, so the next full sync()
        applies it in order — a sip can therefore never flush the
        pipeline or reorder harvests. Idempotency mirrors sync(): the
        cursor only advances after the flush lands, and re-applying a
        flushed run is safe."""
        if not self._started or self._gang_waiting:
            return 0
        try:
            events = self.api.watch_since(
                ("Pod", "Node") + self.WORKLOAD_KINDS + self.VOLUME_KINDS,
                self._rv, timeout=0.0)
        except TooOldResourceVersion:
            return 0  # the next full sync() owns the relist
        if not events:
            return 0
        confirms: List[Pod] = []
        fresh: List[Pod] = []
        buffered: Dict[str, Pod] = {}
        pods_map = self._pods
        last_rv = self._rv
        for ev in events:
            if ev.kind != "Pod":
                break
            obj = ev.obj
            if ev.type == "MODIFIED" and obj.node_name:
                key = obj.key()
                prev = buffered.get(key)
                if prev is None:
                    prev = pods_map.get(key)
                if prev is not None and not prev.node_name:
                    buffered[key] = obj
                    confirms.append(obj)
                    last_rv = ev.rv
                    continue
                break
            if ev.type == "ADDED" and not obj.node_name \
                    and self._responsible_for(obj):
                buffered[obj.key()] = obj
                fresh.append(obj)
                last_rv = ev.rv
                continue
            break
        applied = len(fresh) + len(confirms)
        if not applied:
            return 0
        self._flush_fresh(fresh)
        if confirms:
            self._flush_confirms(confirms, buffered)
        self._rv = last_rv  # advanced only past APPLIED events
        return applied

    def _flush_fresh(self, fresh: List[Pod]) -> None:
        """Admit a run of fresh pending pods columnar: one bookkeeping
        pass, one queue lock (queue.add_many). Per-pod semantics identical
        to _on_pod_event's ADDED-unbound branch; the queue copies are
        shallow (copy.copy), which also carries the Pod.key/_class_key
        memos forward instead of re-deriving them per admission.
        Idempotent per pod (queue dedup + setdefault), so a retried sync()
        may safely re-apply. One clock read for the whole run: the stamps
        feed the metrics distribution, and sync() runs per wave — finer
        granularity than the sync cadence would be fiction anyway (the
        bench's honest latency joins against the CREATOR's stamps)."""
        if not fresh:
            return
        now = time.monotonic()
        pods_map = self._pods
        fq = self._first_queued
        copies = []
        for p in fresh:
            k = p.key()
            pods_map[k] = p
            if k not in fq:
                fq[k] = now
            copies.append(_queue_copy(p))
        self.queue.add_many(copies)
        fresh.clear()

    def _flush_confirms(self, confirms: List[Pod],
                        buffered: Dict[str, Pod]) -> None:
        """Apply a run of bind confirmations columnar: one queue sweep, one
        cache lock, one bookkeeping pass. Per-pod semantics identical to
        _on_pod_event's unbound->bound branch, order preserved per pod.
        Idempotent per pod, so a retried sync() may safely re-apply."""
        keys = [p.key() for p in confirms]
        self.queue.remove_many(keys)
        touched = self.cache.add_pods_bulk(confirms)
        if touched:  # foreign binds / moves mutated NodeInfos
            self.engine.note_node_dirty(*touched)
        pods_map = self._pods
        fq = self._first_queued
        for k, p in zip(keys, confirms):
            pods_map[k] = p
            fq.pop(k, None)
        confirms.clear()
        buffered.clear()

    def _interrupt_pipeline(self) -> None:
        """Harvest any in-flight pipelined wave NOW — called before applying
        a watch event the wave's capacity fence cannot re-validate (node
        spec/membership, volume topology)."""
        if self._pipeline is not None:
            self._pipeline.flush()

    def _node_event_needs_flush(self, etype: str, node: Node) -> bool:
        """Does this node event invalidate anything the in-flight wave's
        fence cannot re-validate? (ISSUE 8: flushing per event was ~all of
        the churn throughput collapse — at 10%/min on 5k nodes the
        pipeline never kept two waves in flight.)

        LIVENESS-ONLY transitions don't need the flush anymore: rows
        targeting a dead/cordoned/NotReady node are caught by the fence's
        liveness re-validation (doomed set + refreshed schedulable/valid),
        and a DELETED node tombstones in place so node order — which the
        fence's row indices bake — never moves. A respawn onto a
        tombstone is safe too: the in-flight wave was dispatched while
        the row was invalid, so no row targets it. What still flushes:
        SPEC changes (labels/taints/allocatable/avoid — the static
        predicates are evaluated at dispatch and never re-checked) and
        genuinely NEW nodes (membership growth reorders the snapshot
        under the fence's indices)."""
        pipe = self._pipeline
        if pipe is None or pipe.idle:
            return False
        if etype == "DELETED":
            return False  # tombstone + liveness fence cover it
        with self.cache._lock:
            info = self.cache._nodes.get(node.name)
            prev = info.node if info is not None else None
        if info is None:
            return True   # new name: membership reorder at next refresh
        if prev is None:
            return False  # respawn onto a tombstone: no in-flight row
            # can target it, and the name keeps its row
        return not (prev.labels == node.labels
                    and prev.taints == node.taints
                    and prev.allocatable == node.allocatable
                    and prev.capacity == node.capacity
                    and prev.allowed_pod_number == node.allowed_pod_number
                    and prev.annotations == node.annotations)

    # ------------------------------------------------------------ scheduling

    def schedule_round(self, max_batch: int = 0, wait: float = 0.0) -> Dict[str, int]:
        """One batch round: pop ready pods, place on device, bind. Mirrors
        scheduleOne (scheduler.go:253) over a whole batch, wrapped in a
        slow-schedule trace (generic_scheduler.go:89-90's 100ms utiltrace).

        This is the SYNCHRONOUS round: device placement blocks before the
        host bookkeeping runs. run_until_drained/run_arrival use the
        pipelined drain (wave k+1's device time overlapping wave k's host
        phases) and fall back to this body per chunk when a batch needs the
        strict/oracle machinery."""
        trace = Trace("Scheduling round")
        self.sync()
        trace.step("informer sync done")
        pods = self.queue.pop_batch(max_n=max_batch, wait=wait)
        pop_ts = time.monotonic()  # NextPod-pop instant (scheduler.go:289)
        return self._process_batch(pods, pop_ts, trace)

    def _process_batch(self, pods: List[Pod], pop_ts: float,
                       trace: Optional[Trace] = None) -> Dict[str, int]:
        if trace is None:
            trace = Trace("Scheduling round")
        stats = {"popped": len(pods), "bound": 0, "unschedulable": 0,
                 "bind_errors": 0, "preemptions": 0}
        # gang (coscheduling) gating: pods in a group schedule atomically
        # once their quorum is in the queue (engine/gang.py); incomplete
        # gangs park in _gang_waiting until members arrive
        plain, gangs = gangmod.partition(pods)
        self._sweep_parked_gangs(gangs)
        if not pods:
            self._idle_gc()
            return stats
        trace.field("pods", len(pods))
        ready_gangs = self._gate_gangs(gangs, plain)
        t0 = time.monotonic()
        scheduled_count = len(plain) + sum(len(m) for _g, m, _q in
                                           ready_gangs)
        results = []
        # ready gangs place FIRST: their members were necessarily queued at
        # or before this round's plain pods, and placing plain first would
        # let a sustained plain stream starve contended gangs (each retry
        # seeing capacity already consumed)
        if ready_gangs:
            for gr in gangmod.schedule_gangs(self.engine, ready_gangs,
                                             mode=self.batch_mode):
                if gr.placed:
                    # quorum committed: the gang is past its atomicity
                    # point — later members/retries go solo
                    self._mark_gang_degraded(gr.name)
                    results.extend(PlacementResult(m, m.node_name, 1)
                                   for m in gr.placed_members)
                unschedulable = gr.unplaced_members
                stats["unschedulable"] += len(unschedulable)
                if unschedulable:
                    self.metrics.failed.inc(len(unschedulable))
                for m in unschedulable:
                    self._event(m, "Warning", "FailedScheduling",
                                f"gang {gr.name}: {gr.reason}")
                    self.queue.add_backoff(
                        dataclasses.replace(m, node_name=""))
        if plain:
            results.extend(self.engine.schedule(plain, assume=True,
                                                mode=self.batch_mode))
        t_alg = time.monotonic() - t0
        trace.step("batch placement computed (device)")
        placed = []
        unschedulable_pods = []
        record = self.record_events
        for r in results:
            if r.node_name is None:
                stats["unschedulable"] += 1
                self.metrics.failed.inc()
                if record:
                    self._event(
                        r.pod, "Warning", "FailedScheduling",
                        f"0/{len(self.engine.snapshot.node_names)} nodes "
                        f"available (fit_count={r.fit_count})")
                unschedulable_pods.append(r.pod)
                if self._requeue_unschedulable(r.pod):
                    stats["spilled"] = stats.get("spilled", 0) + 1
            else:
                placed.append(r)
        # one batched /binding pass (per-binding semantics identical to the
        # per-pod POST; scheduler.go:224-250 error paths preserved per pod)
        tb0 = time.monotonic()
        errs = self.api.bind_many(
            [Binding(r.pod.name, r.pod.namespace, r.pod.uid, r.node_name)
             for r in placed])
        bind_done = time.monotonic()
        t_bind = bind_done - tb0
        bound_pods, n_errors = self._finish_binds(
            [r.pod for r in placed], errs)
        if placed and RECORDER.enabled:
            RECORDER.record(flightrec.BIND_FLUSH, t0=tb0, dur=t_bind,
                            a=len(bound_pods), b=n_errors)
        stats["bind_errors"] += n_errors
        stats["bound"] += len(bound_pods)
        trace.step("bindings written")
        self.cache.finish_bindings_bulk(bound_pods)
        if unschedulable_pods and features.enabled("PodPriority"):
            # after the binding pass, so a victim choice can never race a
            # not-yet-posted Binding from this same round
            stats["preemptions"] = self._preempt_round(unschedulable_pods)
        n = len(bound_pods)
        self.metrics.scheduled.inc(n)
        # honest spans (not amortized t/n): every pod in the batch really
        # waited the FULL algorithm span and the FULL binding span — its
        # placement was not done until the round's was. e2e matches the
        # reference's pop->bind-complete window (scheduler.go:289)
        self.metrics.algorithm_latency.observe_many(t_alg, n)
        self.metrics.binding_latency.observe_many(t_bind, n)
        self.metrics.e2e_latency.observe_many(bind_done - pop_ts, n)
        # per-pod create->bound, queue wait + backoff rounds included:
        # distinct value per pod, the distribution the SLO check reads
        lats = [bind_done - self._first_queued.pop(p.key(), pop_ts)
                for p in bound_pods]
        self.metrics.create_to_bound.observe_batch(lats)
        if SLO.enabled and lats:
            # the SLO engine sees EVERY bound pod (not the tracer's
            # sampled subset) — burn-rate math over the full population
            SLO.observe_batch(lats, t=bind_done)
        if TRACER.enabled and bound_pods:
            TRACER.bound_batch([p.key() for p in bound_pods],
                               t0=bind_done)
        if self.wave_observer is not None and bound_pods:
            self.wave_observer(bind_done, [p.key() for p in bound_pods])
        self._idle_gc()
        # per-pod amortized threshold: a 30k-pod round is not "slow" the way
        # a 30k-pod-long one-pod trace would be; scale like the reference's
        # per-Schedule-call threshold
        trace.log_if_long(SCHEDULE_TRACE_THRESHOLD_S
                          * max(scheduled_count, 1))
        return stats

    def _gate_gangs(self, gangs: Dict[str, List[Pod]],
                    plain: List[Pod]) -> List[Tuple[str, List[Pod], int]]:
        """Quorum gating shared by the classic round and the pipelined
        drain (ISSUE 5): degraded gangs' members (quorum already bound —
        past the atomicity point) join the plain stream, below-quorum
        gangs park in _gang_waiting until members arrive, and gangs whose
        quorum is present are RELEASED from the parking lot and returned
        as (name, members, quorum) ready for atomic placement."""
        ready: List[Tuple[str, List[Pod], int]] = []
        for gname, members in gangs.items():
            if gname in self._gang_degraded:
                plain.extend(members)
                continue
            waiting = self._gang_waiting.setdefault(gname, {})
            if gname not in self._gang_parked_at:
                self._gang_parked_at[gname] = self._now()
            for m in members:
                waiting[m.key()] = m
            quorum = gangmod.min_available(list(waiting.values()))
            if len(waiting) >= quorum:
                ready.append((gname, list(waiting.values()), quorum))
                del self._gang_waiting[gname]
                self._gang_parked_at.pop(gname, None)
            elif TRACER.enabled:
                # parked below quorum: the wait shows on the timeline as
                # gang_wait instead of vanishing into queue time
                TRACER.batch_event(podtrace.GANG_GATED,
                                   [m.key() for m in members],
                                   a=len(waiting))
        return ready

    def _sweep_parked_gangs(self, gangs) -> None:
        """Parked-too-long gangs surface even on empty rounds — a gang below
        quorum with no new arrivals would otherwise never reach the sweep
        (quorum may never come: members deleted, minAvailable typo);
        members re-queue with backoff — retried AND visible via events.
        A gang receiving members THIS round (`gangs`) is exempt: the arrival
        may complete its quorum, and evicting it first would turn an on-time
        completion into a spurious backoff cycle."""
        if not self._gang_parked_at:
            return
        now = self._now()
        for gname in [g for g, t0_ in self._gang_parked_at.items()
                      if now - t0_ > self.GANG_WAIT_TIMEOUT_S
                      and g not in gangs]:
            waiting = self._gang_waiting.pop(gname, {})
            self._gang_parked_at.pop(gname, None)
            for m in waiting.values():
                self._event(m, "Warning", "FailedScheduling",
                            f"gang {gname} below quorum for "
                            f"{self.GANG_WAIT_TIMEOUT_S:.0f}s")
                self.queue.add_backoff(m)

    def _idle_gc(self) -> None:
        """Housekeeping (empty rounds + the streaming loop's wall-clock
        cadence): expire unconfirmed assumes, gc backoff stamps, compact
        node tombstones. An expiry mutates NodeInfos the scheduler cannot
        attribute to a node it tracked — force the next refresh to walk
        everything."""
        if self.cache.cleanup_assumed():
            self.engine.note_full_refresh()
        self.queue.backoff.gc()
        # amortized membership compaction (ISSUE 8): dead nodes tombstone
        # in place so churn never restructures the snapshot per event;
        # once enough podless tombstones accumulate, pay ONE full rebuild
        # to reclaim their rows. ONLY while the pipeline is idle: an
        # in-flight wave's fence/assume path maps row indices baked at
        # dispatch through the refreshed snapshot, and the whole point of
        # tombstoning is that node order never moves under it.
        if self._pipeline is not None and not self._pipeline.idle:
            return
        n_nodes = max(len(self.engine.snapshot.node_names), 8)
        if self.cache.purgeable_tombstones() > max(8, n_nodes // 8) \
                and self.cache.purge_tombstones():
            self.engine.note_full_refresh()

    def _preempt_round(self, unschedulable: List[Pod]) -> int:
        """Preemption pass (1.8 generic_scheduler.Preempt, feature-gated
        behind PodPriority like kube_features.go:122): for each
        unschedulable pod, highest priority first, pick a node + minimal
        victim set (engine/preemption.py) and evict the victims. The
        preemptor is already requeued; once the victims' DELETED events
        drain through sync(), the freed capacity places it in a following
        round (the nominate-then-reschedule flow)."""
        from kubernetes_tpu.engine import preemption as preemptmod
        from kubernetes_tpu.ops.oracle_ext import SchedulingContext
        # clones: the victim bookkeeping below must not mutate the live
        # cache (the DELETED watch events do that authoritatively)
        infos = self.cache.snapshot_infos()
        # full predicate context: without it the feasibility check would
        # ignore inter-pod affinity / volumes / policy algorithms and
        # evict victims that free nothing for the preemptor. Victims stay
        # in ctx.infos during the check — conservative: a node whose
        # feasibility depends on a victim's own anti-affinity going away
        # is skipped rather than over-evicted.
        ctx = SchedulingContext(
            infos, self.engine.workloads_provider(),
            hard_pod_affinity_weight=self.engine.hard_pod_affinity_weight,
            volume_ctx=self.engine.volume_ctx,
            policy_algos=self.engine.policy_algos)
        count = 0
        # lazy: a round whose unschedulable pods are all priority 0 (the
        # default) must not pay the O(total pods) array build
        state = None
        for pod in sorted(unschedulable, key=lambda p: -p.priority):
            if pod.priority <= 0:
                break  # sorted desc: nothing below can preempt either
            if state is None:
                state = preemptmod.PreemptionState(infos)
            plan = preemptmod.pick_preemption(pod, infos, ctx=ctx,
                                              state=state)
            if plan is None:
                continue
            if TRACER.enabled and plan.victims:
                TRACER.evicted_batch([v.key() for v in plan.victims])
            for vic in plan.victims:
                try:
                    self.api.delete("Pod", vic.namespace, vic.name)
                except NotFound:
                    pass
                self._event(vic, "Normal", "Preempted",
                            f"by {pod.key()} on node {plan.node_name}")
                # reflect the eviction in the local view immediately so a
                # second preemptor this round does not double-count the
                # same victims
                info = infos.get(plan.node_name)
                if info is not None:
                    info.remove_pod(vic)
            # reserve the freed capacity for THIS preemptor in the local
            # view (the 1.8 nominated-pod reservation): a second
            # preemptor this round must not plan into the same hole and
            # over-evict
            info = infos.get(plan.node_name)
            if info is not None:
                info.add_pod(pod)
            state.apply_plan(plan, pod)
            self._event(pod, "Normal", "TriggeredPreemption",
                        f"{len(plan.victims)} lower-priority pod(s) on "
                        f"{plan.node_name} evicted")
            count += 1
        return count

    # ------------------------------------------------------ pipelined drain

    def _wave_eligible(self, pods: List[Pod]) -> bool:
        """Cheap host-side gate before dispatch: with gang_pipeline off,
        gang-bearing chunks flush to the classic round (the pre-ISSUE 5
        behavior, kept as the bench A/B baseline). No chunk SHAPE is
        host-gated anymore (ISSUE 18): required (anti-)affinity, gangs,
        host-check, and Policy classes all ride the wave path (ISSUEs
        3/5/18); the engine returns None only for the gang-quorum-
        unreachable corner, which the caller flushes per chunk."""
        if self.gang_pipeline:
            return True
        return all(gangmod.gang_name(p) is None for p in pods)

    def _release_gangs_for_wave(self, pods: List[Pod], stats: Dict[str, int]
                                ) -> Tuple[List[Pod], Optional[list]]:
        """Pipelined gang routing (ISSUE 5): partition a popped chunk,
        park/degrade/release through the shared quorum gate, reject
        provably-infeasible ready gangs host-side (capacity_precheck, the
        classic path's cheap gate), and return (chunk_pods, gang_spans)
        where gang_spans = [(name, member index range, quorum)] into
        chunk_pods. Ready gangs lead the chunk — their members were queued
        at or before this chunk's plain pods, and trailing them would let
        a sustained plain stream starve contended gangs."""
        plain, gangs = gangmod.partition(pods)
        self._sweep_parked_gangs(gangs)
        if not gangs:
            return plain, None
        ready = self._gate_gangs(gangs, plain)
        members_first: List[Pod] = []
        spans = []
        if ready:
            infos = self.cache.node_infos()
            for name, members, quorum in ready:
                if not gangmod.capacity_precheck(members, infos):
                    stats["unschedulable"] += len(members)
                    self.metrics.failed.inc(len(members))
                    for m in members:
                        self._event(m, "Warning", "FailedScheduling",
                                    f"gang {name}: "
                                    "InsufficientClusterCapacity")
                        self.queue.add_backoff(
                            dataclasses.replace(m, node_name=""))
                    continue
                start = len(members_first)
                members_first.extend(members)
                spans.append((name, list(range(start,
                                               start + len(members))),
                              quorum))
        return members_first + plain, spans or None

    def _bind_bulk(self, pods: List[Pod]) -> List[Optional[str]]:
        """One bulk binding write for already-placed pods. Prefers the
        store's identifier-reading fast path; any bind_many-only API
        implementation (the full authenticated apiserver, test doubles)
        gets the classic Binding batch instead."""
        bulk = getattr(self.api, "bind_pods_bulk", None)
        if bulk is not None:
            return bulk(pods)
        return self.api.bind_many(
            [Binding(p.name, p.namespace, p.uid, p.node_name)
             for p in pods])

    def _finish_binds(self, pods: List[Pod], errs) -> Tuple[List[Pod], int]:
        """The shared bind-result tail of BOTH drain paths (classic round
        and pipelined harvest): per-pod error rollback (ForgetPod + backoff
        requeue, scheduler.go:234-245) or Scheduled event. Returns
        (bound_pods, error_count)."""
        bound_pods: List[Pod] = []
        n_errors = 0
        record = self.record_events  # 30k f-strings nobody reads would
        # dominate this loop when event recording is off
        for pod, err in zip(pods, errs):
            if err is not None:
                # undo the optimistic assume
                n_errors += 1
                self.cache.forget_pod(pod)
                self.engine.note_node_dirty(pod.node_name)
                self._event(pod, "Warning", "FailedBinding", err)
                self.queue.add_backoff(
                    dataclasses.replace(pod, node_name=""))
                continue
            bound_pods.append(pod)
            if record:
                self._event(pod, "Normal", "Scheduled",
                            f"Successfully assigned {pod.key()} "
                            f"to {pod.node_name}")
        return bound_pods, n_errors

    def _complete_wave(self, handle) -> Dict[str, int]:
        """Host-side completion of one harvested wave: fence conflicts
        requeue WITHOUT backoff (a capacity race with the blind wave, not
        unschedulability), survivors bind in one bulk write, bookkeeping is
        columnar. This is the work wave k+1's device time hides."""
        res = self.engine.harvest_waves(handle)
        out = {"popped": 0, "bound": 0, "bind_errors": 0, "preemptions": 0,
               "preempt_rollbacks": 0, "victims_evicted": 0,
               "budget_deferred": 0,
               "unschedulable": len(res.unschedulable),
               "fence_requeued": len(res.conflicts),
               "gang_requeued": len(res.gang_requeued),
               "liveness_requeued": len(res.liveness_requeued)}
        record = self.record_events
        for pod in res.liveness_requeued:
            # the target node died/cordoned mid-flight (ISSUE 8): requeue
            # WITH backoff — the topology is not coming back on a
            # capacity-race timescale
            if record:
                self._event(pod, "Warning", "FailedScheduling",
                            f"node {pod.node_name or '?'} no longer live "
                            "at the wave fence")
            self.queue.add_backoff(dataclasses.replace(pod, node_name=""))
        for name in res.gang_committed:
            # quorum committed through the wave fence: the gang is past
            # its atomicity point — later members/retries go solo
            self._mark_gang_degraded(name)
            # a straggler that popped while this wave was in flight was
            # gated BEFORE the commit landed, so it parked below quorum;
            # release it to schedule solo now instead of waiting out the
            # 60s parked-gang sweep (the classic round marks degraded
            # synchronously and never hits this window)
            waiting = self._gang_waiting.pop(name, None)
            self._gang_parked_at.pop(name, None)
            if waiting:
                for m in waiting.values():
                    self.queue.add(m)
        for pod, reason in res.gang_requeued:
            # atomic gang rollback (nothing was assumed): requeue WITH
            # backoff — the gang lost as a unit, like the classic round's
            # below-quorum path; a retry re-waves it against fresh state
            if record:
                self._event(pod, "Warning", "FailedScheduling", reason)
            self.queue.add_backoff(pod)
        for pod in res.conflicts:
            self.queue.add(pod)  # node_name never set on a fenced pod
        preemptors = None
        if res.unschedulable:
            self.metrics.failed.inc(len(res.unschedulable))
            spilled_keys = set()
            for pod, fcnt in res.unschedulable:
                if record:
                    self._event(
                        pod, "Warning", "FailedScheduling",
                        f"0/{len(self.engine.snapshot.node_names)} nodes "
                        f"available (fit_count={fcnt})")
                if self._requeue_unschedulable(pod):
                    out["spilled"] = out.get("spilled", 0) + 1
                    spilled_keys.add(pod.key())
            # wave-path preemption (ISSUE 14): the harvest's unschedulable
            # preemptors displace lower bands WITHOUT flushing the
            # pipeline — planned below, AFTER this wave's binding pass,
            # so a victim choice can never race a not-yet-posted bind
            # (the classic round's ordering, kept). A spilled pod is
            # LEAVING this cell — it must not displace victims here while
            # the router re-admits it elsewhere.
            if self.wave_preemption and features.enabled("PodPriority"):
                preemptors = [p for p, _f in res.unschedulable
                              if p.key() not in spilled_keys]
                if not any(p.priority > 0 for p in preemptors):
                    preemptors = None
        if not res.bound:
            if preemptors:
                for k, v in self._preempt_wave(preemptors,
                                               handle.wave_id).items():
                    out[k] = out.get(k, 0) + v
            return out
        tb0 = time.monotonic()
        errs = self._bind_bulk(res.bound)
        t_bind = time.monotonic() - tb0
        bound_pods, n_errors = self._finish_binds(res.bound, errs)
        out["bind_errors"] += n_errors
        bind_done = time.monotonic()
        if RECORDER.enabled:
            RECORDER.record(flightrec.BIND_FLUSH, wave=handle.wave_id,
                            t0=tb0, dur=t_bind, a=len(bound_pods),
                            b=n_errors)
        keys = [p.key() for p in bound_pods]  # computed once, shared by the
        # TTL pass and the latency harvest below
        self.cache.finish_bindings_bulk(bound_pods, keys=keys)
        n = len(bound_pods)
        out["bound"] = n
        self.metrics.scheduled.inc(n)
        # honest per-wave spans: algorithm = the residual device wait this
        # wave's overlap did NOT hide; e2e = pop -> bind-complete including
        # the one-wave pipeline lag every pod in the chunk really waited
        self.metrics.algorithm_latency.observe_many(res.t_block, n)
        self.metrics.binding_latency.observe_many(t_bind, n)
        self.metrics.e2e_latency.observe_many(bind_done - handle.pop_ts, n)
        fq_pop = self._first_queued.pop
        pop_ts = handle.pop_ts
        lats = [bind_done - fq_pop(k, pop_ts) for k in keys]
        self.metrics.create_to_bound.observe_batch(lats)
        if SLO.enabled:
            SLO.observe_batch(lats, t=bind_done)
        if TRACER.enabled:
            TRACER.bound_batch(keys, t0=bind_done)
        if self.wave_observer is not None:
            self.wave_observer(bind_done, keys)
        if preemptors:
            for k, v in self._preempt_wave(preemptors,
                                           handle.wave_id).items():
                out[k] = out.get(k, 0) + v
        return out

    def _preempt_wave(self, preemptors: List[Pod],
                      wave_id: int = -1) -> Dict[str, int]:
        """One wave-path preemption round (ISSUE 14): plan displacements
        for this harvest's unschedulable preemptors (device victim scan +
        exact verification, engine/preempt_wave.py), rate-limit them
        through the disruption budget, and COMMIT each survivor through
        the store's atomic evict+bind:

        - success: victims leave the cache immediately (their watch
          MODIFIED-unbound events re-enter them as ordinary arrivals the
          streaming loop absorbs), the preemptor assumes + finishes
          binding exactly like a fenced wave placement — either EVERY
          victim eviction landed AND the preemptor bound, or nothing did;
        - error: rollback — the preemptor stays on the backoff requeue
          _complete_wave already gave it, local state untouched. If the
          error hid a landed commit (the at-most-once ambiguity the
          injected eviction TIMEOUT reproduces), the watch stream heals:
          sync() runs before every pop, so the preemptor's confirmation
          removes it from the queue before any retry could double-bind.

        Victims are restricted to store-confirmed bound pods (an assumed
        claim is unbound at the store; planning it would abort commits)."""
        from kubernetes_tpu.utils.trace import COUNTERS

        out = {"preemptions": 0, "preempt_rollbacks": 0,
               "victims_evicted": 0, "budget_deferred": 0}
        api_op = getattr(self.api, "preempt_pods_bulk", None)
        if api_op is None:
            return out  # store cannot commit atomically: no wave path
        t_plan = time.monotonic()
        pods_map = self._pods

        def _evictable(p: Pod) -> bool:
            q = pods_map.get(p.key())
            return q is not None and bool(q.node_name)

        plans = plan_wave_preemptions(
            self.engine, preemptors, evictable=_evictable,
            workloads=self.engine.workloads_provider())
        if RECORDER.enabled:
            RECORDER.record(flightrec.PREEMPT_PROPOSE, wave=wave_id,
                            t0=t_plan, dur=time.monotonic() - t_plan,
                            a=len(preemptors), b=len(plans))
        if not plans:
            return out
        budget = self.disruption_budget
        band_counts = self.engine.snapshot.band_bound_counts() \
            if budget.band_floor else None
        record = self.record_events
        snap_index = self.engine.snapshot.node_index
        for plan in plans:
            pod = plan.pod
            if not budget.admit(plan.victims, band_counts):
                out["budget_deferred"] += 1
                COUNTERS.inc("engine.preempt_budget_deferred")
                if record:
                    self._event(pod, "Normal", "PreemptionDeferred",
                                "disruption budget exhausted")
                continue
            err = api_op(plan.victims,
                         Binding(pod.name, pod.namespace, pod.uid,
                                 plan.node_name))
            if err is not None:
                out["preempt_rollbacks"] += 1
                COUNTERS.inc("engine.preempt_rollbacks")
                if record:
                    self._event(pod, "Warning", "FailedPreemption", err)
                if RECORDER.enabled:
                    RECORDER.record(flightrec.PREEMPT_ROLLBACK,
                                    wave=wave_id, a=len(plan.victims),
                                    b=int("landed" in err))
                continue
            bind_done = time.monotonic()
            key = pod.key()
            # victims leave the cache NOW — the store op landed, and
            # phantom occupancy would hide the freed hole from the next
            # wave; the watch handlers re-apply both sides idempotently
            for vic in plan.victims:
                self.cache.remove_pod(vic)
                if record:
                    self._event(vic, "Normal", "Preempted",
                                f"by {key} on node {plan.node_name}")
            if TRACER.enabled:
                TRACER.evicted_batch([v.key() for v in plan.victims],
                                     t0=bind_done)
            self.queue.remove(key)  # it was backoff-requeued above
            pod.node_name = plan.node_name
            self.cache.assume_pod(pod)
            self.cache.finish_binding(pod)
            self.engine.note_node_dirty(plan.node_name)
            self.metrics.scheduled.inc(1)
            lat = bind_done - self._first_queued.pop(key, t_plan)
            self.metrics.create_to_bound.observe_batch([lat])
            if SLO.enabled:
                SLO.observe_batch([lat], t=bind_done)
            if TRACER.enabled:
                TRACER.bound_batch([key], t0=bind_done)
            if self.wave_observer is not None:
                self.wave_observer(bind_done, [key])
            out["preemptions"] += 1
            out["victims_evicted"] += len(plan.victims)
            COUNTERS.inc("engine.preempt_commits")
            COUNTERS.inc("engine.victims_evicted", len(plan.victims))
            if record:
                self._event(pod, "Normal", "TriggeredPreemption",
                            f"{len(plan.victims)} lower-priority pod(s) "
                            f"on {plan.node_name} evicted")
            if self.preempt_observer is not None:
                self.preempt_observer(bind_done, bind_done - t_plan,
                                      len(plan.victims))
            if RECORDER.enabled:
                RECORDER.record(flightrec.PREEMPT_COMMIT, wave=wave_id,
                                t0=t_plan, dur=bind_done - t_plan,
                                a=len(plan.victims),
                                b=snap_index.get(plan.node_name, -1))
                RECORDER.record(flightrec.VICTIM_REQUEUE, wave=wave_id,
                                a=len(plan.victims),
                                b=min(v.priority for v in plan.victims))
            if band_counts is not None:
                for v in plan.victims:
                    band_counts[v.priority] = \
                        band_counts.get(v.priority, 1) - 1
        return out

    def pipeline(self, chunk: int = 0, overlap: bool = True):
        """A live two-stage drain pipeline (ISSUE 2): the FIXED-chunk mode
        of the scheduling loop. step() pops one chunk, dispatches its fused
        wave eval WITHOUT blocking, then harvests the PREVIOUS chunk — so
        wave k+1's device time overlaps wave k's host bookkeeping.
        overlap=False is the sequential debug mode: identical dataflow
        (same blind window, same fence), device forced to complete before
        the host tail — placements are bit-identical, only the wall-clock
        overlap is forfeited."""
        return ScheduleLoop(self, chunk or self.pipeline_chunk, overlap)

    def _requeue_unschedulable(self, pod) -> bool:
        """Backoff-requeue an unschedulable pod — or SPILL it to the
        federation hook once its verdict count crosses the threshold.
        Returns True when the pod was spilled (it left this cell: no
        requeue, latency stamp cleared). With no spill_handler the
        attempt ledger is never touched — single-cell behavior stays
        bit-identical."""
        h = self.spill_handler
        if h is not None:
            key = pod.key()
            n = self._unsched_attempts.get(key, 0) + 1
            if n >= self.spill_after_attempts:
                self._unsched_attempts.pop(key, None)
                self._first_queued.pop(key, None)
                h([pod])
                return True
            self._unsched_attempts[key] = n
        self.queue.add_backoff(pod)
        return False

    def stream(self, budget_s: float = 0.25, min_quantum: int = 256,
               max_quantum: int = 16384, overlap: bool = True,
               chunk: int = 0, fastlane=False):
        """The ALWAYS-ON loop (ISSUE 7): micro-waves admitted on a latency
        budget instead of fixed chunks — pop whatever is queued when the
        device frees up, bounded by an adaptive power-of-2 quantum so one
        admission can never make the next arrival wait past ``budget_s``.
        Same dataflow and fence as pipeline(); only the admission policy
        differs (engine/streaming.py docstring). ``chunk`` seeds the
        initial quantum when given.

        ``fastlane=True`` arms the Sparrow fast tier (ISSUE 17):
        latency-critical pods bypass the micro-wave quantum through a
        sampled [1, k] eval + late-bind fence (engine/fastlane.py). Pass
        a FastLane instance instead of True to control k/retries/seed."""
        fl = None
        if fastlane:
            from kubernetes_tpu.engine.fastlane import FastLane
            fl = fastlane if not isinstance(fastlane, bool) \
                else FastLane(self)
        return ScheduleLoop(self, chunk, overlap, budget_s=budget_s,
                            min_quantum=min_quantum,
                            max_quantum=max_quantum, fastlane=fl)

    def run_until_drained(self, max_rounds: int = 10_000,
                          max_batch: int = 0,
                          pipeline: Optional[bool] = None,
                          overlap: bool = True) -> Dict[str, int]:
        """Bench helper: rounds until queue is empty and no watch events.

        pipeline=None auto-selects: wave mode without PodPriority drains
        through the two-stage pipeline (chunked, overlapped); strict mode
        and priority scheduling keep the classic synchronous rounds, and
        any chunk the engine cannot wave-place falls back per chunk."""
        total = {"popped": 0, "bound": 0, "unschedulable": 0,
                 "bind_errors": 0, "preemptions": 0, "fence_requeued": 0,
                 "gang_requeued": 0, "liveness_requeued": 0}
        if pipeline is None:
            pipeline = (self.batch_mode == "wave"
                        and not features.enabled("PodPriority"))
        if not pipeline:
            for _ in range(max_rounds):
                stats = self.schedule_round(max_batch=max_batch)
                for k in stats:
                    total[k] = total.get(k, 0) + stats[k]
                if stats["popped"] == 0 and self.sync() == 0 \
                        and self.queue.ready_count() == 0:
                    break
            return total
        # chunk sizing: enough waves for the overlap to hide device time,
        # few enough that per-wave fixed costs (refresh, encode reuse,
        # group assume) stay amortized — a pre-loaded 30k queue drains as
        # two double-buffered waves (measured optimum on the CPU box;
        # PROFILE_r07.md)
        ready = self.queue.ready_count()
        chunk = max_batch or max(self.pipeline_chunk, -(-ready // 2))
        pipe = self.pipeline(chunk=chunk, overlap=overlap)
        try:
            for _ in range(max_rounds):
                stats = pipe.step()
                for k in stats:
                    total[k] = total.get(k, 0) + stats[k]
                if stats["popped"] == 0 and pipe.idle \
                        and self.sync() == 0 \
                        and self.queue.ready_count() == 0:
                    break
        finally:
            for k, v in pipe.close().items():
                total[k] = total.get(k, 0) + v
        return total

    # ------------------------------------------------------------- handlers

    _GANG_DEGRADED_MAX = 10_000
    GANG_WAIT_TIMEOUT_S = 60.0  # parked-below-quorum visibility timeout

    def _mark_gang_degraded(self, name: str) -> None:
        # re-marking refreshes recency so an active gang's entry is never
        # the one evicted
        self._gang_degraded.pop(name, None)
        self._gang_degraded[name] = None
        while len(self._gang_degraded) > self._GANG_DEGRADED_MAX:
            self._gang_degraded.pop(next(iter(self._gang_degraded)))

    def _responsible_for(self, pod: Pod) -> bool:
        return (pod.scheduler_name or DEFAULT_SCHEDULER_NAME) == self.scheduler_name

    def _on_volume_event(self, kind: str, etype: str, obj) -> None:
        """PV/PVC informer handlers (factory.go:120-140 wires both; events
        invalidate the equivalence cache there — here they bump the
        VolumeContext version so the snapshot re-resolves PD rows)."""
        vctx = self.engine.volume_ctx
        if kind == "PersistentVolume":
            if etype == "DELETED":
                vctx.pvs.pop(obj.name, None)
            else:
                vctx.pvs[obj.name] = obj
        else:
            key = (obj.namespace, obj.name)
            if etype == "DELETED":
                vctx.pvcs.pop(key, None)
            else:
                vctx.pvcs[key] = obj
        vctx.version += 1

    def _on_node_event(self, etype: str, node: Node) -> None:
        # membership or spec moved: the targeted-refresh hint cannot name
        # what changed (vocab interning, node order) — next refresh walks all
        self.engine.note_full_refresh()
        if etype == "DELETED":
            # assumed pods on the dead node are forgotten by the cache
            # (ISSUE 8 audit: their optimistic capacity claim pointed at a
            # node that no longer exists). Any that the apiserver still
            # shows UNBOUND requeue with backoff — the assume raced the
            # node's death and the bind never landed; already-bound ones
            # are ghost orphans for node lifecycle to evict, not ours to
            # double-bind.
            for pod in self.cache.remove_node(node.name):
                key = pod.key()
                prev = self._pods.get(key)
                if prev is not None and not prev.node_name:
                    self._event(pod, "Warning", "FailedScheduling",
                                f"assumed node {node.name} deleted "
                                "before bind")
                    self._first_queued.setdefault(key, time.monotonic())
                    self.queue.add_backoff(
                        dataclasses.replace(pod, node_name=""))
        else:
            self.cache.update_node(node)

    def _on_pod_event(self, etype: str, pod: Pod) -> None:
        key = pod.key()
        prev = self._pods.get(key)
        # any event invalidates a parked gang copy: the pod either left
        # (DELETED/bound) or changed spec — it re-enters via the queue and
        # re-partitions fresh, never schedules from a stale parked object
        for waiting in self._gang_waiting.values():
            waiting.pop(key, None)
        if etype == "DELETED":
            self._pods.pop(key, None)
            self._first_queued.pop(key, None)
            self.queue.remove(key)
            if prev is not None and prev.node_name:
                self.cache.remove_pod(prev)
                self.engine.note_node_dirty(prev.node_name)
            return
        self._pods[key] = pod
        if etype == "ADDED":
            if pod.node_name:
                self.cache.add_pod(pod)
                self.engine.note_node_dirty(pod.node_name)
            elif self._responsible_for(pod):
                self._first_queued.setdefault(key, time.monotonic())
                self.queue.add(_queue_copy(pod))
            return
        # MODIFIED
        was_bound = prev is not None and bool(prev.node_name)
        if not was_bound and pod.node_name:
            self.queue.remove(key)
            self._first_queued.pop(key, None)  # bound (possibly by a
            # foreign scheduler); our own binds already harvested it
            self.cache.add_pod(pod)  # confirms our assume, or records a
            # foreign scheduler's bind (cache.go:214)
            self.engine.note_node_dirty(pod.node_name)
        elif was_bound and pod.node_name:
            self.cache.update_pod(prev, pod)
            self.engine.note_node_dirty(prev.node_name, pod.node_name)
        elif was_bound and not pod.node_name:
            self.cache.remove_pod(prev)
            self.engine.note_node_dirty(prev.node_name)
            if self._responsible_for(pod):
                self._first_queued.setdefault(key, time.monotonic())
                self.queue.add(_queue_copy(pod))
        else:
            self.queue.remove(key)
            if self._responsible_for(pod):
                self._first_queued.setdefault(key, time.monotonic())
                self.queue.add(_queue_copy(pod))

    def _relist(self) -> None:
        """Watch fell behind the event log — rebuild everything from a fresh
        List, like a reflector restart. Assumed pods still pending
        confirmation are preserved by re-adding only confirmed state."""
        self.cache = SchedulerCache(ttl_seconds=self.cache._ttl, now=self._now)
        self._workloads = {}
        pad_floor = self.engine.wave_pad_floor  # a live _DrainPipeline's
        # compiled-shape pin must survive the engine swap, or every ragged
        # arrival pop after a relist mints a fresh XLA compile
        self.engine = SchedulingEngine(
            self.cache, priorities=self.engine.priorities,
            workloads_provider=lambda: list(self._workloads.values()),
            policy_algos=self._policy_algos)
        self.engine.track_dirty = True
        self.engine.wave_pad_floor = pad_floor
        self.queue = SchedulingQueue(now=self._now)
        self._pods = {}
        self._gang_waiting = {}
        self._gang_degraded = {}
        self._gang_parked_at = {}
        self._started = False
        self.start()
        # prune create->bound stamps for pods that bound or vanished
        # during the watch blackout (their terminal event is exactly what
        # the log compaction lost) — a stale stamp would otherwise inflate
        # a later reschedule's sample, or leak forever
        self._first_queued = {
            k: t for k, t in self._first_queued.items()
            if k in self._pods and not self._pods[k].node_name}

    def _event(self, pod: Pod, etype: str, reason: str, message: str) -> None:
        if not self.record_events:
            return
        self.events.append(Event(pod.key(), reason, message, etype))


# The two-stage pipeline body now lives in engine/streaming.py as the
# fixed-chunk mode of the always-on ScheduleLoop (ISSUE 7); the old name
# stays importable for callers that grew around the drain-shaped API.
_DrainPipeline = ScheduleLoop
