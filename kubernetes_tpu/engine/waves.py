"""Wave-parallel batch placement: the whole queue in a handful of MXU passes.

The strict engine (engine/batch.py) reproduces the reference's one-pod-at-a-
time loop (scheduler.go:253 scheduleOne) exactly with a 30k-step lax.scan —
bit-faithful, but latency-bound (~90us/step of sequential VPU work). This
module is the throughput mode: batch placement is *new capability* relative
to the reference (SURVEY.md §2.3 — the only in-tree batching notion is the
strictly-sequential loop), so its semantics are defined here, TPU-first, per
the SURVEY §7 step-2 design ("top-k per pod + greedy conflict resolution,
capacity decremented as pods commit"):

Wave semantics (deterministic, documented, score-exact):
  1. All still-pending pods score every node against a FROZEN node state
     using the *identical* predicate/priority kernels as the strict engine
     (ops/predicates.py, ops/priorities.py — integer semantics preserved, so
     individual scores bit-match generic_scheduler.go:88-142).
  2. Each pod draws from the shared round-robin counter in FIFO order (a pod
     with >1 fitting nodes consumes one draw, mirroring selectHost's counter
     discipline at generic_scheduler.go:144-160) and targets the
     (draw mod m)-th node of its class's max-score tie set — so a wave of
     identical pods fans out across the whole tie set in ONE device program
     instead of m sequential steps.
  3. Per-node conflict resolution ON DEVICE: pods that picked the same node
     are ordered FIFO; the longest prefix run of spec-equal pods that still
     fits (exact integer capacity math, including the overlay->scratch
     fallback of predicates.go:590-604) commits; the rest re-enter the next
     wave against the updated state. Pods with host ports or volumes commit
     at most one per node per wave (their within-wave interactions are not
     modeled, so they serialize).
  4. A pod whose class fits NO node under the frozen state is unschedulable:
     capacity only shrinks as pods commit, so it could not have fit later in
     the strict order either (monotonicity makes this verdict exact).

  5. Score-aware acceptance: rank r on a node commits only while the node's
     score AFTER r commits (exact integer re-evaluation of the dynamic
     priorities at the evolved utilization) stays >= the frozen runner-up
     score — reproducing the strict engine's score trajectory at integer
     score granularity, so LeastRequested still spreads and MostRequested
     still bin-packs within a single wave.

Inputs are CLASS-level arrays (state/classes.py) — fits/scores are [C, N]
with C = distinct pod specs, recovered per pod by gather. A uniform 30k-pod
storm is C=1: one [1,N] score row + O(P) index math per wave.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubernetes_tpu.api.types import MAX_PRIORITY
from kubernetes_tpu.engine.batch import NodeState, gather_place_batch
from kubernetes_tpu.ops import predicates as preds
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.state.snapshot import (
    NUM_BASE_RESOURCES,
    R_OVERLAY,
    R_SCRATCH,
)

Arrays = Dict[str, jnp.ndarray]

_BIG = np.int32(2 ** 31 - 1)


# --------------------------------------------------------------------------
# node-axis collectives (ISSUE 12): every cross-node-axis operation in the
# wave body — row reductions, the winner tie-selection, per-row gathers,
# commit scatters — goes through ONE of these vtables so the single-device
# trace stays byte-for-byte what it always was while the sharded trace
# (waves_loop's spmd_mesh path, run under shard_map) becomes an explicit
# TWO-STAGE reduce: local per-shard work over N/D rows, then a tiny
# cross-device combine over n_devices candidates. No step ever gathers a
# full-N tensor to one device; the only cross-device payloads are [D, C]
# tie counts (all_gather), [C]/[P] psum/pmax combines, and the O(P)
# ownership-masked candidate sums.
# --------------------------------------------------------------------------


class _GlobalCol:
    """Whole-node-axis implementation — the ops exactly as the unsharded
    wave body always wrote them (bit-identity anchor for the A/B)."""

    spmd = False

    def __init__(self, n_global: int):
        self.n_global = n_global   # GLOBAL node-id sentinel bound
        self.n_local = n_global    # scatter width (== global here)

    def row_sum(self, x):
        return x.sum(axis=1)

    def row_max(self, x, keepdims=False):
        return x.max(axis=1, keepdims=keepdims)

    def first_fit(self, fits):
        """Global index of each class's first fitting node."""
        return jnp.argmax(fits, axis=1).astype(jnp.int32)

    def tie_select(self, ties, pod_class, kz):
        """Node index of the kz-th tie (ascending node order) of each
        pod's class — the RR fan-out lookup."""
        n = ties.shape[1]
        idx_n = jnp.arange(n, dtype=jnp.int32)
        rank = jnp.cumsum(ties.astype(jnp.int32), axis=1) - 1
        cols = jnp.where(ties, rank, n)
        rows = jnp.broadcast_to(jnp.arange(ties.shape[0])[:, None],
                                ties.shape)
        tiemat = jnp.zeros(ties.shape, dtype=jnp.int32).at[rows, cols].set(
            jnp.broadcast_to(idx_n[None, :], ties.shape), mode="drop")
        return tiemat[pod_class, kz]

    def take_rows(self, arr, idx):
        """arr[idx] for node-axis-0 arrays, idx = global node ids >= 0."""
        return arr[idx]

    def take2(self, arr, rows, cols):
        """arr[rows, cols] for [C, N] arrays, cols = global node ids."""
        return arr[rows, cols]

    def to_local(self, ids):
        """Scatter ids: global node id, or -1 -> the drop sentinel."""
        return jnp.where(ids < 0, jnp.int32(self.n_global), ids)


class _ShardCol:
    """Per-shard implementation, legal only inside shard_map over the node
    axis: shard d owns global rows [d*Nl, (d+1)*Nl). Reductions are local
    + psum/pmax; the tie lookup resolves ownership from an all-gathered
    [D, C] tie-count table (the O(n_devices) candidate traffic the bench
    counter reports); gathers/scatters translate global ids to local rows
    and drop the rest — each commit row is written by exactly ONE shard."""

    spmd = True

    def __init__(self, axis: str, n_global: int, n_local: int):
        self.axis = axis
        self.n_global = n_global
        self.n_local = n_local

    def _off(self):
        return (lax.axis_index(self.axis) * self.n_local).astype(jnp.int32)

    def row_sum(self, x):
        return lax.psum(x.sum(axis=1), self.axis)

    def row_max(self, x, keepdims=False):
        m = lax.pmax(x.max(axis=1), self.axis)
        return m[:, None] if keepdims else m

    def first_fit(self, fits):
        local = jnp.where(
            fits.any(axis=1),
            self._off() + jnp.argmax(fits, axis=1).astype(jnp.int32),
            _BIG)
        return lax.pmin(local, self.axis)

    def tie_select(self, ties, pod_class, kz):
        nl = ties.shape[1]
        off = self._off()
        m_l = ties.sum(axis=1).astype(jnp.int32)            # [C] local
        m_all = lax.all_gather(m_l, self.axis)              # [D, C] tiny
        prefix = jnp.cumsum(m_all, axis=0) - m_all          # exclusive
        my_prefix = prefix[lax.axis_index(self.axis)]       # [C]
        rank = jnp.cumsum(ties.astype(jnp.int32), axis=1) - 1
        cols = jnp.where(ties, rank, nl)
        rows = jnp.broadcast_to(jnp.arange(ties.shape[0])[:, None],
                                ties.shape)
        idx_n = off + jnp.arange(nl, dtype=jnp.int32)       # GLOBAL ids
        tiemat_l = jnp.zeros(ties.shape, dtype=jnp.int32).at[
            rows, cols].set(jnp.broadcast_to(idx_n[None, :], ties.shape),
                            mode="drop")
        lr = kz - my_prefix[pod_class]                      # local rank
        owned = (lr >= 0) & (lr < m_l[pod_class])
        cand = jnp.where(owned,
                         tiemat_l[pod_class, jnp.clip(lr, 0, nl - 1)], 0)
        return lax.psum(cand, self.axis)                    # [P] combine

    def take_rows(self, arr, idx):
        nl = arr.shape[0]
        loc = idx - self._off()
        ok = (loc >= 0) & (loc < nl)
        vals = arr[jnp.clip(loc, 0, nl - 1)]
        mask = ok.reshape(ok.shape + (1,) * (arr.ndim - 1))
        return lax.psum(jnp.where(mask, vals, 0), self.axis)

    def take2(self, arr, rows, cols):
        nl = arr.shape[1]
        loc = cols - self._off()
        ok = (loc >= 0) & (loc < nl)
        vals = arr[rows, jnp.clip(loc, 0, nl - 1)]
        return lax.psum(jnp.where(ok, vals, 0), self.axis)

    def to_local(self, ids):
        loc = ids - self._off()
        return jnp.where((ids >= 0) & (loc >= 0) & (loc < self.n_local),
                         loc, jnp.int32(self.n_local))


def _dynamic_fits(cls: Arrays, nodes: Arrays, state: NodeState) -> jnp.ndarray:
    """Capacity-dependent predicate chain vs the wave's frozen state, [C,N].
    Same math as ops/predicates.fits but reading the evolving NodeState."""
    from kubernetes_tpu.ops.pallas_kernels import resources_fit_fast
    return (
        resources_fit_fast(cls["req"], cls["zero_req"], nodes["alloc"],
                           state.requested)
        & preds.pod_count_fit(state.pod_count, nodes["allowed_pods"])[None, :]
        & preds.ports_fit(cls["ports"], state.port_bitmap)
        & preds.no_disk_conflict(cls["vol_hard"], cls["vol_ro"],
                                 state.vol_present, state.vol_rw)
        & preds.max_pd_fit(cls["pd_req"], cls["pd_req_count"], nodes["pd_kind"],
                           state.pd_present, state.pd_counts, nodes["pd_max"])
    )


_DYNAMIC = ("LeastRequestedPriority", "MostRequestedPriority",
            "BalancedResourceAllocation")
_REDUCE = ("TaintTolerationPriority", "NodeAffinityPriority")


def precompute(cls: Arrays, nodes: Arrays,
               priorities: Tuple[Tuple[str, int], ...]) -> Arrays:
    """Everything state-INdependent, computed once per batch OUTSIDE the
    wave loop (XLA cannot hoist work out of a lax.while_loop body): the
    static predicate mask, the reduce-priority count matrices, and the
    weighted sum of static priorities.

    The result depends only on the CLASS encoding and the STATIC node
    arrays — not on the evolving NodeState — so a pipelined drain reuses
    one instance across every wave/tail dispatch of an encoding
    (engine/scheduler_engine._tail_wave_pre): the selector/taint/
    node-affinity label-axis matmuls in here are the single largest
    per-dispatch cost once the loops themselves are round-granular.
    `precompute_jit` is the standalone entry point for that caching;
    the loops keep computing it inline when no `pre` is passed.

    Optional frozen columns (ISSUE 18): a `host_fit` [C, N] bool column
    (label-pure host-check classes, exact against build-time label
    truth — ops/predicates.static_fits ANDs it in) and `policy_fit` /
    `policy_score` columns (Policy-configured algorithms, frozen per
    class — ops/policy_algos.static_class_arrays). Both ride every
    dispatch of the encoding; staleness is the FENCE's problem
    (scheduler_engine._fence re-validates against live truth), never
    this eval's — which is what lets host-check and Policy chunks ride
    the wave path instead of flushing the pipeline."""
    c = cls["req"].shape[0]
    n = nodes["alloc"].shape[0]
    static_score = jnp.zeros((c, n), dtype=jnp.int32)
    for name, weight in priorities:
        if name in _DYNAMIC or name in _REDUCE:
            continue
        if name in ("SelectorSpreadPriority", "InterPodAffinityPriority"):
            # wave mode scores these against the batch-frozen cluster state
            # (ops/affinity.py); the engine passes them via extra_score
            continue
        static_score = static_score \
            + prio.PRIORITY_REGISTRY[name](cls, nodes, None) * weight
    if "policy_score" in cls:
        # Policy-configured NodeLabel / ServiceAntiAffinity priorities
        # (weights pre-folded; ops/policy_algos.py)
        static_score = static_score + cls["policy_score"]
    tt_cnt = jnp.einsum("ct,nt->cn", cls["intolerated_pref"],
                        nodes["taints_pref"].astype(jnp.int8),
                        preferred_element_type=jnp.int32) \
        if any(nm == "TaintTolerationPriority" for nm, _ in priorities) \
        else jnp.zeros((c, n), dtype=jnp.int32)
    na_cnt = prio.node_affinity_counts(cls, nodes["labels"]) \
        if any(nm == "NodeAffinityPriority" for nm, _ in priorities) \
        else jnp.zeros((c, n), dtype=jnp.int32)
    return {"static_fit": preds.static_fits(cls, nodes),
            "static_score": static_score, "tt_cnt": tt_cnt, "na_cnt": na_cnt}


precompute_jit = jax.jit(precompute, static_argnames=("priorities",))


def _wave_scores(cls: Arrays, nodes: Arrays, state: NodeState,
                 pre: Arrays, fits: jnp.ndarray,
                 priorities: Tuple[Tuple[str, int], ...],
                 col=None) -> jnp.ndarray:
    """Weighted priority sum [C,N] against the frozen state; identical
    per-node integer formulas as the strict path (batch._step_scores).
    `col` carries the node-axis reductions (the reduce-priority maxima) so
    the sharded trace reduces two-stage (ISSUE 12)."""
    if col is None:
        col = _GlobalCol(nodes["alloc"].shape[0])
    total = pre["static_score"]
    alloc = nodes["alloc"]
    for name, weight in priorities:
        if name == "LeastRequestedPriority":
            s = prio.least_requested(cls["nonzero"], state.nonzero, alloc)
        elif name == "MostRequestedPriority":
            s = prio.most_requested(cls["nonzero"], state.nonzero, alloc)
        elif name == "BalancedResourceAllocation":
            s = prio.balanced_allocation(cls["nonzero"], state.nonzero, alloc)
        elif name == "TaintTolerationPriority":
            cnt = pre["tt_cnt"]
            masked = jnp.where(fits, cnt, 0)
            mx = col.row_max(masked, keepdims=True)
            s = jnp.where(mx == 0, MAX_PRIORITY,
                          (MAX_PRIORITY * (mx - cnt)) // jnp.maximum(mx, 1))
        elif name == "NodeAffinityPriority":
            cnt = pre["na_cnt"]
            masked = jnp.where(fits, cnt, 0)
            mx = col.row_max(masked, keepdims=True)
            s = jnp.where(mx > 0, (MAX_PRIORITY * cnt) // jnp.maximum(mx, 1), 0)
        else:  # static and host-only priorities are in pre["static_score"]
            continue
        total = total + s * weight
    return total


def _class_capacity(cls: Arrays, nodes: Arrays, state: NodeState) -> jnp.ndarray:
    """cap[C,N]: how many MORE pods of class c fit on node n, by exact
    integer division per resource column (mirrors resources_fit semantics,
    including the overlay->scratch fallback and the zero-request early-exit
    of predicates.go:576-604) plus the allowed-pod-number ceiling. Division
    keeps everything in int32 with no long-prefix cumsums."""
    alloc = nodes["alloc"]
    rem = alloc - state.requested  # [N,R]
    req = cls["req"]  # [C,R]

    def col_cap(rem_col, req_col):  # [N],[C] -> [C,N]
        r = jnp.maximum(req_col, 1)[:, None]
        cap = jnp.maximum(rem_col, 0)[None, :] // r
        return jnp.where(req_col[:, None] > 0, cap, _BIG)

    plain_cols = [0, 1, 2] + list(range(NUM_BASE_RESOURCES, alloc.shape[1]))
    cap = _BIG * jnp.ones((req.shape[0], alloc.shape[0]), dtype=jnp.int32)
    for col in plain_cols:
        cap = jnp.minimum(cap, col_cap(rem[:, col], req[:, col]))
    # storage special case (predicates.go:590-604)
    no_ov = alloc[:, R_OVERLAY] == 0  # [N]
    scr_rem = jnp.where(no_ov,
                        alloc[:, R_SCRATCH] - state.requested[:, R_SCRATCH]
                        - state.requested[:, R_OVERLAY],
                        rem[:, R_SCRATCH])
    scr_add = jnp.where(no_ov[None, :],
                        (req[:, R_SCRATCH] + req[:, R_OVERLAY])[:, None],
                        req[:, R_SCRATCH][:, None])  # [C,N]
    scr_cap = jnp.where(scr_add > 0,
                        jnp.maximum(scr_rem, 0)[None, :]
                        // jnp.maximum(scr_add, 1), _BIG)
    cap = jnp.minimum(cap, scr_cap)
    ov_cap = jnp.where(no_ov[None, :], _BIG,
                       col_cap(rem[:, R_OVERLAY], req[:, R_OVERLAY]))
    cap = jnp.minimum(cap, ov_cap)
    cap = jnp.where(cls["zero_req"][:, None], _BIG, cap)
    count_cap = jnp.maximum(nodes["allowed_pods"] - state.pod_count, 0)
    return jnp.minimum(cap, count_cap[None, :])


# per-wave per-node acceptance window; bounds rank*request products so all
# acceptance math stays exact in int32 (see _rank_scores overflow analysis)
K_WAVE = 4096


def _dyn_at(total_cpu: jnp.ndarray, total_mem: jnp.ndarray,
            cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray,
            priorities: Tuple[Tuple[str, int], ...]) -> jnp.ndarray:
    """Utilization-dependent priority sum for per-row totals (any shape).
    Mirrors least_requested/most_requested/balanced_allocation exactly."""
    out = jnp.zeros_like(total_cpu)
    for name, weight in priorities:
        if name == "LeastRequestedPriority":
            s = (prio._unused_score(total_cpu, cap_cpu)
                 + prio._unused_score(total_mem, cap_mem)) // 2
        elif name == "MostRequestedPriority":
            s = (prio._used_score(total_cpu, cap_cpu)
                 + prio._used_score(total_mem, cap_mem)) // 2
        elif name == "BalancedResourceAllocation":
            s = prio._balanced_score(total_cpu, total_mem, cap_cpu, cap_mem)
        else:
            continue
        out = out + s * weight
    return out


def _wave_aff_mask(aff: Arrays, committed: jnp.ndarray) -> jnp.ndarray:
    """Per-wave required-anti-affinity mask [C, N] from the PER-NODE
    occupancy carry (ISSUE 3). Wave-eligible anti classes have singleton
    topology domains (AffinityData.wave_strict routes everything else to
    the seeded strict tail), so domain occupancy IS per-node occupancy —
    the mask never touches the label axis, whose width scales with the
    cluster when hostname keys are interned (a [C, L] form here cost
    ~100x at 5k nodes; see PROFILE_r08.md). A node n is forbidden for
    class c when it carries (a) a static forbid (existing pods' matching
    anti terms — precomputed [C, N] at encoding build), (b) a committed
    pod matching one of c's own required anti terms whose key n has, or
    (c) a committed pod of class d whose anti term matches c (the
    symmetry direction, predicates.go:1146) under a key n has.
    key_node[c, a, n] = node n has term (c, a)'s topology key — the
    singleton-domain analog of the keymask."""
    m_anti = aff["m_anti"].astype(jnp.int32)           # [C, A, C]
    kn = aff["key_node"].astype(jnp.int32)             # [C, A, N]
    # own anti: committed pods matching (c, a) resident on n, key present
    occ = jnp.einsum("cad,dn->can", m_anti, committed)
    own = (occ * kn).sum(axis=1)                       # [C, N]
    # symmetry: committed pods of class d at n whose term a matches c
    sym = jnp.einsum("dac,dan->cn", m_anti, kn * committed[:, None, :])
    forb = own + sym + aff["static_forbid"].astype(jnp.int32)
    return forb == 0


def _wave_once(cls: Arrays, nodes: Arrays, state: NodeState,
               pre: Arrays, pod_class: jnp.ndarray, active: jnp.ndarray,
               counter: jnp.ndarray,
               priorities: Tuple[Tuple[str, int], ...],
               aff: Arrays = None,
               committed: jnp.ndarray = None,
               col=None,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                          NodeState, jnp.ndarray, jnp.ndarray]:
    """One wave (pure traceable body — jitted standalone as wave_step and
    iterated on device by waves_loop). `pre` carries the hoisted
    state-independent tensors (see precompute). With `aff` given, the
    required-anti mask is re-evaluated against the per-node occupancy
    carry each wave and commits update it (the on-device topology
    AssumePod — ISSUE 3). `col` is the node-axis collectives vtable
    (ISSUE 12): _GlobalCol preserves the single-device trace exactly;
    _ShardCol (inside waves_loop's shard_map) makes every node-axis
    reduction/gather/scatter a two-stage per-shard form. Returns
    (selected [P] (-1 = no fit), accepted [P] bool, fit_count [P] int32,
    new state, new counter, new committed). `selected` always carries
    GLOBAL node indices, whichever col runs."""
    P = pod_class.shape[0]
    if col is None:
        col = _GlobalCol(nodes["alloc"].shape[0])
    iota = jnp.arange(P, dtype=jnp.int32)

    # conditions fresh per dispatch (NOT from pre): the cached precompute
    # survives node kills/flaps/cordons/respawns since ISSUE 8, so the
    # liveness verdict must come from the nodes dict of THIS dispatch
    fits = pre["static_fit"] & preds.node_condition_fit(cls, nodes) \
        & _dynamic_fits(cls, nodes, state)  # [C,N]
    if aff is not None:
        fits = fits & _wave_aff_mask(aff, committed)
    fitcnt = col.row_sum(fits).astype(jnp.int32)  # [C]
    scores = _wave_scores(cls, nodes, state, pre, fits, priorities, col=col)
    masked = jnp.where(fits, scores, jnp.int32(-1))
    best = col.row_max(masked, keepdims=True)
    ties = (masked == best) & fits  # [C,N]
    m = col.row_sum(ties).astype(jnp.int32)  # [C] global tie count

    fc = fitcnt[pod_class]  # [P]
    # FIFO draw from the shared RR counter (selectHost counter discipline)
    multi = active & (fc > 1)
    draw = counter.astype(jnp.int32) + jnp.cumsum(multi.astype(jnp.int32)) \
        - multi.astype(jnp.int32)
    mz = jnp.maximum(m[pod_class], 1)
    kz = (draw % mz).astype(jnp.int32)
    # the winner reduce: kz-th tie of each pod's class, ascending node
    # order (local rank + cross-shard prefix under _ShardCol)
    sel_multi = col.tie_select(ties, pod_class, kz)
    sel_single = col.first_fit(fits)[pod_class]
    sel = jnp.where(~active | (fc == 0), jnp.int32(-1),
                    jnp.where(fc == 1, sel_single, sel_multi))
    new_counter = counter + multi.sum().astype(jnp.uint32)

    # ---- per-node FIFO conflict resolution --------------------------------
    placeable = sel >= 0
    key = jnp.where(placeable, sel, col.n_global) * P + iota  # unique,
    # segment-sorted
    order = jnp.argsort(key)
    s_sel = sel[order]
    s_class = pod_class[order]
    s_place = placeable[order]
    seg_start = jnp.concatenate(
        [jnp.ones(1, dtype=bool), s_sel[1:] != s_sel[:-1]])
    bs = jax.lax.cummax(jnp.where(seg_start, iota, 0))  # segment-start index
    rank_in_seg = iota - bs
    first_class = s_class[bs]
    same_run = jnp.cumsum((s_class != first_class).astype(jnp.int32))
    same_run = (same_run - same_run[bs]) == 0  # prefix run of first class
    cap = _class_capacity(cls, nodes, state)  # [C,N]
    safe_sel = jnp.maximum(s_sel, 0)
    cap_lim = jnp.minimum(col.take2(cap, s_class, safe_sel), K_WAVE)
    special_cls = ((cls["ports"][:, 0] >= 0)
                   | (cls["vol_hard"].sum(axis=1) + cls["vol_ro"].sum(axis=1)
                      + cls["pd_req"].sum(axis=1) > 0))
    if aff is not None:
        # self-anti classes commit at most one pod per node per wave: the
        # second pod of the same FIFO run would land in a domain its first
        # just made forbidden (singleton domains make per-node the exact
        # granularity; AffinityData.wave_gate). The specials' port/volume
        # scatters below are no-ops for these classes (no ports, no vols).
        special_cls = special_cls | aff["wave_gate"]
    special = special_cls[s_class]
    # score-aware window: node score after r commits of this class must stay
    # >= the frozen runner-up (max score over non-tie nodes). Overflow-safe:
    # r_eff*nz is bounded either by cap (r*req <= alloc per resources_fit)
    # or by K_WAVE * the nonzero defaults (~8.4e8 < 2^31).
    thr = col.row_max(jnp.where(ties, jnp.int32(-1), masked))  # [C]
    r_eff = jnp.minimum(rank_in_seg, cap_lim)
    nz_z = cls["nonzero"][s_class]  # [P,2]
    nz_node = col.take_rows(state.nonzero, safe_sel)
    alloc_rows = col.take_rows(nodes["alloc"], safe_sel)
    tot0 = nz_node + nz_z
    tot_r = nz_node + (r_eff[:, None] + 1) * nz_z
    dyn0 = _dyn_at(tot0[:, 0], tot0[:, 1], alloc_rows[:, 0], alloc_rows[:, 1],
                   priorities)
    dyn_r = _dyn_at(tot_r[:, 0], tot_r[:, 1], alloc_rows[:, 0],
                    alloc_rows[:, 1], priorities)
    score_r = col.take2(masked, s_class, safe_sel) - dyn0 + dyn_r
    acc_core = (s_place & same_run & (rank_in_seg < cap_lim)
                & (~special | (rank_in_seg == 0))
                & ((rank_in_seg == 0) | (score_r >= thr[s_class])))
    # prefix closure: rank r commits only if ranks 0..r-1 all did (the rank/
    # capacity math above assumes the accepted set is a contiguous prefix;
    # BalancedResourceAllocation is not monotone in r, so enforce explicitly)
    fail = (~acc_core).astype(jnp.int32)
    pre_fail = jnp.cumsum(fail) - fail  # failures strictly before each row
    acc_s = acc_core & ((pre_fail - pre_fail[bs]) == 0)
    accepted = jnp.zeros(P, dtype=bool).at[order].set(acc_s)

    # ---- commit (batched AssumePod) ---------------------------------------
    # scatter ids translate to LOCAL rows under _ShardCol (drop sentinel =
    # local width): each accepted row lands on exactly the shard owning its
    # node — the "one shard written per commit" half of the delta story
    nl = col.n_local
    seg_ids = col.to_local(jnp.where(acc_s, s_sel, -1))
    gain = acc_s.astype(jnp.int32)
    add_req = jax.ops.segment_sum(cls["req"][s_class] * gain[:, None],
                                  seg_ids, num_segments=nl + 1)[:nl]
    add_nz = jax.ops.segment_sum(cls["nonzero"][s_class] * gain[:, None],
                                 seg_ids, num_segments=nl + 1)[:nl]
    add_cnt = jax.ops.segment_sum(gain, seg_ids, num_segments=nl + 1)[:nl]
    requested = state.requested + add_req
    nonzero = state.nonzero + add_nz
    pod_count = state.pod_count + add_cnt
    # specials: at most one accepted per node -> direct batched scatters
    sp = acc_s & special
    sp_gain = sp.astype(jnp.int32)
    sp_sel = col.to_local(jnp.where(sp, s_sel, -1))
    ports = cls["ports"][s_class]  # [P,8]
    want = (ports >= 0) & sp[:, None]
    wsafe = jnp.maximum(ports, 0)
    words = jnp.where(want, wsafe // 32, state.port_bitmap.shape[1])
    bits = jnp.where(want, jnp.uint32(1) << (wsafe % 32).astype(jnp.uint32),
                     jnp.uint32(0))
    port_bitmap = state.port_bitmap.at[
        sp_sel[:, None], words].add(bits, mode="drop")
    vh = cls["vol_hard"][s_class]
    vr = cls["vol_ro"][s_class]
    pdq = cls["pd_req"][s_class]
    sp8 = sp[:, None].astype(jnp.int8)
    vol_present = state.vol_present.at[sp_sel].max((vh | vr) * sp8,
                                                   mode="drop")
    vol_rw = state.vol_rw.at[sp_sel].max(vh * sp8, mode="drop")
    pd_present = state.pd_present.at[sp_sel].max(pdq * sp8, mode="drop")
    # distinct new PD ids the pod brings to its node, per kind
    pd_new = []
    for k in range(3):
        req_k = pdq * nodes["pd_kind"][k][None, :]
        overlap = jnp.einsum("pv,pv->p", req_k.astype(jnp.int32),
                             col.take_rows(state.pd_present,
                                           safe_sel).astype(jnp.int32))
        pd_new.append(cls["pd_req_count"][s_class, k] - overlap)
    pd_counts = state.pd_counts.at[sp_sel].add(
        jnp.stack(pd_new, axis=1) * sp_gain[:, None], mode="drop")

    new_state = NodeState(requested, nonzero, pod_count, port_bitmap,
                          vol_present, vol_rw, pd_present, pd_counts)
    if aff is not None:
        # topology-occupancy commit: each accepted pod ticks its (class,
        # node) cell, making it visible to the NEXT wave's mask (and to
        # the seeded strict tail / harvest fence afterwards). Scatter-add
        # accumulates duplicate (class, node) pairs; rejected rows land on
        # the dropped column.
        committed = committed.at[
            s_class, col.to_local(jnp.where(acc_s, s_sel, -1))].add(
                gain, mode="drop")
    return sel, accepted, fc, new_state, new_counter, committed


@functools.partial(jax.jit, static_argnames=("priorities",))
def wave_step(cls, nodes, state, pod_class, active, counter, priorities):
    """Standalone single wave (tests/debugging); waves_loop is the fast path."""
    pre = precompute(cls, nodes, priorities)
    return _wave_once(cls, nodes, state, pre, pod_class, active, counter,
                      priorities)[:5]


def _waves_loop_inner(cls, nodes, state, pod_class, counter, pre,
                      committed0, active0, aff, priorities, max_waves, col):
    """The wave iteration proper — shared verbatim by the single-program
    path and the shard_map SPMD path (the `col` vtable is the only
    difference). Returns (packed, state, committed)."""
    P = pod_class.shape[0]

    def cond(carry):
        _, active, _, _, _, _, w = carry
        return (w < max_waves) & active.any()

    def body(carry):
        state, active, counter, fsel, ffc, committed, w = carry
        sel, accepted, fc, state2, counter2, committed2 = _wave_once(
            cls, nodes, state, pre, pod_class, active, counter, priorities,
            aff=aff, committed=committed, col=col)
        if aff is None:
            committed2 = committed
        placed = active & accepted
        fsel = jnp.where(placed, sel, fsel)
        ffc = jnp.where(active, fc, ffc)
        active2 = active & ~accepted & (sel >= 0)
        return (state2, active2, counter2, fsel, ffc, committed2, w + 1)

    init = (state, active0, counter,
            jnp.full(P, -1, dtype=jnp.int32), jnp.zeros(P, dtype=jnp.int32),
            committed0, jnp.int32(0))
    (state, active, counter, fsel, ffc, committed, w) = \
        lax.while_loop(cond, body, init)
    packed = jnp.concatenate([fsel, ffc, active.astype(jnp.int32),
                              counter.astype(jnp.int32)[None], w[None]])
    return packed, state, committed


@functools.partial(jax.jit, static_argnames=("weights",))
def frozen_affinity_scores(cls: Arrays, nodes: Arrays, state: NodeState,
                           aff: Arrays,
                           weights: Tuple[int, int]) -> jnp.ndarray:
    """SelectorSpread / InterPodAffinity scores [C, N] against the
    batch-frozen cluster state, for the wave engine's additive static score
    (weights = (w_interpod, w_spread)). Wave semantics score these once per
    BATCH, not per wave — within-batch drift of preferred-affinity/spread
    counts is a documented wave-mode approximation that also applies to
    required-(anti-)affinity classes riding the waves (ISSUE 3) — only the
    REQUIRED fit side is re-evaluated per wave; the preferred score stays
    batch-frozen. Pure int32 — no x64 required."""
    from kubernetes_tpu.ops import affinity as aff_ops

    w_ip, w_sp = weights
    fits = preds.static_fits(cls, nodes) \
        & preds.node_condition_fit(cls, nodes) \
        & _dynamic_fits(cls, nodes, state)
    extra = jnp.zeros(fits.shape, dtype=jnp.int32)
    if w_ip:
        # jnp einsum, not the Pallas incidence kernel: this matrix is also
        # computed with the node axis sharded over a mesh (test_mesh.py),
        # and a pallas_call is a custom call the SPMD partitioner cannot
        # split. The single-chip evaluate_pod path uses the kernel.
        # labels_aff (when present) is the projected domain incidence the
        # caller's aff arrays are sliced to (engine _aff_tail_arrays).
        lab = aff["labels_aff"] if "labels_aff" in aff else nodes["labels"]
        pre = aff_ops.precompute_static(aff, lab)
        extra = extra + w_ip * aff_ops.interpod_score(pre["prio_counts"],
                                                      fits)
    if w_sp:
        extra = extra + w_sp * aff_ops.spread_score(
            aff, aff["sp_has"], aff["sp_static"], fits)
    return extra


@functools.partial(jax.jit,
                   static_argnames=("priorities", "max_waves", "spmd_mesh"))
def waves_loop(cls: Arrays, nodes: Arrays, state: NodeState,
               pod_class: jnp.ndarray, counter: jnp.ndarray,
               priorities: Tuple[Tuple[str, int], ...],
               max_waves: int = 32,
               extra_score: jnp.ndarray = None,
               aff: Arrays = None,
               committed0: jnp.ndarray = None,
               active0: jnp.ndarray = None,
               pre: Arrays = None,
               spmd_mesh=None,
               ) -> Union[Tuple[jnp.ndarray, NodeState],
                          Tuple[jnp.ndarray, NodeState, jnp.ndarray]]:
    """The whole wave iteration as ONE device program (lax.while_loop over
    _wave_once) — a single dispatch + a single [3P+2] host fetch regardless
    of wave count; device sync latency dominates small fetches on a tunneled
    TPU, so per-wave host round-trips would swamp the kernel time.

    With `aff` (ISSUE 3): committed0 seeds the [C, N] per-node topology
    occupancy carry (the engine's cumulative fence-accepted commits, so
    earlier chunks' placements are visible) and the per-wave mask +
    occupancy commit run inside the loop; active0 masks out pods routed to
    the seeded strict tail (AffinityData.wave_strict) — they exit with
    selected = -1 and still_active = 0 and the harvest places them.

    With `spmd_mesh` (a jax.sharding.Mesh whose one axis is the node
    axis — ISSUE 12), the WHOLE loop runs under shard_map: every
    node-axis tensor stays resident on its shard, the winner selection is
    the explicit two-stage reduce of _ShardCol, and commits write exactly
    the shard owning each node. Placements are bit-identical to the
    single-program run (the vtable swaps op implementations, never
    semantics); pass None (default) everywhere a mesh is not resident.

    Returns (packed, final state[, committed]) with packed =
    [selected(P), fit_count(P), still_active(P), counter, waves_used];
    still_active pods exhausted max_waves (the host finishes them via the
    strict scan). The trailing occupancy is returned only when `aff` is
    given."""
    P = pod_class.shape[0]
    if pre is None:  # hoisted: while_loop bodies re-execute everything
        # every iteration and XLA cannot hoist for us; callers draining
        # many chunks pass the per-encoding cached instance instead
        pre = precompute(cls, nodes, priorities)
    if extra_score is not None:  # batch-frozen spread/interpod scores
        pre = dict(pre, static_score=pre["static_score"] + extra_score)
    if aff is not None:
        committed0 = committed0.astype(jnp.int32)
    else:  # inert carry keeps ONE loop structure for both trace variants
        committed0 = jnp.zeros((1, 1), dtype=jnp.int32)
    if active0 is None:
        active0 = jnp.ones(P, dtype=bool)
    n_global = nodes["alloc"].shape[0]
    if spmd_mesh is None:
        col = _GlobalCol(n_global)
        packed, state, committed = _waves_loop_inner(
            cls, nodes, state, pod_class, counter, pre, committed0,
            active0, aff, priorities, max_waves, col)
    else:
        packed, state, committed = _waves_loop_spmd(
            cls, nodes, state, pod_class, counter, pre, committed0,
            active0, aff, priorities, max_waves, spmd_mesh)
    if aff is None:
        return packed, state
    return packed, state, committed


def _waves_loop_spmd(cls, nodes, state, pod_class, counter, pre,
                     committed0, active0, aff, priorities, max_waves,
                     mesh):
    """waves_loop's shard_map wrapper: node-axis operands enter sharded
    (specs from parallel/mesh's shared tables), pod-side operands enter
    replicated, and _waves_loop_inner runs per shard with _ShardCol
    supplying the cross-device stages. check_rep is off: the replication
    checker cannot see through the ownership-masked psum combines, but
    every P()-spec output is replicated by construction (psum/pmax
    results and replicated-input math only)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as PS

    from kubernetes_tpu.parallel.mesh import (
        _NODE_SHARDED_KEYS,
        aff_spec,
    )

    axis = mesh.axis_names[0]
    n_global = nodes["alloc"].shape[0]
    n_dev = int(mesh.devices.size)
    col = _ShardCol(axis, n_global, n_global // n_dev)
    node_sp = PS(axis)
    rep = PS()

    def nspec(k):
        return node_sp if k in _NODE_SHARDED_KEYS else rep

    nodes_spec = {k: nspec(k) for k in nodes}
    state_spec = NodeState(*([node_sp] * len(state)))
    pre_spec = {k: PS(None, axis) for k in pre}
    cls_spec = {k: rep for k in cls}
    comm_spec = PS(None, axis) if aff is not None else rep
    args = [cls, nodes, state, pod_class, counter, committed0, active0]
    in_specs = [cls_spec, nodes_spec, state_spec, rep, rep, comm_spec, rep]
    # pre/aff ride as operands (shard_map forbids closed-over tracers)
    args.append(pre)
    in_specs.append(pre_spec)
    has_aff = aff is not None
    if has_aff:
        args.append(aff)
        in_specs.append({k: aff_spec(k) for k in aff})

    def inner(cls_, nodes_, state_, pc_, ctr_, comm_, act_, pre_,
              *maybe_aff):
        aff_ = maybe_aff[0] if maybe_aff else None
        return _waves_loop_inner(cls_, nodes_, state_, pc_, ctr_, pre_,
                                 comm_, act_, aff_, priorities, max_waves,
                                 col)

    return shard_map(inner, mesh=mesh,
                     in_specs=tuple(in_specs),
                     out_specs=(rep, state_spec, comm_spec),
                     check_rep=False)(*args)


@functools.partial(jax.jit, static_argnames=("priorities", "aff_mode"))
def tail_rounds_loop(cls: Arrays, nodes: Arrays, state: NodeState,
                     pod_class: jnp.ndarray, counter: jnp.ndarray,
                     priorities: Tuple[Tuple[str, int], ...],
                     aff: Arrays = None,
                     aff_mode: Tuple[bool, bool, bool] = (False, False, False),
                     aff_init=None,
                     pre: Arrays = None,
                     ) -> Tuple[jnp.ndarray, NodeState]:
    """The seeded strict tail as CONFLICT ROUNDS — one device program
    whose sequential depth is the number of rounds (a handful), not the
    number of tail pods (hundreds), with required-(anti-)affinity
    semantics EXACT at every commit.

    The per-pod scan (engine/batch.place_batch, still reachable via
    GRAFT_TAIL_ROUNDS=0) serializes the whole tail to keep two things
    exact: the affinity occupancy each pod evaluates against, and the
    classic one-at-a-time tie-break order. Only the first is a
    CONSTRAINT; the second is the same tie-spreading freedom every
    wave-mode class already trades away (PROFILE_r08 §6 — batch-defined
    RR fan-out instead of the classic serialized order). So each round:

      1. re-evaluates the REQUIRED mask for every class exactly against
         the cumulative occupancy carry (ops/affinity.step_fits_all over
         the projected domain columns — allow side, own anti, the
         symmetry direction, and the lone-bootstrap rule, bit-identical
         per class to the scan's per-step mask), plus exact capacity
         predicates and scores;
      2. places every still-active pod wave-style: FIFO prefix RR draws
         over the per-class tie sets, per-node FIFO conflict resolution
         with exact integer capacity and the score-aware window (the
         _wave_once discipline);
      3. gates the commits whose own effects the round-start mask cannot
         see: a class still BOOTSTRAPPING an allow-side group (no static
         or committed match yet) commits at most ONE pod per round — the
         group picks its domain serially, then fans out — and classes
         coupled through any required ANTI term (as source or target,
         m_aff is monotone-benign but m_anti is not) commit at most one
         pod per round ACROSS the whole coupled pool, so a commit can
         never invalidate a same-round placement made under the stale
         mask. Allow-satisfied, anti-free classes fan out freely: their
         masks can only widen as the round's commits land.
      4. retires placed pods; fit_count==0 pods stay active while ANY
         commit lands (an allow-side commit may widen their mask — the
         scan's order-dependent schedulability, reproduced round-
         granular) and retire as unschedulable the first round nothing
         commits, which is also the loop exit.

    Every round with a placeable pod commits at least one (the first
    active pod survives per-node rank-0 resolution and every quota), so
    the loop terminates in <= P+1 rounds; the typical mixed-affinity
    tail is one bootstrap round per co-location group plus one or two
    fan-out rounds. Placements stay deterministic — the pipelined ==
    sequential (overlap=False) A/B holds bit-exactly — but tie-breaks
    follow wave semantics, the same documented divergence as every
    other wave-path class. Spread scoring is not modeled here (the
    harvest tail never runs it).

    Returns (packed, final NodeState) with packed =
    [selected(P), fit_count(P), counter, rounds_used]."""
    from kubernetes_tpu.engine.batch import check_affinity_priorities
    from kubernetes_tpu.ops import affinity as aff_ops

    fits_on, prio_on, spread_on = aff_mode
    if spread_on:
        raise ValueError("tail_rounds_loop does not model spread scoring "
                         "(the harvest tail runs with spread off)")
    check_affinity_priorities(priorities, aff, None)
    any_aff = aff is not None and (fits_on or prio_on)
    P = pod_class.shape[0]
    N = nodes["alloc"].shape[0]
    C = cls["req"].shape[0]
    iota = jnp.arange(P, dtype=jnp.int32)
    idx_n = jnp.arange(N, dtype=jnp.int32)
    if pre is None:
        pre = precompute(cls, nodes, priorities)
    w_ip = sum(w for nm, w in priorities
               if nm == "InterPodAffinityPriority") if prio_on else 0
    if any_aff:
        labels = aff["labels_aff"] if "labels_aff" in aff \
            else nodes["labels"]
        pre_aff = aff_ops.precompute_static(aff, labels)
        l_dim = labels.shape[1]
        # anti-coupled pool: classes that appear in ANY required anti
        # relation, as matching target or term owner — their commits can
        # shrink a same-round mask, so the pool shares one commit quota
        m_anti_b = aff["m_anti"].astype(bool)
        anti_pool = m_anti_b.any(axis=(1, 2)) | m_anti_b.any(axis=(0, 1))
        boot_candidate = (aff["aff_active"] & ~aff["aff_has_static"])
    else:
        labels = jnp.zeros((N, 1), dtype=jnp.int8)
        pre_aff = None
        l_dim = 1
        anti_pool = jnp.zeros(C, dtype=bool)
        boot_candidate = None
    if aff_init is not None:
        commdom0, committed0, comm_cnt0 = aff_init
        commdom0 = commdom0.astype(jnp.int32)
        committed0 = committed0.astype(jnp.int32)
        comm_cnt0 = comm_cnt0.astype(jnp.int32)
    else:
        commdom0 = jnp.zeros((C, l_dim), dtype=jnp.int32)
        committed0 = jnp.zeros((C, N), dtype=jnp.int32)
        comm_cnt0 = jnp.zeros(C, dtype=jnp.int32)
    special_base = ((cls["ports"][:, 0] >= 0)
                    | (cls["vol_hard"].sum(axis=1) + cls["vol_ro"].sum(axis=1)
                       + cls["pd_req"].sum(axis=1) > 0))

    def cond(carry):
        active = carry[1]
        w = carry[-1]
        return active.any() & (w <= P)

    def body(carry):
        (state, active, counter, fsel, ffc, commdom, committed,
         comm_cnt, w) = carry
        # ---- exact round-start evaluation, class-level [C, N] -----------
        fits_c = pre["static_fit"] & preds.node_condition_fit(cls, nodes) \
            & _dynamic_fits(cls, nodes, state)
        if fits_on:
            fits_c = fits_c & aff_ops.step_fits_all(aff, pre_aff, commdom,
                                                    comm_cnt, labels)
        scores_c = _wave_scores(cls, nodes, state, pre, fits_c, priorities)
        if prio_on:
            cnt = aff_ops.step_prio_counts_all(aff, pre_aff, commdom,
                                               labels)
            scores_c = scores_c + w_ip * aff_ops.interpod_score(cnt, fits_c)
        # ---- wave-style selection (the _wave_once discipline) -----------
        # NOTE: steps 2/4 below mirror _wave_once's tie-selection, per-node
        # FIFO conflict resolution, score window, and commit scatters with
        # only the fits source and the round-quota gate differing. A fix
        # to the acceptance math there (K_WAVE analysis, prefix closure,
        # port/volume scatters) must be applied HERE too — the tail and
        # the wave loop are tested to agree on those semantics.
        fitcnt = fits_c.sum(axis=1).astype(jnp.int32)
        masked = jnp.where(fits_c, scores_c, jnp.int32(-1))
        best = masked.max(axis=1, keepdims=True)
        ties = (masked == best) & fits_c
        m = ties.sum(axis=1).astype(jnp.int32)
        rank = jnp.cumsum(ties.astype(jnp.int32), axis=1) - 1
        cols = jnp.where(ties, rank, N)
        rows = jnp.broadcast_to(jnp.arange(ties.shape[0])[:, None],
                                ties.shape)
        tiemat = jnp.zeros(ties.shape, dtype=jnp.int32).at[rows, cols].set(
            jnp.broadcast_to(idx_n[None, :], ties.shape), mode="drop")
        fc = fitcnt[pod_class]
        multi = active & (fc > 1)
        draw = counter.astype(jnp.int32) \
            + jnp.cumsum(multi.astype(jnp.int32)) - multi.astype(jnp.int32)
        mz = jnp.maximum(m[pod_class], 1)
        kz = (draw % mz).astype(jnp.int32)
        sel_multi = tiemat[pod_class, kz]
        sel_single = jnp.argmax(fits_c, axis=1).astype(jnp.int32)[pod_class]
        sel = jnp.where(~active | (fc == 0), jnp.int32(-1),
                        jnp.where(fc == 1, sel_single, sel_multi))
        new_counter = counter + multi.sum().astype(jnp.uint32)
        # ---- per-node FIFO conflict resolution --------------------------
        placeable = sel >= 0
        key = jnp.where(placeable, sel, N) * P + iota
        order = jnp.argsort(key)
        s_sel = sel[order]
        s_class = pod_class[order]
        s_place = placeable[order]
        seg_start = jnp.concatenate(
            [jnp.ones(1, dtype=bool), s_sel[1:] != s_sel[:-1]])
        bs = jax.lax.cummax(jnp.where(seg_start, iota, 0))
        rank_in_seg = iota - bs
        first_class = s_class[bs]
        same_run = jnp.cumsum((s_class != first_class).astype(jnp.int32))
        same_run = (same_run - same_run[bs]) == 0
        cap = _class_capacity(cls, nodes, state)
        safe_sel = jnp.maximum(s_sel, 0)
        cap_lim = jnp.minimum(cap[s_class, safe_sel], K_WAVE)
        special = special_base[s_class]
        thr = jnp.where(ties, jnp.int32(-1), masked).max(axis=1)
        r_eff = jnp.minimum(rank_in_seg, cap_lim)
        nz_z = cls["nonzero"][s_class]
        nz_node = state.nonzero[safe_sel]
        alloc_rows = nodes["alloc"][safe_sel]
        tot0 = nz_node + nz_z
        tot_r = nz_node + (r_eff[:, None] + 1) * nz_z
        dyn0 = _dyn_at(tot0[:, 0], tot0[:, 1], alloc_rows[:, 0],
                       alloc_rows[:, 1], priorities)
        dyn_r = _dyn_at(tot_r[:, 0], tot_r[:, 1], alloc_rows[:, 0],
                        alloc_rows[:, 1], priorities)
        score_r = masked[s_class, safe_sel] - dyn0 + dyn_r
        acc_core = (s_place & same_run & (rank_in_seg < cap_lim)
                    & (~special | (rank_in_seg == 0))
                    & ((rank_in_seg == 0) | (score_r >= thr[s_class])))
        fail = (~acc_core).astype(jnp.int32)
        pre_fail = jnp.cumsum(fail) - fail
        acc_s = acc_core & ((pre_fail - pre_fail[bs]) == 0)
        accepted = jnp.zeros(P, dtype=bool).at[order].set(acc_s)
        # ---- the round gates (step 3 of the docstring) ------------------
        if any_aff:
            # boot_pending[c]: some active allow term has neither a static
            # nor a committed match — this round's commit IS the group's
            # domain choice, so it must be singular
            dyn_total = jnp.einsum("csd,d->cs",
                                   aff["m_aff"].astype(jnp.int32), comm_cnt)
            boot_pending = (boot_candidate & (dyn_total == 0)).any(axis=1)
            # quota group per class: bootstrapping classes serialize
            # individually (group id = class index); the anti-coupled pool
            # shares ONE group (id = C); everyone else is unquota'd
            qgroup = jnp.where(anti_pool, jnp.int32(C),
                               jnp.where(boot_pending,
                                         jnp.arange(C, dtype=jnp.int32),
                                         jnp.int32(-1)))
            g = qgroup[pod_class]                             # [P]
            member = accepted & (g >= 0)
            oh = (member[:, None]
                  & (g[:, None] == jnp.arange(C + 1, dtype=jnp.int32)[None, :]))
            rank_in_group = jnp.cumsum(oh.astype(jnp.int32), axis=0) \
                - oh.astype(jnp.int32)
            keep = ~member | (rank_in_group[iota, jnp.maximum(g, 0)] == 0)
            accepted = accepted & keep
            acc_s = accepted[order]
        # ---- commit (batched AssumePod, dropped pods stay active) -------
        seg_ids = jnp.where(acc_s, s_sel, N)
        gain = acc_s.astype(jnp.int32)
        add_req = jax.ops.segment_sum(cls["req"][s_class] * gain[:, None],
                                      seg_ids, num_segments=N + 1)[:N]
        add_nz = jax.ops.segment_sum(cls["nonzero"][s_class] * gain[:, None],
                                     seg_ids, num_segments=N + 1)[:N]
        add_cnt = jax.ops.segment_sum(gain, seg_ids, num_segments=N + 1)[:N]
        requested = state.requested + add_req
        nonzero = state.nonzero + add_nz
        pod_count = state.pod_count + add_cnt
        sp = acc_s & special
        sp_gain = sp.astype(jnp.int32)
        sp_sel = jnp.where(sp, s_sel, N)
        ports = cls["ports"][s_class]
        want = (ports >= 0) & sp[:, None]
        wsafe = jnp.maximum(ports, 0)
        words = jnp.where(want, wsafe // 32, state.port_bitmap.shape[1])
        bits = jnp.where(want,
                         jnp.uint32(1) << (wsafe % 32).astype(jnp.uint32),
                         jnp.uint32(0))
        port_bitmap = state.port_bitmap.at[
            jnp.where(sp, s_sel, N)[:, None], words].add(bits, mode="drop")
        vh = cls["vol_hard"][s_class]
        vr = cls["vol_ro"][s_class]
        pdq = cls["pd_req"][s_class]
        sp8 = sp[:, None].astype(jnp.int8)
        vol_present = state.vol_present.at[sp_sel].max((vh | vr) * sp8,
                                                       mode="drop")
        vol_rw = state.vol_rw.at[sp_sel].max(vh * sp8, mode="drop")
        pd_present = state.pd_present.at[sp_sel].max(pdq * sp8, mode="drop")
        pd_new = []
        for k in range(3):
            req_k = pdq * nodes["pd_kind"][k][None, :]
            overlap = jnp.einsum("pv,pv->p", req_k.astype(jnp.int32),
                                 state.pd_present[safe_sel].astype(jnp.int32))
            pd_new.append(cls["pd_req_count"][s_class, k] - overlap)
        pd_counts = state.pd_counts.at[sp_sel].add(
            jnp.stack(pd_new, axis=1) * sp_gain[:, None], mode="drop")
        new_state = NodeState(requested, nonzero, pod_count, port_bitmap,
                              vol_present, vol_rw, pd_present, pd_counts)
        # occupancy carry: committed pods become visible to the NEXT
        # round's exact mask
        sel_safe_p = jnp.maximum(sel, 0)
        gain_p = accepted.astype(jnp.int32)
        commdom = commdom.at[pod_class].add(
            labels[sel_safe_p].astype(jnp.int32) * gain_p[:, None])
        committed = committed.at[
            pod_class, jnp.where(accepted, sel, N)].add(gain_p, mode="drop")
        comm_cnt = comm_cnt.at[pod_class].add(gain_p)
        # ---- retire: placed pods always; fit_count==0 pods only once a
        # round commits nothing (an allow-side commit may still widen
        # their mask) — which is also the loop's natural exit
        none_committed = ~accepted.any()
        retire_unsched = active & (fc == 0) & none_committed
        done = accepted | retire_unsched
        fsel = jnp.where(accepted, sel, fsel)
        ffc = jnp.where(done, fc, ffc)
        return (new_state, active & ~done, new_counter, fsel, ffc,
                commdom, committed, comm_cnt, w + 1)

    init = (state, jnp.ones(P, dtype=bool), counter,
            jnp.full(P, -1, dtype=jnp.int32), jnp.zeros(P, dtype=jnp.int32),
            commdom0, committed0, comm_cnt0, jnp.int32(0))
    (state, _active, counter, fsel, ffc, _cd, _cm, _cc, w) = \
        lax.while_loop(cond, body, init)
    packed = jnp.concatenate([fsel, ffc,
                              counter.astype(jnp.int32)[None], w[None]])
    return packed, state


def place_waves(cls: Arrays, nodes: Arrays, state: NodeState,
                pod_class: np.ndarray, counter: int,
                priorities: Tuple[Tuple[str, int], ...],
                max_waves: int = 64,
                extra_score: jnp.ndarray = None,
                aff: Arrays = None,
                aff_mode: Tuple[bool, bool, bool] = (False, False, False),
                ) -> Tuple[np.ndarray, np.ndarray, NodeState, int]:
    """Run waves until every pod is placed or proven unplaceable — one
    device program (waves_loop) + one host fetch. Returns (selected [P]
    int32 node index or -1, fit_count [P], final NodeState, final counter).
    Each non-empty conflict segment commits at least its first pod per wave,
    so the device loop terminates in <= P waves (typically 1-3)."""
    P = len(pod_class)
    packed, state = waves_loop(cls, nodes, state, jnp.asarray(pod_class),
                               jnp.uint32(counter), priorities, max_waves,
                               extra_score)
    packed_h = np.asarray(packed)  # graftlint: sync-ok — the ONLY
    # blessed device->host sync on the classic wave path: one [3P+2]
    # fetch for the whole drain round, everything before it is one
    # async device program
    final_sel = packed_h[:P].copy()
    final_fc = packed_h[P:2 * P].copy()
    act_h = packed_h[2 * P:3 * P].astype(bool)
    counter_h = int(np.uint32(packed_h[3 * P]))
    if act_h.any():
        # pathological interleaving exhausted max_waves: finish the
        # stragglers strictly. The straggler count is padded to a bucket
        # (inert rows) so this rare path doesn't mint a compile per count.
        idx = np.nonzero(act_h)[0]
        n_strag = len(idx)
        if bool(np.asarray(cls["impossible"][-1])):
            pad_class = cls["req"].shape[0] - 1  # inert padding class row
            pc = np.full(preds.bucket(n_strag), pad_class, dtype=np.int32)
        else:  # caller passed unpadded class arrays: no inert row to map to
            pc = np.empty(n_strag, dtype=np.int32)
        pc[:n_strag] = pod_class[idx]
        # thread the affinity class data through so priorities containing
        # SelectorSpread/InterPodAffinity don't trip place_batch's guard
        # when extra_score is None (fits-only affinity batches)
        sel, fcs, state, counter_d = gather_place_batch(
            cls, jnp.asarray(pc), nodes, state, jnp.uint32(counter_h),
            priorities, aff=aff, aff_mode=aff_mode, extra_score=extra_score)
        # rare straggler finish (max_waves exhausted): a second fetch is
        # the cost of correctness here, not a hot-path stall
        final_sel[idx] = np.asarray(sel)[:n_strag]  # graftlint: sync-ok
        final_fc[idx] = np.asarray(fcs)[:n_strag]  # graftlint: sync-ok
        counter_h = int(counter_d)  # graftlint: sync-ok (scalar, idle)
    return final_sel, final_fc, state, counter_h
