"""Wave-path preemption: planning + disruption budgeting (ISSUE 14).

The classic host round (`Scheduler._preempt_round` over
engine/preemption.py) flushes the pipeline, builds O(total pods) arrays
per round, deletes victims best-effort, and leaves the preemptor to
reschedule whenever the DELETED events drain — the flush-everything
escape hatch. This module is the always-on form:

- ``plan_wave_preemptions`` narrows candidate nodes with ONE fused
  device dispatch over the snapshot's priority-band tensors
  (``SchedulingEngine.preempt_scan`` -> ops/preempt.victim_scan_jit),
  then verifies candidates EXACTLY with the classic reprieve loop
  (``preemption._select_victims``) against a copy-on-write overlay of
  the live NodeInfos — multi-preemptor rounds reserve holes the way the
  classic round does, without cloning the whole cluster. Because the
  device mask is a proved superset of the classic pre-filter and the
  exact verification + node-choice ordering are shared code, plans are
  identical to the classic round's whenever the candidate set fits the
  exact-verification budget (the fuzz A/B in tests/test_preempt_wave.py
  pins it). PAST ``MAX_VERIFIED_CANDIDATES`` both paths truncate their
  exact phase — classic by exact ``tight_bounds`` over its narrower
  mask, the wave path by the device ``bound`` over its superset — and
  the truncated sets can differ: the same approximation class the
  reference's percentageOfNodesToScore accepts, traded deliberately
  (an exact bound would need the O(total pods) host build the device
  scan exists to kill).

- ``DisruptionBudget`` rate-limits the commits PodDisruptionBudget-
  style: a global max-evictions-per-minute sliding window plus optional
  per-band floors (a priority band must keep at least ``floor`` pods
  bound cluster-wide). Tiresias' lesson (PAPERS.md §Tiresias):
  preemption pays off only when its victim churn is bounded and
  measured — denied plans count ``engine.preempt_budget_deferred`` and
  the preemptor simply waits out its backoff.

The COMMIT itself lives in ``Scheduler._preempt_wave``: every plan goes
through the store's atomic evict+bind op, so partial preemptions are
impossible by construction (see apiserver_lite.preempt_pods_bulk).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.engine.preemption import (
    MAX_VERIFIED_CANDIDATES,
    PreemptionState,
    _select_victims,
)
from kubernetes_tpu.observability import podtrace
from kubernetes_tpu.observability.podtrace import TRACER


@dataclass
class WavePreemption:
    """One planned displacement: evict ``victims`` (lowest priority
    first) from ``node_name`` and bind ``pod`` there — committed
    atomically or not at all."""

    pod: Pod
    node_name: str
    victims: List[Pod] = field(default_factory=list)


def plan_wave_preemptions(engine, preemptors: List[Pod], *,
                          evictable: Optional[Callable[[Pod], bool]] = None,
                          workloads=(),
                          max_per_round: int = 128
                          ) -> List[WavePreemption]:
    """Plan displacements for a round of unschedulable preemptors.

    Highest priority first (ties keep input order, like the classic
    round's sort). Candidate nodes come from the device victim scan —
    or, when the band vocab overflowed, from the classic host pre-filter
    — and every candidate is verified exactly against the round's
    copy-on-write overlay, so plan k+1 sees plan k's reservations.
    The engine's snapshot must be refreshed (the harvest that produced
    the preemptors already did)."""
    from kubernetes_tpu.ops.oracle_ext import SchedulingContext
    from kubernetes_tpu.utils.trace import COUNTERS

    cands = [p for p in preemptors if p.priority > 0]
    if not cands:
        return []
    order = sorted(range(len(cands)), key=lambda i: -cands[i].priority)
    cands = [cands[i] for i in order][:max_per_round]
    snap = engine.snapshot
    names = snap.node_names
    if not names:
        return []
    # copy-on-write overlay over the LIVE infos: reads are free, a
    # chosen node is cloned once — never the O(total pods) wholesale
    # clone the classic round pays
    view: Dict[str, object] = dict(engine.cache.node_infos())
    ctx = SchedulingContext(
        view, list(workloads),
        hard_pod_affinity_weight=engine.hard_pod_affinity_weight,
        volume_ctx=engine.volume_ctx,
        policy_algos=engine.policy_algos)
    scan = engine.preempt_scan(cands)
    host_state = None
    if scan is None:
        # band-vocab overflow / bands unavailable: the exact host
        # pre-filter (one O(total pods) build per round, classic shape)
        host_state = PreemptionState(view)
        COUNTERS.inc("engine.preempt_scan_host_fallback")
    n_real = len(names)
    name_index = snap.node_index
    touched: set = set()
    plans: List[WavePreemption] = []
    # per-class verification memo: a burst of same-class preemptors (the
    # overcommit storm shape — hundreds of one band) re-verifies only
    # the nodes this round's plans TOUCHED; untouched nodes' victim sets
    # are state-deterministic and reused. Exact only when nothing
    # couples nodes (pod affinity makes node j's feasibility depend on
    # node i's residents; workloads/Policy algos likewise) — gated off
    # wholesale then, falling back to the classic per-candidate cost.
    from kubernetes_tpu.ops.affinity import _has_affinity
    from kubernetes_tpu.state.classes import pod_class_key
    memo_ok = (not workloads
               and (engine.policy_algos is None
                    or not engine.policy_algos.active)
               and not any(getattr(i, "pods_with_affinity", None)
                           for i in view.values()))
    vmemo: Dict[tuple, Dict[int, Optional[tuple]]] = {}
    for k, pod in enumerate(cands):
        if scan is not None:
            cand_np, bound_np, class_of = scan
            row = cand_np[class_of[k]][:n_real]
            cand_idx = np.flatnonzero(row)
            bounds = bound_np[class_of[k]]
        else:
            mask = host_state.candidate_mask(pod)
            cand_idx = np.flatnonzero(mask[:n_real])
            bounds = None
        if len(cand_idx) > MAX_VERIFIED_CANDIDATES:
            if bounds is None:
                bounds = host_state.tight_bounds(pod)
            rk = np.argsort(bounds[cand_idx], kind="stable")
            cand_idx = cand_idx[rk][:MAX_VERIFIED_CANDIDATES]
        # node choice == classic pickOneNodeForPreemption: the classic
        # round verifies every candidate and keeps the first strictly-
        # smaller key, i.e. min over ((key), node index). Verifying in
        # device-BOUND-ascending order lets us stop early: bound[n] is a
        # LOWER bound on node n's achievable max-victim-priority (the
        # over-approximated freeable can only flatter it), so once every
        # remaining candidate's bound exceeds the best key's first
        # component, none can win — candidates tied on that component
        # all have bound <= it and were already verified, so the choice
        # is exactly the classic one.
        best = None  # ((key, node index), victims)
        node_memo = None
        if memo_ok and not _has_affinity(pod):
            node_memo = vmemo.setdefault(pod_class_key(pod), {})

        def _verify(i: int) -> None:
            nonlocal best
            res = node_memo.get(i, False) if node_memo is not None \
                else False
            if res is False:
                info = view.get(names[i])
                if info is None:
                    res = None
                else:
                    victims = _select_victims(pod, info, ctx=ctx,
                                              evictable=evictable)
                    res = None if not victims else (
                        (max(v.priority for v in victims),
                         sum(v.priority for v in victims),
                         len(victims)), victims)
                if node_memo is not None:
                    node_memo[i] = res
            if res is None:
                return
            key = (res[0], i)
            if best is None or key < best[0]:
                best = (key, res[1])

        # touched nodes first: their device rows predate this round's
        # reservations, so they are verified unconditionally against the
        # overlay (they are few — one per plan this round)
        for i in sorted(touched):
            if i < n_real:
                _verify(i)
        if scan is not None:
            order = cand_idx[np.argsort(bounds[cand_idx], kind="stable")]
            for i in order:
                i = int(i)
                if i in touched:
                    continue
                if best is not None and int(bounds[i]) > best[0][0][0]:
                    break
                _verify(i)
        else:
            for i in sorted(set(int(x) for x in cand_idx) - touched):
                _verify(i)
        if best is None:
            continue
        (_key, i), victims = best
        name = names[i]
        # reserve in the overlay: victims out, preemptor's request in —
        # the classic round's infos bookkeeping, copy-on-write
        clone = view[name].clone_shallow()
        for vic in victims:
            clone.remove_pod(vic)
        clone.add_pod(pod)
        view[name] = clone
        touched.add(int(name_index.get(name, i)))
        for nc in vmemo.values():  # node i moved: memoized victim sets
            nc.pop(i, None)        # for it are stale for every class
        if memo_ok and _has_affinity(pod):
            # an affinity-CARRYING preemptor just entered the overlay:
            # it couples nodes (its anti terms forbid OTHER nodes'
            # domains), so every memoized row is suspect from here on
            memo_ok = False
            vmemo.clear()
        ctx.infos = view
        ctx.invalidate()
        if host_state is not None:
            from kubernetes_tpu.engine.preemption import PreemptionPlan
            host_state.apply_plan(
                PreemptionPlan(node_name=name, victims=victims), pod)
        plans.append(WavePreemption(pod=pod, node_name=name,
                                    victims=victims))
        if TRACER.enabled and victims:
            # pod-level black box (ISSUE 15): a planned victim visible
            # mid-requeue gets its PREEMPT_VICTIM stamp (host ints only;
            # the node row is the snapshot index already in hand)
            TRACER.batch_event(podtrace.PREEMPT_VICTIM,
                               [vic.key() for vic in victims],
                               a=name_index.get(name, -1))
    return plans


class DisruptionBudget:
    """PodDisruptionBudget-shaped rate limit on preemption evictions.

    ``max_evictions_per_min``: sliding 60 s window over COMMIT ATTEMPTS
    (an attempt whose evictions may have landed must consume budget even
    if the scheduler later treats it as rolled back — the at-most-once
    ambiguity cuts toward consuming). ``band_floor`` maps a priority
    value to the minimum number of pods of that band that must remain
    bound cluster-wide; a plan whose victims would breach any floor is
    denied whole (no partial trimming — the victim set is minimal for
    its node, trimming it would break the fit)."""

    WINDOW_S = 60.0

    def __init__(self, max_evictions_per_min: Optional[int] = 600,
                 band_floor: Optional[Dict[int, int]] = None,
                 now: Callable[[], float] = time.monotonic):
        self.max_evictions_per_min = max_evictions_per_min
        self.band_floor = dict(band_floor or {})
        self._now = now
        self._events: deque = deque()  # eviction instants in the window

    def _prune(self, now: float) -> None:
        cutoff = now - self.WINDOW_S
        ev = self._events
        while ev and ev[0] <= cutoff:
            ev.popleft()

    def window_evictions(self) -> int:
        """Evictions consumed inside the current sliding window."""
        self._prune(self._now())
        return len(self._events)

    def admit(self, victims: List[Pod],
              band_counts: Optional[Dict[int, int]] = None) -> bool:
        """Admit-and-consume for one plan's victim set; False = deferred
        (nothing consumed)."""
        now = self._now()
        self._prune(now)
        if self.max_evictions_per_min is not None \
                and len(self._events) + len(victims) \
                > self.max_evictions_per_min:
            return False
        if self.band_floor and band_counts is not None:
            per: Dict[int, int] = {}
            for v in victims:
                per[v.priority] = per.get(v.priority, 0) + 1
            for prio, n in per.items():
                floor = self.band_floor.get(prio)
                if floor is not None \
                        and band_counts.get(prio, 0) - n < floor:
                    return False
        self._events.extend([now] * len(victims))
        return True


__all__ = ["DisruptionBudget", "WavePreemption", "plan_wave_preemptions"]
