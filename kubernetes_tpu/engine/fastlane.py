"""Sparrow fast lane: a sub-10 ms admission tier beside the bulk waves
(ISSUE 17).

The streaming engine's 250 ms budget is a THROUGHPUT budget: a pod waits
for a micro-wave quantum to fill, rides a [C, N] fused eval, and binds in
a bulk flush. A latency-critical pod (serving sidecar, scale-up replica
mid-spike) needs none of that machinery and can't afford any of it. This
module is the Sparrow answer (PAPERS.md §Sparrow — batch sampling + late
binding) grafted onto the resident state the wave engine already keeps:

- **power-of-k-choices sampling**: draw k (~16) node rows weighted toward
  CPU headroom from the snapshot's cached ``headroom_view`` — O(k) host
  work against arrays that already exist;
- **one tiny eval**: score exactly those k rows with
  ``ops.fastlane.sample_eval`` — a [1, k] gather-eval against the
  RESIDENT device snapshot (no encoding build, no vocab work, compiled
  once per shape) — or its bit-equal numpy twin when a bulk wave owns
  the device (the CPU backend runs device programs FIFO, so a dispatch
  behind an in-flight wave would inherit the wave's whole latency);
- **late binding through the fence**: the sampled score is advisory; the
  winner is re-validated against LIVE cache truth (doomed notes first,
  then liveness/capacity/ports — the same checks the wave harvest and
  the extender's _bind_fence apply) and assumed through the cache's
  double-claim guard, so wave-path correctness and the exactly-once
  ledger are untouched. A fence loss resamples with jitter (the rng
  advances, so retries draw different nodes); after bounded retries the
  pod falls back to the wave path and is never lost.

Eligibility is deliberately narrow (``eligible``): latency-critical AND
"simple" — no affinity, no selector, no tolerations, no host ports, no
volumes, no gang, no extended resources, not pre-bound. Everything the
[1, k] kernel doesn't model is excluded by construction, and one
cluster-wide gate handles the k8s-1.8 symmetry trap: an EXISTING pod's
anti-affinity can forbid a new plain pod, so the fast lane only runs
while ``cache.affinity_pod_count() == 0`` — otherwise pods take the wave
path, which models affinity exactly.

Outcome accounting partitions every fast pod exactly once:
``fastlane.bound`` + ``fastlane.fell_back`` + ``fastlane.bind_error`` +
``fastlane.superseded`` == fast pods popped; ``fastlane.resampled``
counts fence/no-fit retries within attempts (not pods).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Optional, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.engine import gang as gangmod
from kubernetes_tpu.observability import recorder as flightrec
from kubernetes_tpu.observability.podtrace import (
    FAST_DISPATCHED,
    TRACER,
)
from kubernetes_tpu.observability.recorder import RECORDER
from kubernetes_tpu.observability.slo import SLO_FAST
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops.fastlane import (
    FAST_NODE_KEYS,
    sample_eval,
    sample_eval_host,
)
from kubernetes_tpu.utils.trace import COUNTERS

# the annotation contract: "scheduling.k8s.io/latency-critical" = "true"
# routes a pod to the fast tier; alternatively any priority at or above
# the band floor (GRAFT_FASTLANE_PRIO) qualifies — both knobs documented
# in README "Latency tiers"
FASTLANE_ANNOTATION = "scheduling.k8s.io/latency-critical"

DEFAULT_K = int(os.environ.get("GRAFT_FASTLANE_K", 16))
DEFAULT_RETRIES = int(os.environ.get("GRAFT_FASTLANE_RETRIES", 3))
FAST_PRIO = int(os.environ.get("GRAFT_FASTLANE_PRIO", 2_000_000_000))


def is_latency_critical(pod: Pod) -> bool:
    """The tier contract: explicit annotation, or priority at/above the
    fast band floor."""
    v = pod.annotations.get(FASTLANE_ANNOTATION, "")
    if v in ("true", "1"):
        return True
    return pod.priority >= FAST_PRIO


def eligible(pod: Pod) -> bool:
    """Latency-critical AND simple enough for the [1, k] kernel. Anything
    here that returns False takes the bulk wave path, which models the
    full predicate set exactly — the fast lane never approximates, it
    declines."""
    if not is_latency_critical(pod):
        return False
    if pod.node_name:  # pre-bound / PodFitsHost constrained
        return False
    if pod.affinity is not None or pod.node_selector:
        return False
    if pod.tolerations:  # kernel assumes toleration-free (any-taint fails)
        return False
    if pod.volumes:
        return False
    if gangmod.gang_name(pod) is not None:
        return False
    if pod.used_ports():
        return False
    for c in pod.containers:
        for k in c.requests:
            if k not in ("cpu", "memory", "nvidia.com/gpu",
                         "storage.kubernetes.io/scratch",
                         "storage.kubernetes.io/overlay"):
                return False  # extended resource: vocab-dependent row
    return True


class FastLane:
    """Per-scheduler fast-lane executor. Owned and driven by the
    streaming loop between micro-waves; everything it touches is either
    resident host state or the one sampled eval."""

    # a fast pod seen within this window keeps the harvest-overlap poll
    # alive (ScheduleLoop polls for fast arrivals while blocked on a
    # wave); outside it the loop reverts to the exact r18 step shape
    HOT_WINDOW_S = 1.0

    def __init__(self, scheduler, k: int = 0, retries: int = -1,
                 seed: int = 0x5bdd):
        self.s = scheduler
        self.engine = scheduler.engine
        self.cache = scheduler.cache
        self.queue = scheduler.queue
        self.k = k or DEFAULT_K
        self.retries = retries if retries >= 0 else DEFAULT_RETRIES
        # seeded: resample jitter comes from the rng ADVANCING between
        # attempts, reproducibly — frozen-trace A/Bs stay deterministic
        self._rng = random.Random(seed)
        self._cum = None  # cached cumsum of headroom weights
        self._cum_version = -1
        self._seen = 0
        self._last_seen = 0.0

    # ------------------------------------------------------------ admission

    def classify(self, pod: Pod) -> bool:
        """The queue's fast_classifier: route + note activity (the
        streaming loop's poll gate keys on it)."""
        if not eligible(pod):
            return False
        self._seen += 1
        self._last_seen = time.monotonic()
        return True

    def hot(self) -> bool:
        """A fast pod was routed recently — worth polling for more while
        a wave blocks. False forever if none ever arrives, so the A/B
        with zero latency-critical pods never takes a single extra
        branch of work."""
        return self._seen > 0 and \
            time.monotonic() - self._last_seen < self.HOT_WINDOW_S

    # ------------------------------------------------------------- sampling

    def _sample(self, snap) -> Optional[np.ndarray]:
        """k weighted draws (with replacement) from the headroom view —
        power-of-k-choices. Fixed k keeps the jitted eval at ONE compiled
        shape; duplicates are harmless (argmax picks one)."""
        weights, _ok = snap.headroom_view()
        if self._cum_version != snap.version or self._cum is None:
            self._cum = np.cumsum(weights)
            self._cum_version = snap.version
        cum = self._cum
        if cum.shape[0] == 0 or cum[-1] <= 0.0:
            return None  # no plausible row anywhere
        rng = self._rng
        total = float(cum[-1])
        draws = np.asarray([rng.random() for _ in range(self.k)]) * total
        idx = np.searchsorted(cum, draws, side="right")
        return np.minimum(idx, cum.shape[0] - 1).astype(np.int32)

    # ----------------------------------------------------------------- eval

    def _eval(self, idx: np.ndarray, req: np.ndarray, zero_req: bool,
              best_effort: bool, snap, device_ok: bool
              ) -> Tuple[np.ndarray, bool]:
        """Route the sampled eval: the resident DEVICE arrays when the
        device is idle and current, else the numpy twin (same verdicts,
        test-pinned). Never uploads, never refreshes — staleness is the
        fence's job."""
        dev = self.engine._device_nodes
        if device_ok and dev is not None \
                and self.engine._device_version == snap.version \
                and all(k in dev for k in FAST_NODE_KEYS):
            nodes = {k: dev[k] for k in FAST_NODE_KEYS}
            out = sample_eval(idx, req, zero_req, best_effort, nodes)
            res = np.asarray(out)  # graftlint: sync-ok
            COUNTERS.inc("fastlane.dispatch_device")
            return res, True
        nodes = {k: getattr(snap, k) for k in FAST_NODE_KEYS}
        COUNTERS.inc("fastlane.dispatch_host")
        return sample_eval_host(idx, req, zero_req, best_effort,
                                nodes), False

    # ---------------------------------------------------------------- fence

    def _fence(self, pod: Pod, node_name: str) -> Tuple[bool, str]:
        """Late-bind re-validation against LIVE truth — the wave
        harvest's fence discipline on a single node. Order matters:
        DOOMED notes first (a dying watch event not yet applied — the
        ISSUE 8 liveness fence extended to this path), then the
        _bind_fence liveness ladder, then exact capacity/ports, then the
        cluster-wide affinity gate (an existing pod's anti-affinity can
        forbid a plain pod — k8s 1.8 symmetry)."""
        if node_name in self.engine._doomed_nodes:
            return False, "doomed"
        info = self.cache.node_info(node_name)
        if info is None or info.node is None:
            return False, "gone"
        node = info.node
        if node.unschedulable:
            return False, "cordoned"
        if not oracle.check_node_condition(node):
            return False, "not_ready"
        fits, _fails = oracle.pod_fits_resources(pod, info)
        if not fits:
            return False, "capacity"
        if not oracle.pod_fits_host_ports(pod, info):
            return False, "ports"
        if self.cache.affinity_pod_count() > 0:
            return False, "affinity"
        return True, ""

    # --------------------------------------------------------------- commit

    def _commit(self, placed: Pod, pop_ts: float, t0: float,
                attempt: int, used_device: bool) -> bool:
        """Assume + bind + bookkeeping — the _complete_wave bind tail for
        one pod. Returns False only on the double-claim race (another
        path owns the key; the watch confirmation supersedes us)."""
        s = self.s
        try:
            self.cache.assume_pod(placed)
        except KeyError:
            # double-claim guard fired: a racing bind (wave row, foreign
            # scheduler) already owns this key — converge on the owner's
            # placement, exactly like the multiproc fence losers
            COUNTERS.inc("fastlane.superseded")
            return False
        self.engine.note_node_dirty(placed.node_name)
        tb0 = time.monotonic()
        errs = s._bind_bulk([placed])
        t_bind = time.monotonic() - tb0
        bound_pods, n_errors = s._finish_binds([placed], errs)
        if n_errors:
            # _finish_binds already forgot the assume + requeued with
            # backoff — the pod is safe on the wave path
            COUNTERS.inc("fastlane.bind_error")
            return True
        bind_done = time.monotonic()
        key = placed.key()
        s.cache.finish_bindings_bulk(bound_pods, keys=[key])
        s.metrics.scheduled.inc(1)
        s.metrics.binding_latency.observe_many(t_bind, 1)
        s.metrics.e2e_latency.observe_many(bind_done - pop_ts, 1)
        lat = bind_done - s._first_queued.pop(key, pop_ts)
        s.metrics.create_to_bound.observe_batch([lat])
        if SLO_FAST.enabled:
            # the fast tier burns against ITS OWN 10 ms objective — a
            # fast bind never lands in the bulk SLO windows (and vice
            # versa), so neither tier's backlog can hide the other's
            # regression
            SLO_FAST.observe_batch([lat], t=bind_done)
        if TRACER.enabled:
            TRACER.bound_batch([key], t0=bind_done)
        if RECORDER.enabled:
            RECORDER.record(flightrec.FASTLANE, t0=t0, dur=bind_done - t0,
                            a=attempt + 1, b=1 if used_device else 0)
        if s.wave_observer is not None:
            s.wave_observer(bind_done, [key])
        COUNTERS.inc("fastlane.bound")
        return True

    # ------------------------------------------------------------- schedule

    def schedule(self, pod: Pod, pop_ts: float, device_ok: bool = False
                 ) -> None:
        """One fast pod, pop to outcome: sample -> eval -> fence ->
        bind, resampling on fence loss, falling back to the wave path
        after bounded retries. Every path lands the pod somewhere — a
        fast pod is never dropped."""
        snap = self.engine.snapshot
        if snap._shape_sig is None:
            # cold start: no wave has primed the snapshot yet (a wave in
            # flight implies a refresh already ran, so this can't race
            # one). Prime it ONCE through the engine's own refresh; every
            # later fast pod reuses the resident arrays delta-free. A
            # stale snapshot between waves is fine — the fence re-checks
            # live truth, and persistent staleness self-heals because
            # fence losses fall back to the wave path, which refreshes.
            self.engine._refresh()
        if not snap.node_names or self.cache.affinity_pod_count() > 0:
            self._fallback(pod)
            return
        rr = pod.resource_request()
        req = snap.resource_row(
            milli_cpu=rr.milli_cpu, memory=rr.memory, gpu=rr.nvidia_gpu,
            scratch=rr.storage_scratch, overlay=rr.storage_overlay,
            extended={}, up=True, width=snap.num_resources)
        zero_req = (rr.milli_cpu == 0 and rr.memory == 0
                    and rr.nvidia_gpu == 0 and rr.storage_scratch == 0
                    and rr.storage_overlay == 0)
        best_effort = pod.is_best_effort()
        t0 = time.monotonic()
        key = pod.key()
        for attempt in range(self.retries + 1):
            idx = self._sample(snap)
            if idx is None:
                break
            res, used_device = self._eval(idx, req, zero_req, best_effort,
                                          snap, device_ok)
            if TRACER.enabled:
                TRACER.event(key, FAST_DISPATCHED,
                             a=0 if used_device else 1, b=attempt)
            fit_count = int(res[1])
            if fit_count == 0:
                COUNTERS.inc("fastlane.resampled")
                continue  # sampled set had no fit: jittered resample
            node_name = snap.node_names[int(idx[int(res[0])])]
            ok, reason = self._fence(pod, node_name)
            if not ok:
                COUNTERS.inc("fastlane.fence_" + reason)
                COUNTERS.inc("fastlane.resampled")
                continue
            placed = dataclasses.replace(pod, node_name=node_name)
            if self._commit(placed, pop_ts, t0, attempt, used_device):
                return
            return  # superseded: the racing owner's bind stands
        self._fallback(pod)

    def _fallback(self, pod: Pod) -> None:
        """Retries exhausted (or the lane can't serve this state): hand
        the pod to the wave path WITHOUT re-classification — add_bulk
        bypasses the fast classifier, so a fell-back pod cannot loop."""
        COUNTERS.inc("fastlane.fell_back")
        self.queue.add_bulk([pod])


__all__ = ["DEFAULT_K", "DEFAULT_RETRIES", "FASTLANE_ANNOTATION",
           "FAST_PRIO", "FastLane", "eligible", "is_latency_critical"]
