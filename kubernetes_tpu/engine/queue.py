"""Pending-pod queue with exponential backoff requeue.

Mirrors the reference's FIFO pod queue (factory.go:140 podQueue =
cache.NewFIFO) + the error-path backoff requeue (factory.go:897
MakeDefaultErrorFunc with util.PodBackoff: initial 1s, max 60s, doubling per
pod — plugin/pkg/scheduler/util/backoff_utils.go).

Batch-native twist: pop_batch drains up to max_n ready pods at once (the
snapshot-the-queue idea from SURVEY.md §2.3) instead of one blocking Pop.
"""

from __future__ import annotations

import heapq
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.observability.podtrace import TRACER
from kubernetes_tpu.utils import features

INITIAL_BACKOFF = 1.0
MAX_BACKOFF = 60.0


class PodBackoff:
    """Per-pod doubling backoff (backoff_utils.go:SchedulerBackoff)."""

    def __init__(self, initial: float = INITIAL_BACKOFF, max_s: float = MAX_BACKOFF,
                 now: Callable[[], float] = time.monotonic):
        self._initial = initial
        self._max = max_s
        self._now = now
        self._durations: Dict[str, float] = {}
        self._last: Dict[str, float] = {}

    def next_delay(self, key: str) -> float:
        """Current delay for the pod, then double for next time."""
        d = self._durations.get(key, self._initial)
        self._durations[key] = min(d * 2, self._max)
        self._last[key] = self._now()
        return d

    def gc(self, max_age: float = 2 * MAX_BACKOFF) -> None:
        cutoff = self._now() - max_age
        for k in [k for k, t in self._last.items() if t < cutoff]:
            self._durations.pop(k, None)
            self._last.pop(k, None)


class SchedulingQueue:
    # starvation guard (ISSUE 14): a pod that has waited this long pops
    # AHEAD of the priority order — under a sustained high-priority
    # offered stream, a preempted low-priority victim would otherwise
    # never reach the head of a priority-sorted queue (Tiresias' aging
    # discipline, PAPERS.md §Tiresias). The stamp survives backoff
    # requeues (waiting is cumulative from first admission) and clears
    # on terminal removal (bind confirmation, deletion).
    AGING_THRESHOLD_S = 30.0

    def __init__(self, now: Callable[[], float] = time.monotonic):
        self._now = now
        self._lock = lockcheck.make_condition("SchedulingQueue._lock")
        self._fifo: List[Pod] = []
        self._keys: Dict[str, Pod] = {}
        self._deferred: List = []  # heap of (ready_time, seq, pod)
        self._seq = 0
        self._queued_at: Dict[str, float] = {}  # first-admission stamp
        self.aging_threshold_s = self.AGING_THRESHOLD_S
        self.backoff = PodBackoff(now=now)
        # fast tier (ISSUE 17): pods the classifier routes latency-critical
        # pop via pop_fast() ahead of any quantum. None (default) keeps
        # the queue single-tier — BIT-identical to the pre-fast-lane
        # behavior, pinned by the A/B test. The bulk tier's r14
        # aging/starvation guard is untouched: fast pods never enter the
        # priority sort, bulk pods never wait behind the fast tier's pop
        # (the streaming loop budgets fast pops per step).
        self._fast: List[Pod] = []
        self.fast_classifier: Optional[Callable[[Pod], bool]] = None

    def add(self, pod: Pod) -> None:
        with self._lock:
            key = pod.key()
            if key in self._keys:
                return
            self._queued_at.setdefault(key, self._now())
            self._keys[key] = pod
            cls = self.fast_classifier
            if cls is not None and cls(pod):
                self._fast.append(pod)
            else:
                self._fifo.append(pod)
            self._lock.notify_all()
        if TRACER.enabled:
            # pod-level black box (ISSUE 15): the queue-admission stamp
            # — head-sampling decides here, everything later is a probe
            TRACER.begin_batch((key,))

    def add_many(self, pods: List[Pod]) -> None:
        """add() for a batch under ONE lock with ONE waiter wakeup — the
        arrival-storm admission path (ISSUE 7): at 20k+ creates/s the
        per-pod lock acquire + notify_all of add() is a measurable slice
        of the scheduler core the stream is trying to keep on waves."""
        admitted = None
        with self._lock:
            keys = self._keys
            fifo = self._fifo
            fast = self._fast
            cls = self.fast_classifier
            now = self._now()
            stamps = self._queued_at
            if TRACER.enabled:
                admitted = []
            for pod in pods:
                key = pod.key()
                if key in keys:
                    continue
                stamps.setdefault(key, now)
                keys[key] = pod
                if cls is not None and cls(pod):
                    fast.append(pod)
                else:
                    fifo.append(pod)
                if admitted is not None:
                    admitted.append(key)
            self._lock.notify_all()
        if admitted:
            TRACER.begin_batch(admitted)

    def add_bulk(self, pods: List[Pod]) -> None:
        """Admit straight to the BULK tier, bypassing the fast
        classifier — the fast lane's fallback path (ISSUE 17): a pod
        whose bounded retries ran out must ride the wave path next, not
        re-route into the fast tier forever."""
        admitted = None
        with self._lock:
            keys = self._keys
            now = self._now()
            stamps = self._queued_at
            if TRACER.enabled:
                admitted = []
            for pod in pods:
                key = pod.key()
                if key in keys:
                    continue
                stamps.setdefault(key, now)
                keys[key] = pod
                self._fifo.append(pod)
                if admitted is not None:
                    admitted.append(key)
            self._lock.notify_all()
        if admitted:
            TRACER.begin_batch(admitted)

    def add_backoff(self, pod: Pod) -> float:
        """Requeue after the pod's current backoff delay; returns the delay."""
        with self._lock:
            key = pod.key()
            if key in self._keys:
                return 0.0
            self._queued_at.setdefault(key, self._now())
            delay = self.backoff.next_delay(key)
            self._keys[key] = pod
            self._seq += 1
            heapq.heappush(self._deferred, (self._now() + delay, self._seq, pod))
            self._lock.notify_all()
        if TRACER.enabled:
            TRACER.begin_batch((key,), backoff=True)
        return delay

    def remove(self, pod_key: str) -> None:
        """Drop a pod (deleted / scheduled by someone else)."""
        with self._lock:
            self._queued_at.pop(pod_key, None)  # terminal: stamp clears
            if self._keys.pop(pod_key, None) is not None:
                self._fifo = [p for p in self._fifo if p.key() != pod_key]
                if self._fast:
                    self._fast = [p for p in self._fast
                                  if p.key() != pod_key]
                self._deferred = [(t, s, p) for (t, s, p) in self._deferred
                                  if p.key() != pod_key]
                heapq.heapify(self._deferred)

    def remove_many(self, pod_keys: List[str]) -> None:
        """remove() for a batch under one lock. The bind-confirmation storm
        calls this with keys that are almost never queued (the pods were
        popped before binding), so absence costs one set probe per key and
        the list rebuilds happen at most once per batch."""
        with self._lock:
            stamps = self._queued_at
            for k in pod_keys:
                stamps.pop(k, None)  # terminal: bind confirmed
            present = {k for k in pod_keys if k in self._keys}
            if not present:
                return
            for k in present:
                del self._keys[k]
            self._fifo = [p for p in self._fifo if p.key() not in present]
            if self._fast:
                self._fast = [p for p in self._fast
                              if p.key() not in present]
            self._deferred = [(t, s, p) for (t, s, p) in self._deferred
                              if p.key() not in present]
            heapq.heapify(self._deferred)

    def pop_batch(self, max_n: int = 0, wait: Optional[float] = None) -> List[Pod]:
        """Drain up to max_n (0 = all) ready pods; optionally block up to
        `wait` seconds for the first one."""
        deadline = None if wait is None else self._now() + wait
        with self._lock:
            while True:
                self._promote_ready_locked()
                if self._fast and not self._fifo:
                    # a fast-tier arrival must not sit out a bulk
                    # blocking wait: return empty so the streaming loop
                    # pumps the fast lane now (with no classifier set
                    # _fast is always empty — this branch never fires)
                    return []
                if self._fifo:
                    if features.enabled("PodPriority"):
                        # priority queue semantics (1.8's podqueue
                        # heap ordered by priority): higher priority
                        # pops first; stable sort keeps FIFO order
                        # within a priority band. AGED pods lead the
                        # whole order (ISSUE 14 starvation guard): a
                        # preempted victim that has waited past the
                        # aging threshold pops before fresh
                        # high-priority arrivals, so it rebinds the
                        # moment capacity frees instead of starving
                        # behind a sustained high-band stream.
                        now = self._now()
                        age = self.aging_threshold_s
                        stamps = self._queued_at
                        self._fifo.sort(
                            key=lambda p:
                            (0 if now - stamps.get(p.key(), now) >= age
                             else 1, -p.priority))
                    n = len(self._fifo) if max_n == 0 else min(max_n, len(self._fifo))
                    out = self._fifo[:n]
                    self._fifo = self._fifo[n:]
                    for p in out:
                        self._keys.pop(p.key(), None)
                    if TRACER.enabled and out:
                        # POPPED carries the realized admission size (=
                        # the quantum that popped it) and the pod's own
                        # pop round — requeue loops made visible
                        TRACER.pop_batch([p.key() for p in out])
                    return out
                if deadline is None:
                    return []
                remaining = deadline - self._now()
                if remaining <= 0:
                    return []
                timeout = remaining
                if self._deferred:
                    timeout = min(timeout, max(self._deferred[0][0] - self._now(), 0.01))
                self._lock.wait(timeout)

    def pop_fast(self, max_n: int = 0) -> List[Pod]:
        """Drain up to max_n (0 = all) fast-tier pods NOW — no blocking,
        no quantum, no priority sort (the fast tier is FIFO: every pod
        in it is equally latency-critical and k-sampling spreads the
        load server-side, Sparrow's discipline)."""
        with self._lock:
            if not self._fast:
                return []
            n = len(self._fast) if max_n == 0 else min(max_n,
                                                       len(self._fast))
            out = self._fast[:n]
            self._fast = self._fast[n:]
            for p in out:
                self._keys.pop(p.key(), None)
            if TRACER.enabled:
                TRACER.pop_batch([p.key() for p in out])
            return out

    def fast_count(self) -> int:
        with self._lock:
            return len(self._fast)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def ready_count(self) -> int:
        with self._lock:
            self._promote_ready_locked()
            return len(self._fifo) + len(self._fast)

    def _promote_ready_locked(self) -> None:
        lockcheck.assert_held(self._lock, "_promote_ready_locked")
        now = self._now()
        while self._deferred and self._deferred[0][0] <= now:
            _, _, pod = heapq.heappop(self._deferred)
            if pod.key() in self._keys:
                self._fifo.append(pod)
