"""Effective security-context resolution (pkg/securitycontext/util.go):
container-level values override pod-level defaults; absent values stay None
so callers can distinguish unset from explicit."""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.types import (
    Container,
    Pod,
    PodSecurityContext,
    SecurityContext,
)


def effective_run_as_user(pod: Pod, c: Container) -> Optional[int]:
    if c.security_context is not None \
            and c.security_context.run_as_user is not None:
        return c.security_context.run_as_user
    if pod.security_context is not None:
        return pod.security_context.run_as_user
    return None


def effective_run_as_non_root(pod: Pod, c: Container) -> Optional[bool]:
    if c.security_context is not None \
            and c.security_context.run_as_non_root is not None:
        return c.security_context.run_as_non_root
    if pod.security_context is not None:
        return pod.security_context.run_as_non_root
    return None


def is_privileged(c: Container) -> bool:
    return bool(c.security_context is not None
                and c.security_context.privileged)


def read_only_root(c: Container) -> Optional[bool]:
    if c.security_context is None:
        return None
    return c.security_context.read_only_root_filesystem
