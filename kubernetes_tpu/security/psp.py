"""PodSecurityPolicy: the policy object + the validate/mutate provider.

Mirror of the reference's PSP surface (pkg/apis/extensions/types.go:875-1030
PodSecurityPolicySpec; provider pkg/security/podsecuritypolicy/provider.go;
strategies under pkg/security/podsecuritypolicy/{user,capabilities,...}):

- boolean gates: privileged, hostNetwork
- hostPorts: list of allowed [min, max] ranges
- volumes: allowed FSTypes ("*" = everything); our Volume model collapses
  scheduling-inert sources to OTHER, so FSTypes here are the VolumeKind
  values plus "*"
- runAsUser: RunAsAny | MustRunAsNonRoot | MustRunAs{ranges} — MustRunAs
  DEFAULTS an unset pod-level runAsUser to the first range's min (the
  generating half of the strategy, user/mustrunas.go Generate) and
  validates explicit values against the ranges
- readOnlyRootFilesystem: required when true

The provider is pure: validate(pod) -> [errors]; apply_defaults(pod) -> a
mutated COPY (the admission plugin commits it only if validation passes,
like provider.DefaultPodSecurityContext + ValidatePod in admission.go:177).
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import (
    Pod,
    PodSecurityContext,
    VolumeKind,
)
from kubernetes_tpu.security import securitycontext as sc

PSP_KIND = "PodSecurityPolicy"
PSP_ANNOTATION = "kubernetes.io/psp"  # admission.go:41 pspAnnotation

RUN_AS_ANY = "RunAsAny"
MUST_RUN_AS = "MustRunAs"
MUST_RUN_AS_NON_ROOT = "MustRunAsNonRoot"


@dataclass
class PodSecurityPolicy:
    """extensions/v1beta1 PodSecurityPolicy reduced to the enforced slice."""

    name: str
    privileged: bool = False
    host_network: bool = False
    host_ports: List[Tuple[int, int]] = field(default_factory=list)
    volumes: List[str] = field(default_factory=lambda: ["*"])
    run_as_user_rule: str = RUN_AS_ANY
    run_as_user_ranges: List[Tuple[int, int]] = field(default_factory=list)
    read_only_root_filesystem: bool = False
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    resource_version: int = 0


class Provider:
    """provider.go: one PSP's validate + default logic."""

    def __init__(self, psp: PodSecurityPolicy):
        self.psp = psp

    # ------------------------------------------------------------- defaults

    def apply_defaults(self, pod: Pod) -> Pod:
        """The generating half (DefaultPodSecurityContext): MustRunAs with
        no explicit runAsUser anywhere assigns the first range's min at the
        pod level. Copies lazily — a policy with nothing to default returns
        the input unchanged (the admission loop tries every policy, so the
        common RunAsAny case must not pay a deepcopy per policy)."""
        if self.psp.run_as_user_rule == MUST_RUN_AS \
                and self.psp.run_as_user_ranges \
                and not any(sc.effective_run_as_user(pod, c) is not None
                            for c in pod.containers):
            out = copy.deepcopy(pod)
            base = out.security_context or PodSecurityContext()
            out.security_context = dataclasses.replace(
                base, run_as_user=self.psp.run_as_user_ranges[0][0])
            return out
        return pod

    # ------------------------------------------------------------- validate

    def validate(self, pod: Pod) -> List[str]:
        errs: List[str] = []
        psp = self.psp
        if pod.host_network and not psp.host_network:
            errs.append("hostNetwork is not allowed to be used")
        allowed_vols = set(psp.volumes)
        if "*" not in allowed_vols:
            for v in pod.volumes:
                kind = VolumeKind(v.kind).value
                if kind not in allowed_vols:
                    errs.append(f"volume kind {kind} is not allowed")
        for c in pod.containers:
            if sc.is_privileged(c) and not psp.privileged:
                errs.append(
                    f"container {c.name}: privileged is not allowed")
            for p in c.ports:
                if p.host_port and not self._host_port_ok(p.host_port):
                    errs.append(f"container {c.name}: host port "
                                f"{p.host_port} is not allowed")
            errs.extend(self._validate_run_as_user(pod, c))
            if psp.read_only_root_filesystem \
                    and sc.read_only_root(c) is not True:
                errs.append(f"container {c.name}: root filesystem must be "
                            "read-only")
        return errs

    def _host_port_ok(self, port: int) -> bool:
        if not self.psp.host_ports:
            return False  # no ranges = no host ports (types.go:904-906)
        return any(lo <= port <= hi for lo, hi in self.psp.host_ports)

    def _validate_run_as_user(self, pod: Pod, c) -> List[str]:
        rule = self.psp.run_as_user_rule
        uid = sc.effective_run_as_user(pod, c)
        if rule == RUN_AS_ANY:
            return []
        if rule == MUST_RUN_AS_NON_ROOT:
            # user/nonroot.go: uid 0 is invalid; unset uid needs
            # runAsNonRoot=true so the runtime can verify
            if uid == 0:
                return [f"container {c.name}: running as root is not "
                        "allowed (MustRunAsNonRoot)"]
            if uid is None and sc.effective_run_as_non_root(pod, c) \
                    is not True:
                return [f"container {c.name}: runAsNonRoot must be true "
                        "or runAsUser set (MustRunAsNonRoot)"]
            return []
        if rule == MUST_RUN_AS:
            if uid is None:
                return [f"container {c.name}: runAsUser must be set "
                        "(MustRunAs)"]
            if not any(lo <= uid <= hi
                       for lo, hi in self.psp.run_as_user_ranges):
                return [f"container {c.name}: runAsUser {uid} outside "
                        "allowed ranges (MustRunAs)"]
            return []
        return [f"unknown runAsUser rule {rule!r}"]
