"""Feature gates: --feature-gates=K=V registry.

Mirror of pkg/features/kube_features.go:33-135 (the scheduling-relevant
subset) + the generic map-flag parser in
staging/src/k8s.io/apiserver/pkg/util/feature/feature_gate.go. Defaults match
the reference at v1.7: alpha features off, beta features on.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
from typing import Dict

# name -> default enabled (kube_features.go:137-150 defaultKubernetesFeatureGates)
_DEFAULTS: Dict[str, bool] = {
    "AppArmor": True,  # beta (kube_features.go:42)
    "DynamicKubeletConfig": False,  # alpha (:48)
    "DynamicVolumeProvisioning": True,  # alpha->on by default (:54)
    "ExperimentalHostUserNamespaceDefaulting": False,  # beta-off (:60)
    "ExperimentalCriticalPodAnnotation": False,  # alpha (:68)
    "Accelerators": False,  # alpha (:76)
    "TaintBasedEvictions": False,  # alpha (:83)
    "RotateKubeletServerCertificate": False,  # alpha (:90)
    "RotateKubeletClientCertificate": False,  # alpha (:97)
    "PersistentLocalVolumes": False,  # alpha (:104) — gates NoVolumeNodeConflict
    "LocalStorageCapacityIsolation": False,  # alpha (:110)
    "PodPriority": False,  # alpha (:122) — gates preemption
    "EnableEquivalenceClassCache": False,  # alpha (:128)
    "AllAlpha": False,
}

_ALPHA = {
    "DynamicKubeletConfig", "ExperimentalCriticalPodAnnotation",
    "Accelerators", "TaintBasedEvictions", "RotateKubeletServerCertificate",
    "RotateKubeletClientCertificate", "PersistentLocalVolumes",
    "LocalStorageCapacityIsolation", "PodPriority",
    "EnableEquivalenceClassCache",
}


class FeatureGate:
    """Thread-safe gate map; AllAlpha=true flips every alpha gate unless it
    was explicitly set (feature_gate.go Set)."""

    def __init__(self):
        self._lock = lockcheck.make_lock("FeatureGate._lock")
        self._enabled = dict(_DEFAULTS)
        self._explicit: set = set()

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name not in self._enabled:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._enabled[name]

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            if name not in self._enabled:
                raise KeyError(f"unknown feature gate {name!r}")
            self._enabled[name] = value
            self._explicit.add(name)
            if name == "AllAlpha":
                for k in _ALPHA:
                    if k not in self._explicit:
                        self._enabled[k] = value

    def parse(self, spec: str) -> None:
        """--feature-gates=K=V,K=V (feature_gate.go:Set)."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            self.set(k.strip(), v.strip().lower() == "true")

    def reset(self) -> None:
        with self._lock:
            self._enabled = dict(_DEFAULTS)
            self._explicit = set()


DEFAULT_FEATURE_GATE = FeatureGate()


def enabled(name: str) -> bool:
    return DEFAULT_FEATURE_GATE.enabled(name)
