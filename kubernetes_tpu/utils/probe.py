"""Probe executors — pkg/probe/{http,tcp,exec}.

The reference's probers return one of three results (pkg/probe/probe.go
Result: Success/Failure/Unknown) with a message:

- HTTP (pkg/probe/http/http.go): GET the URL; 2xx/3xx is Success, any
  other status Failure, transport errors Failure (the kubelet treats a
  refused connection as a failed probe, not an error), timeouts bounded.
- TCP (pkg/probe/tcp/tcp.go): a successful connect is Success.
- Exec (pkg/probe/exec/exec.go): exit 0 Success, non-zero Failure —
  here a callable returning (rc, output), since the hollow runtime has
  no containers to exec into.

These are the real network probers the framework's own HTTP surfaces
are checked with (kubelet API /healthz, proxy healthcheck, daemon
healthz) — the hollow kubelet's annotation-driven pod probes stay the
kubemark-style fake for scripted outcomes.
"""

from __future__ import annotations

import socket
import urllib.error
import urllib.request
from typing import Callable, Tuple

SUCCESS = "Success"
FAILURE = "Failure"
UNKNOWN = "Unknown"


def probe_http(url: str, timeout: float = 1.0) -> Tuple[str, str]:
    """http.go DoHTTPProbe: 2xx/3xx Success, other statuses Failure,
    transport errors Failure (a dead endpoint is a FAILED probe)."""
    try:
        req = urllib.request.Request(url, headers={
            "User-Agent": "kube-probe/1.7-tpu"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
    except Exception as e:
        return FAILURE, f"Get {url}: {e}"
    if 200 <= code < 400:
        return SUCCESS, f"HTTP probe succeeded with code {code}"
    return FAILURE, f"HTTP probe failed with statuscode: {code}"


def probe_tcp(host: str, port: int, timeout: float = 1.0) -> Tuple[str, str]:
    """tcp.go DoTCPProbe: connect() decides."""
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return SUCCESS, "TCP probe succeeded"
    except OSError as e:
        return FAILURE, f"dial tcp {host}:{port}: {e}"


def probe_exec(fn: Callable[[], Tuple[int, str]]) -> Tuple[str, str]:
    """exec.go Probe over a callable standing in for the container exec:
    rc 0 Success, non-zero Failure, an exception Unknown (the reference
    maps exec-infrastructure errors to Unknown, not Failure)."""
    try:
        rc, output = fn()
    except Exception as e:
        return UNKNOWN, str(e)
    return (SUCCESS if rc == 0 else FAILURE), output
