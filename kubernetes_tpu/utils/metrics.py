"""Scheduler metrics: histograms with the reference's bucket layout.

Mirrors plugin/pkg/scheduler/metrics/metrics.go:31-55 — three latency
histograms (e2e scheduling, algorithm, binding) with exponential buckets
1ms..~16s (ExponentialBuckets(1000, 2, 15) microseconds), exported in
Prometheus text format via render() (scrape endpoint wired in server/).
"""

from __future__ import annotations

import bisect
import threading
from kubernetes_tpu.analysis import lockcheck
from typing import Dict, List


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    out = []
    v = start
    for _ in range(count):
        out.append(v)
        v *= factor
    return out


# seconds; matches 1000us * 2^k for k in 0..14 (metrics.go:38,46,54)
DEFAULT_BUCKETS = exponential_buckets(0.001, 2, 15)


class Histogram:
    # bound on retained sample-store entries (weighted tuples + chunk
    # elements + compacted reservoir points). The r10 always-on loop made
    # unbounded growth a real leak: create_to_bound appends one chunk per
    # WAVE forever — at 20k pods/s that is ~7 GB/hour of float64 samples.
    # Past the bound the store compacts to a weighted quantile reservoir
    # (RESERVOIR_MAX // 4 points at equal-mass ranks), bounding memory at
    # O(RESERVOIR_MAX) while percentile() stays exact below the bound and
    # rank-accurate to ~total/k above it (test-pinned on a known
    # distribution in tests/test_observability.py).
    RESERVOIR_MAX = 65536

    def __init__(self, name: str, help_text: str = "",
                 buckets: List[float] = None,
                 reservoir_max: int = 0):
        self.name = name
        self.help = help_text
        self.buckets = list(buckets or DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        # (value, multiplicity) samples for percentiles in benches —
        # weighted so a 30k-pod batch round is one entry, not 30k appends.
        # observe_batch keeps its per-pod arrays as raw numpy chunks
        # instead (zero per-value Python objects on the drain hot path —
        # the r5 version built 30k (float, 1) tuples per round, a measured
        # slice of the 0.559->0.898s headline regression); percentile()
        # merges both stores plus the compacted reservoir.
        self._values: List[tuple] = []
        self._chunks: List = []
        self._res_vals = None   # compacted reservoir: sorted values
        self._res_wts = None    # ... and their (float) multiplicities
        self._points = 0        # retained entries across all three stores
        self._compactions = 0
        self.reservoir_max = int(reservoir_max) or self.RESERVOIR_MAX
        self._lock = lockcheck.make_lock("Histogram._lock")

    def observe(self, v: float) -> None:
        self.observe_many(v, 1)

    def _observe_locked(self, v: float, n: int) -> None:
        lockcheck.assert_held(self._lock, "_observe_locked")
        i = bisect.bisect_left(self.buckets, v)
        self._counts[i] += n
        self._sum += v * n
        self._count += n
        self._values.append((v, n))
        self._points += 1
        if self._points > self.reservoir_max:
            self._compact_locked()

    def observe_many(self, v: float, n: int) -> None:
        """Record n observations of the same value (one lock, one append) —
        the batch rounds observe whole-round spans per pod."""
        if n <= 0:
            return
        with self._lock:
            self._observe_locked(v, n)

    def observe_batch(self, values: List[float]) -> None:
        """Record a round's worth of DISTINCT per-pod values under one lock,
        vectorized — 30k individual observe() calls would pay 30k lock
        round-trips and bisects on the hot drain path."""
        if not values:
            return
        import numpy as np
        arr = np.asarray(values, dtype=np.float64)
        # bisect_left semantics == searchsorted 'left'
        idx = np.searchsorted(np.asarray(self.buckets), arr, side="left")
        binned = np.bincount(idx, minlength=len(self.buckets) + 1)
        with self._lock:
            for i, c in enumerate(binned):
                self._counts[i] += int(c)
            self._sum += float(arr.sum())
            self._count += len(values)
            self._chunks.append(arr)
            self._points += len(arr)
            if self._points > self.reservoir_max:
                self._compact_locked()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def totals(self):
        """(count, sum) read under the lock — the telemetry registry's
        torn-read-free accessor (count and sum advance together under
        observe; reading the properties separately could tear)."""
        with self._lock:
            return self._count, self._sum

    @property
    def stored_points(self) -> int:
        """Retained sample-store entries — what the bounded-growth test
        pins (memory is O(stored_points), never O(count))."""
        with self._lock:
            return self._points

    def _merged_locked(self):
        """All three stores as (sorted values, aligned weights), or None
        when empty. Read-time cost only — never on the observe path."""
        import numpy as np
        vparts, wparts = [], []
        if self._res_vals is not None:
            vparts.append(self._res_vals)
            wparts.append(self._res_wts)
        if self._values:
            vparts.append(np.array([v for v, _ in self._values],
                                   dtype=np.float64))
            wparts.append(np.array([n for _, n in self._values],
                                   dtype=np.float64))
        for c in self._chunks:
            vparts.append(c)
            wparts.append(np.ones(len(c)))
        if not vparts:
            return None
        v = np.concatenate(vparts)
        w = np.concatenate(wparts)
        order = np.argsort(v, kind="stable")
        return v[order], w[order]

    def _compact_locked(self) -> None:
        """Fold every retained sample into a bounded weighted reservoir:
        k points at equal-mass ranks (stratum centers), stratum masses as
        weights — total mass preserved exactly, rank error per later
        percentile() bounded by ~total/k per compaction."""
        lockcheck.assert_held(self._lock, "_compact_locked")
        import numpy as np
        merged = self._merged_locked()
        self._values = []
        self._chunks = []
        if merged is None:
            self._res_vals = self._res_wts = None
            self._points = 0
            return
        v, w = merged
        k = max(self.reservoir_max // 4, 16)
        if len(v) <= k:
            self._res_vals, self._res_wts = v, w
            self._points = len(v)
            return
        cum = np.cumsum(w)
        total = cum[-1]
        centers = (np.arange(k) + 0.5) * (total / k)
        idx = np.minimum(np.searchsorted(cum, centers, side="right"),
                         len(v) - 1)
        edges = np.arange(1, k) * (total / k)
        self._res_vals = v[idx]
        self._res_wts = np.diff(np.concatenate([[0.0], edges, [total]]))
        self._points = k
        self._compactions += 1

    def percentile(self, p: float) -> float:
        """Percentile over the merged stores: exact while the sample
        store is under the reservoir bound (rank semantics identical to
        the pre-r15 two-pointer walk), rank-accurate to ~total/k once
        compaction has folded history into the weighted reservoir."""
        import numpy as np
        with self._lock:
            merged = self._merged_locked()
            if merged is None:
                return 0.0
            v, w = merged
            cum = np.cumsum(w)
            total = cum[-1]
            if total <= 0:
                return 0.0
            target = min(int(p / 100.0 * total), total - 1)
            i = int(np.searchsorted(cum, target, side="right"))
            return float(v[min(i, len(v) - 1)])

    def render(self) -> str:
        with self._lock:
            lines = [f"# HELP {self.name} {self.help}",
                     f"# TYPE {self.name} histogram"]
            cum = 0
            for b, c in zip(self.buckets, self._counts):
                cum += c
                lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {self._count}')
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._count}")
            return "\n".join(lines)


class Counter:
    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._v = 0
        self._lock = lockcheck.make_lock("Counter._lock")

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._v

    def render(self) -> str:
        with self._lock:
            return (f"# HELP {self.name} {self.help}\n"
                    f"# TYPE {self.name} counter\n{self.name} {self._v}")


class SchedulerMetrics:
    """The scheduler's metric set (metrics.go:31-66)."""

    def __init__(self):
        self.e2e_latency = Histogram(
            "scheduler_e2e_scheduling_latency_seconds",
            "E2e scheduling latency (scheduling algorithm + binding)")
        self.algorithm_latency = Histogram(
            "scheduler_scheduling_algorithm_latency_seconds",
            "Scheduling algorithm latency")
        self.binding_latency = Histogram(
            "scheduler_binding_latency_seconds", "Binding latency")
        # NOT in the reference's metric set: per-pod first-queued ->
        # bind-complete, queue wait included. The batch engine amortizes
        # compute across a round, so the three span histograms above are
        # round-constant within a round; this one is the honest per-pod
        # distribution the pod-startup SLO reads (e2e framework
        # metrics_util.go:46 5s p99 pod startup, minus the kubelet leg)
        self.create_to_bound = Histogram(
            "scheduler_pod_create_to_bound_seconds",
            "Pod first seen unscheduled to bind-complete, per pod")
        self.scheduled = Counter("scheduler_pods_scheduled_total",
                                 "Pods successfully bound")
        self.failed = Counter("scheduler_pods_failed_total",
                              "Pods that failed scheduling")

    def render(self) -> str:
        return "\n".join(m.render() for m in (
            self.e2e_latency, self.algorithm_latency, self.binding_latency,
            self.create_to_bound, self.scheduled, self.failed))
