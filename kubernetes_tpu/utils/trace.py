"""utiltrace analog: timestamped step traces dumped only when slow.

Mirror of staging/src/k8s.io/apiserver/pkg/util/trace/trace.go:33-90
(Trace.Step / LogIfLong): callers mark named steps; if the total latency
exceeds the threshold, the whole step breakdown is emitted — the
scheduler wraps every Schedule call at a 100ms threshold
(plugin/pkg/scheduler/core/generic_scheduler.go:89-90).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

LOG = logging.getLogger("kubernetes_tpu.trace")

# the scheduler's slow-schedule threshold (generic_scheduler.go:90)
SCHEDULE_TRACE_THRESHOLD_S = 0.1


class Trace:
    def __init__(self, name: str, now: Callable[[], float] = time.monotonic,
                 sink: Optional[Callable[[str], None]] = None, **fields):
        self.name = name
        self._now = now
        self._start = now()
        self._steps: List[Tuple[float, str]] = []
        self._sink = sink or (lambda msg: LOG.info("%s", msg))
        self._fields = fields

    def step(self, msg: str) -> None:
        self._steps.append((self._now(), msg))

    def field(self, key: str, value) -> None:
        """Attach a context field learned after construction (shown in the
        dump header)."""
        self._fields[key] = value

    def total(self) -> float:
        return self._now() - self._start

    def log_if_long(self, threshold_s: float) -> bool:
        """Emit the breakdown when total exceeds threshold (trace.go:57
        LogIfLong). Returns True if dumped."""
        total = self.total()
        if total < threshold_s:
            return False
        fields = "".join(f" {k}={v}" for k, v in self._fields.items())
        lines = [f'Trace "{self.name}"{fields} (total {total * 1e3:.1f}ms):']
        last = self._start
        for t, msg in self._steps:
            lines.append(f'  [{(t - self._start) * 1e3:.1f}ms] '
                         f'(+{(t - last) * 1e3:.1f}ms) {msg}')
            last = t
        self._sink("\n".join(lines))
        return True
