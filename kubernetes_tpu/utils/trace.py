"""utiltrace analog: timestamped step traces dumped only when slow.

Mirror of staging/src/k8s.io/apiserver/pkg/util/trace/trace.go:33-90
(Trace.Step / LogIfLong): callers mark named steps; if the total latency
exceeds the threshold, the whole step breakdown is emitted — the
scheduler wraps every Schedule call at a 100ms threshold
(plugin/pkg/scheduler/core/generic_scheduler.go:89-90).
"""

from __future__ import annotations

import logging
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger("kubernetes_tpu.trace")

# the scheduler's slow-schedule threshold (generic_scheduler.go:90)
SCHEDULE_TRACE_THRESHOLD_S = 0.1


class SpanCounters:
    """Named monotonic counters + accumulated wall time for hot-path spans.

    The profiling companion to Trace: Trace narrates ONE slow call;
    SpanCounters aggregate across thousands of fast ones (how many times
    did the extender rebuild AffinityData this session? where did the warm
    /filter's milliseconds go?). Tests assert on counts to pin cache
    behavior structurally; profile_bench reads times for attribution."""

    def __init__(self):
        self._lock = lockcheck.make_lock("SpanCounters._lock")
        self._counts: Dict[str, int] = {}
        self._times: Dict[str, float] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def add_time(self, name: str, seconds: float) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + 1
            self._times[name] = self._times.get(name, 0.0) + seconds

    def count(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def time(self, name: str) -> float:
        with self._lock:
            return self._times.get(name, 0.0)

    def snapshot(self) -> Dict[str, Tuple[int, float]]:
        with self._lock:
            return {k: (c, self._times.get(k, 0.0))
                    for k, c in self._counts.items()}

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._times.clear()


# process-wide registry, used by the extender fast lane (server/extender.py,
# engine/scheduler_engine.evaluate_pod) and read by profile_bench + tests
COUNTERS = SpanCounters()


class timed_span:
    """`with timed_span("extender.refresh"): ...` — count + accumulate."""

    __slots__ = ("name", "_t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        COUNTERS.add_time(self.name, time.perf_counter() - self._t0)
        return False


class Trace:
    def __init__(self, name: str, now: Callable[[], float] = time.monotonic,
                 sink: Optional[Callable[[str], None]] = None, **fields):
        self.name = name
        self._now = now
        self._start = now()
        self._steps: List[Tuple[float, str]] = []
        self._sink = sink or (lambda msg: LOG.info("%s", msg))
        self._fields = fields

    def step(self, msg: str) -> None:
        self._steps.append((self._now(), msg))

    def field(self, key: str, value) -> None:
        """Attach a context field learned after construction (shown in the
        dump header)."""
        self._fields[key] = value

    def total(self) -> float:
        return self._now() - self._start

    def log_if_long(self, threshold_s: float) -> bool:
        """Emit the breakdown when total exceeds threshold (trace.go:57
        LogIfLong). Returns True if dumped."""
        total = self.total()
        if total < threshold_s:
            return False
        fields = "".join(f" {k}={v}" for k, v in self._fields.items())
        lines = [f'Trace "{self.name}"{fields} (total {total * 1e3:.1f}ms):']
        last = self._start
        for t, msg in self._steps:
            lines.append(f'  [{(t - self._start) * 1e3:.1f}ms] '
                         f'(+{(t - last) * 1e3:.1f}ms) {msg}')
            last = t
        self._sink("\n".join(lines))
        return True
