"""ctypes loader for the C++ host-ops library (native/hostops.cc).

The native seam of SURVEY §2: dense-array encoding kernels for the
snapshot layer live in C++ (built by build/Makefile, or on demand here
with g++), with pure-Python/numpy fallbacks so every path works without a
toolchain. `lib()` returns the loaded library or None; the public
functions below pick the fast path automatically and are bit-identical
either way (tests/test_native.py asserts both sides).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from kubernetes_tpu.analysis import lockcheck
from typing import Optional

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "hostops.cc")
_SO = os.path.join(_ROOT, "native", "libhostops.so")

_lock = lockcheck.make_lock("native._lock")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    lib.fill_port_bitmaps.argtypes = [
        ctypes.POINTER(i64), i64, ctypes.POINTER(ctypes.c_uint32), i64, i64]
    lib.fill_port_bitmaps.restype = None
    lib.fill_multi_hot.argtypes = [
        ctypes.POINTER(i64), i64, ctypes.POINTER(ctypes.c_int8), i64, i64]
    lib.fill_multi_hot.restype = None
    lib.fnv1a64.argtypes = [ctypes.POINTER(ctypes.c_uint8), i64]
    lib.fnv1a64.restype = ctypes.c_uint64
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it once with g++ if absent. None when
    no prebuilt .so exists and the build fails (no toolchain)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) and os.path.exists(_SRC):
            try:
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-o", _SO, _SRC],
                    check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        if os.path.exists(_SO):
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except OSError:
                _lib = None
    return _lib


def available() -> bool:
    return lib() is not None


def _as_pairs(pairs) -> np.ndarray:
    a = np.ascontiguousarray(pairs, dtype=np.int64)
    if a.ndim != 2 or a.shape[1] != 2:
        raise ValueError("pairs must be [n, 2]")
    return a


def fill_port_bitmaps(pairs, bitmap: np.ndarray) -> None:
    """OR (row, port) pairs into the uint32 [N, W] bitmap in place."""
    a = _as_pairs(pairs)
    l = lib()
    if l is not None and bitmap.flags.c_contiguous:
        l.fill_port_bitmaps(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(a),
            bitmap.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            bitmap.shape[0], bitmap.shape[1])
        return
    words = bitmap.shape[1]
    for row, port in a:
        if 0 <= row < bitmap.shape[0] and 0 < port < words * 32:
            bitmap[row, port // 32] |= np.uint32(1 << (port % 32))


def fill_multi_hot(pairs, out: np.ndarray) -> None:
    """Set (row, col) entries of the int8 [R, W] matrix to 1 in place."""
    a = _as_pairs(pairs)
    l = lib()
    if l is not None and out.flags.c_contiguous:
        l.fill_multi_hot(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(a),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            out.shape[0], out.shape[1])
        return
    rows, width = out.shape
    for row, col in a:
        if 0 <= row < rows and 0 <= col < width:
            out[row, col] = 1


def fnv1a64(data: bytes) -> int:
    l = lib()
    if l is not None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return int(l.fnv1a64(buf, len(data)))
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h
