"""The hyperkube analog — cmd/hyperkube: every component behind one
entrypoint, dispatched by the first argument:

    python -m kubernetes_tpu scheduler [--nodes N --pods P --config F]
    python -m kubernetes_tpu ktctl     [--server URL] VERB ...
    python -m kubernetes_tpu ktadm     {init|reset|preflight} --workdir D
    python -m kubernetes_tpu apiserver [--port P --nodes N]
    python -m kubernetes_tpu version

The reference builds one fat binary whose argv[0]/first-arg selects the
component (cmd/hyperkube/hyperkube.go Server registry); here the module
main does the same over the in-process components.
"""

from __future__ import annotations

import sys


def _run_apiserver(argv) -> int:
    """Standalone apiserver: REST facade over an in-process store with an
    optional hollow-node preload, serving until interrupted."""
    import argparse
    import time

    from kubernetes_tpu.api.types import make_node
    from kubernetes_tpu.server.apiserver import ApiServer
    from kubernetes_tpu.server.rest_http import RestServer

    ap = argparse.ArgumentParser(prog="kubernetes-tpu apiserver")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=0)
    ap.add_argument("--once", action="store_true",
                    help="print the address and exit (smoke mode)")
    args = ap.parse_args(argv)
    api = ApiServer()
    from kubernetes_tpu.api.workloads import Namespace
    api.store.create("Namespace", Namespace("default"))
    for i in range(args.nodes):
        api.store.create("Node", make_node(f"node-{i:04d}"))
    srv = RestServer(api, port=args.port)
    srv.start()
    print(f"apiserver listening on http://127.0.0.1:{srv.port}")
    if args.once:
        srv.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def _run_ktadm(argv) -> int:
    import argparse

    from kubernetes_tpu.cli.ktadm import KtAdm

    ap = argparse.ArgumentParser(prog="kubernetes-tpu ktadm")
    ap.add_argument("phase", choices=["init", "reset", "preflight"])
    ap.add_argument("--workdir", default="./ktadm-cluster")
    args = ap.parse_args(argv)
    adm = KtAdm()
    if args.phase == "init":
        adm.init(args.workdir)
    elif args.phase == "reset":
        adm.reset(args.workdir)
    else:
        return 1 if adm.preflight(args.workdir) else 0
    return 0


def _run_scheduler(argv) -> int:
    from kubernetes_tpu.server.daemon import main as daemon_main
    daemon_main(argv)
    return 0


def _run_ktctl(argv) -> int:
    from kubernetes_tpu.cli.ktctl import main as ktctl_main
    return ktctl_main(argv)


COMPONENTS = {
    "scheduler": _run_scheduler,
    "ktctl": _run_ktctl,
    "ktadm": _run_ktadm,
    "apiserver": _run_apiserver,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print("usage: python -m kubernetes_tpu "
              f"{{{'|'.join(sorted(COMPONENTS))}|version}} ...")
        return 0
    comp, rest = argv[0], argv[1:]
    if comp == "version":
        from kubernetes_tpu.server.rest_http import VERSION
        print(f"kubernetes-tpu {VERSION['gitVersion']} "
              f"(hyperkube-style dispatcher)")
        return 0
    fn = COMPONENTS.get(comp)
    if fn is None:
        print(f"error: unknown component {comp!r}; have "
              f"{sorted(COMPONENTS)} + version", file=sys.stderr)
        return 1
    return fn(rest)


if __name__ == "__main__":
    sys.exit(main())
