"""Pallas TPU kernels for the hot ops.

The PodFitsResources check (`ops/predicates.py resources_fit`,
reference predicates.go:556-624) is the one [P,N]-shaped op whose jnp
form materializes a [P, N, R] intermediate (`pod_req[:,None,:] +
requested[None,:,:]`): at 30k pods x 5k nodes x 8 resources that is
~4.8 GB of int32 traffic through HBM per wave. XLA usually fuses the
reduction, but the fusion is at the compiler's mercy; this kernel makes
the tiling explicit the Pallas way (pallas_guide.md): grid over
(P, N) tiles, node arrays transposed to [R, N] so each resource row is
a [1, N_BLK] lane vector, the R loop unrolled in-register — the [P,N,R]
cube never exists, each (bp, bn) output tile is produced from one
[bp, R] pod block + two [R, bn] node blocks resident in VMEM.

Semantics are bit-identical to resources_fit (the scratch/overlay
fallback of predicates.go:590-604 included); `resources_fit_fast`
dispatches to the kernel on TPU backends and to the reference jnp path
elsewhere, and the tests pin kernel-vs-jnp equality in interpret mode.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.state.snapshot import R_OVERLAY, R_SCRATCH

try:  # pallas is TPU-oriented; keep import failures non-fatal (CPU CI)
    from jax.experimental import pallas as pl
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    _HAVE_PALLAS = False

P_BLK = 128
N_BLK = 256


def _capacity_kernel(pod_req_ref, alloc_t_ref, req_t_ref, out_ref, *,
                     n_res: int):
    """One (P_BLK, N_BLK) output tile.

    pod_req_ref [P_BLK, Rpad] int32 — pod requests, resource axis last;
    alloc_t_ref / req_t_ref [Rpad, N_BLK] int32 — node arrays TRANSPOSED
    so slicing a resource yields a [1, N_BLK] lane row. The resource loop
    is a Python loop: n_res is static, so it unrolls at trace time into
    n_res fused VPU compare-ands — no [P,N,R] cube.
    """
    # everything stays int32 0/1 — Mosaic (this jax build) cannot place
    # i1 vector intermediates/stores ("Unsupported target bitwidth for
    # truncation"), so AND is multiply and select is arithmetic blend
    ok = None
    for r in range(n_res):
        if r in (R_SCRATCH, R_OVERLAY):
            continue  # handled by the storage special-case below
        total = pod_req_ref[:, r:r + 1] + req_t_ref[r:r + 1, :]
        fit_r = (total <= alloc_t_ref[r:r + 1, :]).astype(jnp.int32)
        ok = fit_r if ok is None else ok * fit_r
    # storage special-case (predicates.go:590-604): no overlay capacity
    # -> overlay requests fall back onto scratch space
    alloc_s = alloc_t_ref[R_SCRATCH:R_SCRATCH + 1, :]
    alloc_o = alloc_t_ref[R_OVERLAY:R_OVERLAY + 1, :]
    node_s = req_t_ref[R_SCRATCH:R_SCRATCH + 1, :]
    node_o = req_t_ref[R_OVERLAY:R_OVERLAY + 1, :]
    pod_s = pod_req_ref[:, R_SCRATCH:R_SCRATCH + 1]
    pod_o = pod_req_ref[:, R_OVERLAY:R_OVERLAY + 1]
    no_overlay = (alloc_o == 0).astype(jnp.int32)  # [1, bn]
    spill_ok = (pod_s + pod_o + node_s + node_o <= alloc_s).astype(jnp.int32)
    plain_ok = (pod_s + node_s <= alloc_s).astype(jnp.int32)
    scratch_ok = no_overlay * spill_ok + (1 - no_overlay) * plain_ok
    overlay_fit = (pod_o + node_o <= alloc_o).astype(jnp.int32)
    overlay_ok = no_overlay + (1 - no_overlay) * overlay_fit
    out_ref[:, :] = ok * scratch_ok * overlay_ok


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    want = ((size + mult - 1) // mult) * mult
    if want == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, want - size)
    return jnp.pad(x, pads)


def capacity_fits_pallas(pod_req: jnp.ndarray, alloc: jnp.ndarray,
                         requested: jnp.ndarray,
                         interpret: bool = False) -> jnp.ndarray:
    """The resource-fit mask [P, N] via the tiled kernel. Zero-padding is
    exact: padded pods request 0 (fit everywhere, rows sliced off), padded
    nodes have alloc 0 (total 0 <= 0 passes, columns sliced off)."""
    p, n_res = pod_req.shape
    n = alloc.shape[0]
    # resource axis padded to the sublane quantum so [Rpad, N_BLK] node
    # blocks tile cleanly; padded resources: 0 + 0 <= 0 -> pass
    r_pad = max(8, ((n_res + 7) // 8) * 8)
    pod_p = _pad_to(_pad_to(pod_req, 1, r_pad), 0, P_BLK)
    alloc_t = _pad_to(_pad_to(alloc, 1, r_pad).T, 1, N_BLK)
    req_t = _pad_to(_pad_to(requested, 1, r_pad).T, 1, N_BLK)
    pp, nn = pod_p.shape[0], alloc_t.shape[1]
    import functools
    out = pl.pallas_call(
        functools.partial(_capacity_kernel, n_res=n_res),
        out_shape=jax.ShapeDtypeStruct((pp, nn), jnp.int32),
        grid=(pp // P_BLK, nn // N_BLK),
        in_specs=[
            pl.BlockSpec((P_BLK, r_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((r_pad, N_BLK), lambda i, j: (0, j)),
            pl.BlockSpec((r_pad, N_BLK), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((P_BLK, N_BLK), lambda i, j: (i, j)),
        interpret=interpret,
    )(pod_p, alloc_t, req_t)
    return out[:p, :n] != 0


# ---------------------------------------------------------------------------
# topology-incidence matmul (SURVEY §7 phase 2's flagship kernel):
# [C,S,L] x [N,L] -> [C,S,N] — the static affinity hit matrix
# ---------------------------------------------------------------------------

M_BLK = 128
K_BLK = 512


def _incidence_kernel(a_ref, b_ref, o_ref):
    """One (M_BLK, N_BLK) tile of A @ B with the L (contraction) axis
    blocked over the third grid dimension — the canonical Pallas matmul
    shape (pallas_guide.md): zero the accumulator on the first k step,
    accumulate an MXU dot per k block. f32 is exact here: entries are
    0/1 incidences (or small int weights), so every partial sum stays
    far below 2^24."""
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)
    o_ref[...] += jnp.dot(a_ref[...], b_ref[...],
                          preferred_element_type=jnp.float32)


def incidence_matmul_pallas(a: jnp.ndarray, b_t: jnp.ndarray,
                            interpret: bool = False) -> jnp.ndarray:
    """A [M, L] int x B_t [N, L] int -> [M, N] int32, tiled (M,N,L) on
    the MXU. Zero padding is exact (0-rows/cols contribute 0)."""
    m, l = a.shape
    n = b_t.shape[0]
    a_p = _pad_to(_pad_to(a.astype(jnp.float32), 0, M_BLK), 1, K_BLK)
    b_p = _pad_to(_pad_to(b_t.astype(jnp.float32), 0, N_BLK), 1, K_BLK).T
    mm, kk = a_p.shape
    nn = b_p.shape[1]
    out = pl.pallas_call(
        _incidence_kernel,
        out_shape=jax.ShapeDtypeStruct((mm, nn), jnp.float32),
        grid=(mm // M_BLK, nn // N_BLK, kk // K_BLK),
        in_specs=[
            pl.BlockSpec((M_BLK, K_BLK), lambda i, j, k: (i, k)),
            pl.BlockSpec((K_BLK, N_BLK), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((M_BLK, N_BLK), lambda i, j, k: (i, j)),
        interpret=interpret,
    )(a_p, b_p)
    return out[:m, :n].astype(jnp.int32)


def precompute_static_fast(aff, labels: jnp.ndarray,
                           force: Optional[bool] = None,
                           interpret: bool = False):
    """Drop-in for affinity.precompute_static with the [C,S,L]x[N,L]
    allow-hit contraction (and the [C,L] forbid/prio ones, which batch
    into the same call) Pallas-tiled.

    Measured A/B on the real TPU chip (r5; 20-iter steady-state, jitted,
    block_until_ready, parity asserted on device):

        C=8   S=4 L=2048 N=5120   jnp 0.221 ms   pallas 0.044 ms  (5.0x)
        C=64  S=8 L=2048 N=5120   jnp 10.772 ms  pallas 10.658 ms (1.01x)
        C=256 S=8 L=4096 N=5120   jnp 13.108 ms  pallas 12.661 ms (1.04x)

    Stacking the three einsums into ONE tiled matmul dominates at small
    class counts (the common case: density batches have few classes) and
    never loses at large ones — so unlike resources_fit_fast (where the
    measurement said sub-tile shapes lose), the gate here is simply
    "pallas available on a TPU backend". Off-TPU the reference jnp path
    runs."""
    from kubernetes_tpu.ops.affinity import precompute_static
    c, s, l = aff["aff_allow"].shape
    n = labels.shape[0]
    use = force if force is not None else _use_pallas()
    if not use:
        return precompute_static(aff, labels)
    # one [C*(S+2), L] stack: allow terms, then forbid, then prio rows —
    # a single tiled matmul instead of three
    stacked = jnp.concatenate([
        aff["aff_allow"].reshape(c * s, l).astype(jnp.int32),
        aff["forbid_static"].astype(jnp.int32),
        aff["prio_static"].astype(jnp.int32)], axis=0)
    hits = incidence_matmul_pallas(stacked, labels.astype(jnp.int32),
                                   interpret=interpret)
    allow_hit = hits[:c * s].reshape(c, s, n) > 0
    forbid_hit = hits[c * s:c * s + c] > 0
    prio_counts = hits[c * s + c:]
    return {"allow_hit": allow_hit, "forbid_hit": forbid_hit,
            "prio_counts": prio_counts}


def _use_pallas() -> bool:
    env = os.environ.get("KT_PALLAS", "")
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        # an explicit opt-in still cannot run without the pallas import
        return _HAVE_PALLAS
    return _HAVE_PALLAS and jax.default_backend() == "tpu"


def resources_fit_fast(pod_req: jnp.ndarray, zero_req: jnp.ndarray,
                       alloc: jnp.ndarray, requested: jnp.ndarray,
                       force: Optional[bool] = None,
                       interpret: bool = False) -> jnp.ndarray:
    """Drop-in for predicates.resources_fit: Pallas-tiled on TPU, the
    reference jnp path elsewhere (and for sub-tile batches where tile
    padding would dominate). The zero-request override (predicates.go
    :576-578) composes outside the kernel — a [P,N] op XLA fuses into
    the surrounding AND-chain either way."""
    if force:
        # explicit force bypasses the size gate — the tests rely on it to
        # actually exercise the kernel on small hand cases
        fit = capacity_fits_pallas(pod_req, alloc, requested,
                                   interpret=interpret)
        return fit | zero_req[:, None]
    # per-dimension gate, set by MEASUREMENT (density bench A/B): the
    # kernel only pays off when both axes fill their tiles — the one-shot
    # full-batch fits() (P in the thousands). Inside the wave loop the
    # class axis is small (C~10): padding 7->128 rows plus the per-call
    # [N,R]->[R,N] transpose made waves 40-70% slower than the jnp path
    # XLA already fuses (0.83-1.17s vs 0.52-0.56s), so sub-tile axes
    # stay on the reference path.
    if force is None and _use_pallas() \
            and pod_req.shape[0] >= P_BLK and alloc.shape[0] >= N_BLK:
        fit = capacity_fits_pallas(pod_req, alloc, requested,
                                   interpret=interpret)
        return fit | zero_req[:, None]
    from kubernetes_tpu.ops.predicates import resources_fit
    return resources_fit(pod_req, zero_req, alloc, requested)
