"""Policy-configured (parameterized) predicates & priorities.

The four algorithm-registry entries that exist only as Policy arguments in
the reference — they have no default-provider registration and are built
per-config by factory/plugins.go:135-152 (predicates) and :235-251
(priorities):

  ServiceAffinity        predicates.go:783-855 checkServiceAffinity
  NodeLabelPresence      predicates.go:717-752 CheckNodeLabelPresence
  ServiceAntiAffinity    priorities/selector_spreading.go:220-268
  NodeLabel (preference) priorities/node_label.go:45-60

Device mapping: all four are per-batch STATIC in the happy path — node-label
checks are pure node functions, and the service-coupled pair reads the pod
lister, which in the reference is the scheduler cache (factory.go:139
``podLister: schedulerCache``). That cache sees in-flight assumed pods, so a
class that a Service actually selects is order-dependent within a batch and
must take the exact sequential host path (needs_host flag); every other
class gets exact [C, N] masks/scores computed here host-side and shipped as
``policy_fit`` / ``policy_score`` class arrays (ANDed/added by
ops/predicates.static_fits and the engines' static score fold).

Determinism note: the reference's ``pods[0]`` (ServiceAffinity backfill) and
``services[0]`` (ServiceAntiAffinity) come from informer-store iteration
order, which Go does not define. We canonicalize: pods sorted by
(namespace, name), services sorted by (namespace, name) — a fixed choice
within the reference's set of permitted behaviors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import MAX_PRIORITY, Node, Pod, WorkloadObject


@dataclass(frozen=True)
class NodeLabelPresencePred:
    """predicates.go:717 CheckNodeLabelPresence (Policy `labelsPresence`)."""
    labels: Tuple[str, ...]
    presence: bool = True


@dataclass(frozen=True)
class ServiceAffinityPred:
    """predicates.go:783 checkServiceAffinity (Policy `serviceAffinity`)."""
    labels: Tuple[str, ...]


@dataclass(frozen=True)
class NodeLabelPrio:
    """node_label.go:45 CalculateNodeLabelPriorityMap (`labelPreference`)."""
    label: str
    presence: bool
    weight: int


@dataclass(frozen=True)
class ServiceAntiAffinityPrio:
    """selector_spreading.go:220 CalculateAntiAffinityPriority
    (`serviceAntiAffinity`)."""
    label: str
    weight: int


def _anti_affinity_core(spec: "ServiceAntiAffinityPrio", pod: Pod,
                        workloads, all_pods,
                        node_labels: Sequence[Optional[Dict[str, str]]]
                        ) -> List[int]:
    """Shared ServiceAntiAffinity scoring over per-node label dicts (None =
    node unknown -> score 0). selector_spreading.go:223-268."""
    services = [s for s in _services(workloads) if s.selects(pod)]
    ns_pods: List[Pod] = []
    if services:
        sel = services[0].match_labels
        ns_pods = [q for q, _node in all_pods
                   if q.namespace == pod.namespace
                   and _sel_from_labels(sel, q)]
    node_label_value: Dict[str, str] = {}
    for q, qnode in all_pods:
        if qnode is not None and spec.label in qnode.labels:
            node_label_value[qnode.name] = qnode.labels[spec.label]
    counts: Dict[str, int] = {}
    for q in ns_pods:
        val = node_label_value.get(q.node_name)
        if val is not None:
            counts[val] = counts.get(val, 0) + 1
    num = len(ns_pods)
    out = []
    for lbls in node_labels:
        if lbls is None or spec.label not in lbls:
            out.append(0)
        elif num > 0:
            c = counts.get(lbls[spec.label], 0)
            out.append((MAX_PRIORITY * (num - c)) // num)
        else:
            out.append(MAX_PRIORITY)
    return out


def _services(workloads: Sequence[WorkloadObject]) -> List[WorkloadObject]:
    svcs = [w for w in workloads if w.kind == "Service"]
    svcs.sort(key=lambda w: (w.namespace, w.name))
    return svcs


def _sel_from_labels(labels: Dict[str, str], pod: Pod) -> bool:
    """labels.SelectorFromSet(labels).Matches(pod.labels) — equality on
    every key (an empty set matches everything)."""
    return all(pod.labels.get(k) == v for k, v in labels.items())


class PolicyAlgorithms:
    """The configured algorithm set, evaluable both as class-level device
    arrays (static side) and per-pod at the object level (oracle side)."""

    def __init__(self,
                 predicates: Sequence = (),
                 priorities: Sequence = ()):
        self.predicates = tuple(predicates)
        self.priorities = tuple(priorities)

    @property
    def active(self) -> bool:
        return bool(self.predicates or self.priorities)

    # ----------------------------------------------------------- oracle side

    def _service_affinity_labels(self, spec: ServiceAffinityPred, pod: Pod,
                                 workloads, all_pods) -> Dict[str, str]:
        """The affinityLabels map of checkServiceAffinity: node_selector
        values first, then backfill unset labels from the node of the first
        cache pod matching the pod's own labels — only when some Service
        selects the pod (predicates.go:798-846)."""
        affinity_labels = {l: pod.node_selector[l] for l in spec.labels
                           if l in pod.node_selector}
        if len(spec.labels) > len(affinity_labels):
            services = [s for s in _services(workloads) if s.selects(pod)]
            if services:
                matched = [(q, node) for q, node in all_pods
                           if q.namespace == pod.namespace
                           and _sel_from_labels(pod.labels, q)]
                matched.sort(key=lambda t: (t[0].namespace, t[0].name))
                if matched and matched[0][1] is not None:
                    first_node = matched[0][1]
                    for l in spec.labels:
                        if l not in affinity_labels \
                                and l in first_node.labels:
                            affinity_labels[l] = first_node.labels[l]
        return affinity_labels

    def oracle_fit(self, pod: Pod, node: Node, ctx) -> bool:
        """All configured predicates against one node (exact object level)."""
        for spec in self.predicates:
            if isinstance(spec, NodeLabelPresencePred):
                for l in spec.labels:
                    exists = l in node.labels
                    if exists != spec.presence:
                        return False
            elif isinstance(spec, ServiceAffinityPred):
                want = self._service_affinity_labels(
                    spec, pod, ctx.workloads, ctx.all_pods())
                if not all(node.labels.get(k) == v
                           for k, v in want.items()):
                    return False
        return True

    def oracle_scores(self, pod: Pod, infos, ctx) -> List[int]:
        """Weighted sum of configured priorities per info (exact)."""
        out = [0] * len(infos)
        for spec in self.priorities:
            if isinstance(spec, NodeLabelPrio):
                for i, info in enumerate(infos):
                    node = info.node
                    if node is None:
                        continue
                    exists = spec.label in node.labels
                    if exists == spec.presence:
                        out[i] += MAX_PRIORITY * spec.weight
            elif isinstance(spec, ServiceAntiAffinityPrio):
                per = self._anti_affinity_scores(spec, pod, ctx.workloads,
                                                 ctx.all_pods(),
                                                 [i.node for i in infos])
                for i in range(len(infos)):
                    out[i] += per[i] * spec.weight
        return out

    def _anti_affinity_scores(self, spec: ServiceAntiAffinityPrio, pod: Pod,
                              workloads, all_pods,
                              nodes: Sequence[Optional[Node]]) -> List[int]:
        """selector_spreading.go:223-268, exact integer math:
        int(10*(num-c)/num) == (10*(num-c))//num for the reachable
        (non-negative) inputs."""
        return _anti_affinity_core(
            spec, pod, workloads, all_pods,
            [(n.labels if n is not None else None) for n in nodes])

    # ----------------------------------------------------------- device side

    def needs_host(self, reps: Sequence[Pod],
                   workloads: Sequence[WorkloadObject]) -> np.ndarray:
        """[C] bool — classes whose evaluation is order-dependent in-batch
        (a Service selects them, and the reference's cache-backed pod lister
        would see earlier in-batch commits)."""
        out = np.zeros(len(reps), dtype=bool)
        sa_pred = any(isinstance(s, ServiceAffinityPred)
                      for s in self.predicates)
        saa_prio = any(isinstance(s, ServiceAntiAffinityPrio)
                       for s in self.priorities)
        if not (sa_pred or saa_prio):
            return out
        svcs = _services(workloads)
        for c, rep in enumerate(reps):
            selected = any(s.selects(rep) for s in svcs)
            if saa_prio and selected:
                out[c] = True
            if sa_pred and selected:
                # only order-dependent when backfill can engage (some
                # configured label missing from the pod's own nodeSelector)
                for spec in self.predicates:
                    if isinstance(spec, ServiceAffinityPred) and any(
                            l not in rep.node_selector for l in spec.labels):
                        out[c] = True
        return out

    def static_class_arrays(self, reps: Sequence[Pod], snap,
                            workloads: Sequence[WorkloadObject],
                            all_pods, c_pad: int,
                            skip: Optional[np.ndarray] = None
                            ) -> Tuple[Optional[np.ndarray],
                                       Optional[np.ndarray]]:
        """(policy_fit [c_pad, Npad] bool, policy_score [c_pad, Npad] int32)
        over the snapshot's raw node-label rows (exact — the label-pair
        vocab is irrelevant here). Classes in `skip` (the needs_host mask)
        get all-True fit / zero score without evaluation; the host path
        re-evaluates them exactly and the fast path never reads their rows.
        Padding class rows: fit False (they must stay impossible)."""
        n_pad = snap.valid.shape[0]
        row_labels = snap._row_labels  # raw dicts, padding rows = {}
        n_real = len(snap.node_names)
        fit = None
        score = None
        if self.predicates:
            fit = np.zeros((c_pad, n_pad), dtype=bool)
            for c, rep in enumerate(reps):
                row = np.ones(n_pad, dtype=bool)
                row[n_real:] = False
                if skip is not None and skip[c]:
                    fit[c] = row
                    continue
                for spec in self.predicates:
                    if isinstance(spec, NodeLabelPresencePred):
                        for l in spec.labels:
                            has = np.fromiter(
                                (l in row_labels[i] for i in range(n_real)),
                                dtype=bool, count=n_real)
                            if spec.presence:
                                row[:n_real] &= has
                            else:
                                row[:n_real] &= ~has
                    elif isinstance(spec, ServiceAffinityPred):
                        want = self._service_affinity_labels(
                            spec, rep, workloads, all_pods)
                        for k, v in want.items():
                            m = np.fromiter(
                                (row_labels[i].get(k) == v
                                 for i in range(n_real)),
                                dtype=bool, count=n_real)
                            row[:n_real] &= m
                fit[c] = row
        if self.priorities:
            score = np.zeros((c_pad, n_pad), dtype=np.int32)
            for c, rep in enumerate(reps):
                if skip is not None and skip[c]:
                    continue
                for spec in self.priorities:
                    if isinstance(spec, NodeLabelPrio):
                        has = np.fromiter(
                            (spec.label in row_labels[i]
                             for i in range(n_real)),
                            dtype=bool, count=n_real)
                        hit = has if spec.presence else ~has
                        score[c, :n_real] += np.where(
                            hit, MAX_PRIORITY * spec.weight, 0
                        ).astype(np.int32)
                    elif isinstance(spec, ServiceAntiAffinityPrio):
                        per = self._anti_affinity_scores_rows(
                            spec, rep, workloads, all_pods,
                            row_labels, n_real)
                        score[c, :n_real] += np.asarray(
                            per, dtype=np.int64).astype(np.int32) \
                            * spec.weight
        return fit, score

    def _anti_affinity_scores_rows(self, spec, rep, workloads, all_pods,
                                   row_labels, n_real) -> List[int]:
        """_anti_affinity_scores against snapshot label rows (device-side
        static evaluation for classes no Service selects — then ns_pods is
        empty or count-stable, so this equals the oracle)."""
        return _anti_affinity_core(spec, rep, workloads, all_pods,
                                   [row_labels[i] for i in range(n_real)])


# ---------------------------------------------------------------------------
# Policy -> (kernel priorities, PolicyAlgorithms)
# ---------------------------------------------------------------------------

# every predicate name registered in the reference (factory/plugins.go
# RegisterFitPredicate call sites + defaults.go) that our fixed kernel chain
# already covers — accepted, no per-name toggling (the chain is a superset
# of GeneralPredicates, like the reference's mandatory predicates)
KNOWN_PREDICATES = frozenset({
    "PodFitsPorts", "PodFitsHostPorts", "PodFitsResources", "HostName",
    "MatchNodeSelector", "NoDiskConflict", "NoVolumeZoneConflict",
    "MaxEBSVolumeCount", "MaxGCEPDVolumeCount", "MaxAzureDiskVolumeCount",
    "MatchInterPodAffinity", "GeneralPredicates", "PodToleratesNodeTaints",
    "CheckNodeMemoryPressure", "CheckNodeDiskPressure", "CheckNodeCondition",
    "NoVolumeNodeConflict",
})

KNOWN_PRIORITIES = frozenset({
    "LeastRequestedPriority", "MostRequestedPriority",
    "BalancedResourceAllocation", "SelectorSpreadPriority",
    "ServiceSpreadingPriority", "InterPodAffinityPriority",
    "NodePreferAvoidPodsPriority", "NodeAffinityPriority",
    "TaintTolerationPriority", "ImageLocalityPriority", "EqualPriority",
})


def algorithms_from_policy(policy) -> Tuple[Tuple[Tuple[str, int], ...],
                                            "PolicyAlgorithms"]:
    """(kernel priority tuple, PolicyAlgorithms) from a parsed api.policy
    Policy — the CreateFromConfig path (factory.go:619). Unknown names
    raise: config that silently does nothing is a lying config file
    (VERDICT r3 missing #4)."""
    preds = []
    for p in (policy.predicates or []):
        if p.service_affinity is not None:
            preds.append(ServiceAffinityPred(tuple(p.service_affinity.labels)))
        elif p.labels_presence is not None:
            preds.append(NodeLabelPresencePred(
                tuple(p.labels_presence.labels), p.labels_presence.presence))
        elif p.name not in KNOWN_PREDICATES:
            raise ValueError(f"unknown predicate {p.name!r} in Policy")
    kernel_prios: List[Tuple[str, int]] = []
    prios = []
    for p in (policy.priorities or []):
        if p.service_antiaffinity_label is not None:
            prios.append(ServiceAntiAffinityPrio(
                p.service_antiaffinity_label, p.weight))
        elif p.label_preference is not None:
            lp = p.label_preference
            prios.append(NodeLabelPrio(lp.get("label", ""),
                                       bool(lp.get("presence", True)),
                                       p.weight))
        elif p.name == "ServiceSpreadingPriority":
            # legacy alias: spreading by services only (plugins.go:70-76);
            # our spread kernel consumes the provided workload set, so the
            # alias maps to SelectorSpreadPriority
            kernel_prios.append(("SelectorSpreadPriority", p.weight))
        elif p.name in KNOWN_PRIORITIES:
            kernel_prios.append((p.name, p.weight))
        else:
            raise ValueError(f"unknown priority {p.name!r} in Policy")
    return tuple(kernel_prios), PolicyAlgorithms(preds, prios)
