"""Vectorized fit predicates: the pod x node filter as one fused kernel.

Replaces the reference's findNodesThatFit hot loop
(plugin/pkg/scheduler/core/generic_scheduler.go:163-232: 16-way
workqueue.Parallelize over nodes, each worker running the predicate chain
object-by-object) with dense [P, N] masks computed in one XLA program.

Predicate parity map (reference: plugin/pkg/scheduler/algorithm/predicates/predicates.go):
  PodFitsResources        :556  -> resources_fit (incl. zero-request early-exit
                                   :576 and the overlay->scratch fallback :590-604)
  PodFitsHost             :698  -> host_fit
  PodFitsHostPorts        :859  -> ports_fit (bitmap gather over 65536 ports)
  PodMatchNodeSelector    :686  -> selector_fit (OR-of-AND terms as int8 matmuls)
  PodToleratesNodeTaints  :1241 -> taints_fit (intolerated x taint matmul)
  CheckNodeCondition      :1306 -> node_ok (precomputed host-side verdict)
  CheckNodeMemoryPressure :1274 -> mem_pressure_fit (best-effort pods only)
  CheckNodeDiskPressure   :1296 -> disk_pressure_fit
  GeneralPredicates       :900  -> resources & host & ports & selector

All functions are shape-polymorphic jittable JAX; inputs are the arrays
produced by kubernetes_tpu.state.snapshot (node side) and PodBatch (pod side),
passed as two dicts (pytrees). Integer semantics are preserved exactly.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from kubernetes_tpu.state.snapshot import (
    NUM_BASE_RESOURCES,
    R_GPU,
    R_MEM,
    R_CPU,
    R_OVERLAY,
    R_SCRATCH,
)

Arrays = Dict[str, jnp.ndarray]


_NODE_ARRAY_KEYS = ("alloc", "requested", "nonzero", "pod_count",
                    "allowed_pods", "schedulable", "mem_pressure",
                    "disk_pressure", "labels", "taints_sched",
                    "taints_pref", "port_bitmap", "valid", "avoid",
                    "image_sizes", "vol_present", "vol_rw", "pd_present",
                    "pd_counts", "pd_kind", "pd_max", "has_zone")


def node_arrays(snap) -> Arrays:
    """Assemble the node-side pytree from a ClusterSnapshot.

    Zero-copy VIEW seam: callers consume the dispatch synchronously
    (the extender cold path, tests) before any snapshot mutation can run,
    so aliasing the live snapshot arrays is safe AND free. Anything that
    holds device work across host bookkeeping must go through the
    engine's copying seam instead (_nodes_on_device — GL001's
    copy-required contract). GRAFT_SANITIZE=1 upgrades these to verified
    copies, so sanitized runs don't depend on the synchronous-consumption
    argument at all."""
    from kubernetes_tpu.analysis.sanitize import upload_view
    return {k: upload_view(getattr(snap, k)) for k in _NODE_ARRAY_KEYS}


def bucket(n: int, lo: int = 16) -> int:
    """Power-of-2 shape bucket: jit kernels specialize per shape, so batch
    axes are padded to buckets to bound recompiles at log2(max) variants."""
    p = lo
    while p < n:
        p *= 2
    return p


def pod_arrays_padded(batch, rows: int) -> Arrays:
    """pod_arrays with the batch axis padded to `rows`. Padding rows are
    marked `impossible` so they fit nothing, commit nothing, and never tick
    the RR counter — inert in both the strict scan and the wave kernel.
    Padding happens in NUMPY: eager jnp ops each compile a tiny XLA program
    (expensive per-shape on a tunneled backend); np.pad + one device_put per
    array costs no compiles."""
    import numpy as _np
    arrs = _pod_arrays_np(batch)
    c = len(batch)
    if rows < c:
        raise ValueError(f"rows {rows} < batch size {c}")
    out = {}
    for k, a in arrs.items():
        if rows > c:
            pad = _np.zeros((rows - c,) + a.shape[1:], dtype=a.dtype)
            if k == "impossible":
                pad[:] = True
            a = _np.concatenate([a, pad], axis=0)
        out[k] = jnp.asarray(a)
    return out


def pod_arrays(batch) -> Arrays:
    """Assemble the pod-side pytree from a PodBatch (one device_put each)."""
    return {k: jnp.asarray(v) for k, v in _pod_arrays_np(batch).items()}


# selector/preference slot axes sized by actual usage (PodBatch): key ->
# (axis -> dim kind). Zero padding is inert on every one of them — padded
# terms carry sel_term_valid/pref_valid False (the OR skips them) and padded
# any-groups carry *_any_used False (the conjunct auto-passes).
_SLOT_AXES = {
    "sel_req_all": {1: "T"}, "sel_req_any": {1: "T", 2: "A"},
    "sel_forbid": {1: "T"}, "sel_term_valid": {1: "T"},
    "sel_any_used": {1: "T", 2: "A"}, "sel_unsat": {1: "T"},
    "pref_req_all": {1: "TP"}, "pref_req_any": {1: "TP", 2: "A"},
    "pref_forbid": {1: "TP"}, "pref_any_used": {1: "TP", 2: "A"},
    "pref_valid": {1: "TP"}, "pref_unsat": {1: "TP"},
    "pref_empty": {1: "TP"}, "pref_weight": {1: "TP"},
    "pvaff_req_any": {1: "A"}, "pvaff_any_used": {1: "A"},
}


def pod_arrays_bucketed(batch, rows: int = 0) -> Arrays:
    """pod_arrays with the selector-term / any-group / preferred-term axes
    padded up to power-of-2 buckets. PodBatch sizes those axes to the batch's
    actual usage, so [1,N] single-pod evaluations (the extender fast lane)
    would otherwise compile one kernel variant per distinct term count;
    bucketing bounds the variants at log2(slot caps) like every other batch
    axis (bucket()).

    ``rows`` > 0 additionally pads the CLASS axis to that many rows (the
    coalesced multi-class extender eval, ISSUE 9): padding rows are
    `impossible` — they fit nothing and score nothing — exactly the
    pod_arrays_padded contract, so a batch of B distinct classes compiles
    one kernel per bucket(B), not one per B."""
    import numpy as _np
    arrs = _pod_arrays_np(batch)
    c = len(batch)
    if rows and rows < c:
        raise ValueError(f"rows {rows} < batch size {c}")
    dims = {"T": bucket(arrs["sel_req_all"].shape[1], lo=1),
            "A": bucket(arrs["sel_req_any"].shape[2], lo=1),
            "TP": bucket(arrs["pref_req_all"].shape[1], lo=1)}
    out = {}
    for k, a in arrs.items():
        axes = _SLOT_AXES.get(k)
        if axes:
            widths = [(0, 0)] * a.ndim
            grow = False
            for ax, kind in axes.items():
                pad = dims[kind] - a.shape[ax]
                if pad > 0:
                    widths[ax] = (0, pad)
                    grow = True
            if grow:
                a = _np.pad(a, widths)
        if rows and rows > c:
            pad = _np.zeros((rows - c,) + a.shape[1:], dtype=a.dtype)
            if k == "impossible":
                pad[:] = True
            a = _np.concatenate([a, pad], axis=0)
        out[k] = jnp.asarray(a)
    return out


def _pod_arrays_np(batch):
    """The pod-side arrays as host numpy, keyed like pod_arrays."""
    return {
        "req": batch.req,
        "nonzero": batch.nonzero,
        "zero_req": batch.zero_req,
        "impossible": batch.impossible,
        "best_effort": batch.best_effort,
        "ports": batch.ports,
        "intolerated": batch.intolerated,
        "intolerated_pref": batch.intolerated_pref,
        "host_required": batch.host_required,
        "has_host": batch.has_host,
        "sel_req_all": batch.sel_req_all,
        "sel_req_any": batch.sel_req_any,
        "sel_forbid": batch.sel_forbid,
        "sel_term_valid": batch.sel_term_valid,
        "sel_any_used": batch.sel_any_used,
        "sel_unsat": batch.sel_unsat,
        "has_selector": batch.has_selector,
        "pref_req_all": batch.pref_req_all,
        "pref_req_any": batch.pref_req_any,
        "pref_forbid": batch.pref_forbid,
        "pref_any_used": batch.pref_any_used,
        "pref_valid": batch.pref_valid,
        "pref_unsat": batch.pref_unsat,
        "pref_empty": batch.pref_empty,
        "pref_weight": batch.pref_weight,
        "avoid_idx": batch.avoid_idx,
        "img_count": batch.img_count,
        "vol_hard": batch.vol_hard,
        "vol_ro": batch.vol_ro,
        "pd_req": batch.pd_req,
        "pd_req_count": batch.pd_req_count,
        "vz_req": batch.vz_req,
        "vz_err": batch.vz_err,
        "pvaff_req_all": batch.pvaff_req_all,
        "pvaff_req_any": batch.pvaff_req_any,
        "pvaff_forbid": batch.pvaff_forbid,
        "pvaff_any_used": batch.pvaff_any_used,
        "pvaff_unsat": batch.pvaff_unsat,
        "pvaff_has": batch.pvaff_has,
    }


# ---------------------------------------------------------------------------
# capacity-dependent predicates (re-evaluated inside the placement scan)
# ---------------------------------------------------------------------------


def resources_fit(pod_req: jnp.ndarray, zero_req: jnp.ndarray,
                  alloc: jnp.ndarray, requested: jnp.ndarray) -> jnp.ndarray:
    """PodFitsResources (predicates.go:556-624) minus the pod-count check.

    pod_req [P,R], zero_req [P], alloc [N,R], requested [N,R] -> bool [P,N].
    Column layout: 0=cpu 1=mem 2=gpu 3=scratch 4=overlay 5..=extended.
    """
    total = pod_req[:, None, :] + requested[None, :, :]  # [P,N,R]
    ok = total <= alloc[None, :, :]
    # cpu/mem/gpu + extended: plain elementwise
    plain = jnp.concatenate(
        [ok[..., :R_SCRATCH], ok[..., NUM_BASE_RESOURCES:]], axis=-1
    ).all(axis=-1)
    # storage special-case (predicates.go:590-604): when the node reports no
    # overlay capacity, overlay requests fall back onto scratch space.
    alloc_s = alloc[None, :, R_SCRATCH]
    alloc_o = alloc[None, :, R_OVERLAY]
    pod_s = pod_req[:, None, R_SCRATCH]
    pod_o = pod_req[:, None, R_OVERLAY]
    node_s = requested[None, :, R_SCRATCH]
    node_o = requested[None, :, R_OVERLAY]
    no_overlay = alloc_o == 0
    scratch_ok = jnp.where(
        no_overlay,
        pod_s + pod_o + node_s + node_o <= alloc_s,
        pod_s + node_s <= alloc_s,
    )
    overlay_ok = no_overlay | (pod_o + node_o <= alloc_o)
    fit = plain & scratch_ok & overlay_ok
    # all-zero request skips resource checks entirely (predicates.go:576-578)
    return fit | zero_req[:, None]


def pod_count_fit(pod_count: jnp.ndarray, allowed_pods: jnp.ndarray) -> jnp.ndarray:
    """len(pods)+1 <= allowedPodNumber (predicates.go:563-566). [N] -> [N]."""
    return pod_count + 1 <= allowed_pods


def ports_fit(ports: jnp.ndarray, port_bitmap: jnp.ndarray) -> jnp.ndarray:
    """PodFitsHostPorts (predicates.go:859-878) via packed-bitmap gather.

    ports [P,8] int32 with -1 sentinel; port_bitmap [N,2048] uint32 -> [P,N].
    """
    want = ports >= 0
    safe = jnp.maximum(ports, 0)
    word = safe // 32  # [P,8]
    bit = (safe % 32).astype(jnp.uint32)
    # gather words: [N, P, 8]
    gathered = jnp.take(port_bitmap, word, axis=1)
    hit = ((gathered >> bit[None, :, :]) & jnp.uint32(1)).astype(bool)
    conflict = (hit & want[None, :, :]).any(axis=-1)  # [N,P]
    return ~conflict.T


def no_disk_conflict(vol_hard: jnp.ndarray, vol_ro: jnp.ndarray,
                     vol_present: jnp.ndarray, vol_rw: jnp.ndarray
                     ) -> jnp.ndarray:
    """NoDiskConflict (predicates.go:183-196) as two int8 matmuls over the
    conflict-key vocab: a HARD key (EBS, or any read-write mount) conflicts
    with any presence; an RO key conflicts only with a read-write mount.
    vol_hard/vol_ro [P,Vc]; vol_present/vol_rw [N,Vc] -> bool [P,N]."""
    hard_hit = jnp.einsum("pv,nv->pn", vol_hard, vol_present,
                          preferred_element_type=jnp.int32)
    ro_hit = jnp.einsum("pv,nv->pn", vol_ro, vol_rw,
                        preferred_element_type=jnp.int32)
    return (hard_hit == 0) & (ro_hit == 0)


def max_pd_fit(pd_req: jnp.ndarray, pd_req_count: jnp.ndarray,
               pd_kind: jnp.ndarray, pd_present: jnp.ndarray,
               pd_counts: jnp.ndarray, pd_max: jnp.ndarray) -> jnp.ndarray:
    """MaxPDVolumeCount for all three filters (predicates.go:285-323):
    numExisting + numNew <= max, where numNew = pod's distinct filtered ids
    not already on the node; a pod with no kind-f volumes passes filter f
    (the quick return at :297-300).

    pd_req [P,Vpd], pd_req_count [P,3], pd_kind [3,Vpd], pd_present [N,Vpd],
    pd_counts [N,3], pd_max [3] -> bool [P,N]."""
    fit = None
    for k in range(3):
        req_k = pd_req * pd_kind[k][None, :]  # [P,Vpd] int8
        overlap = jnp.einsum("pv,nv->pn", req_k, pd_present,
                             preferred_element_type=jnp.int32)
        new = pd_req_count[:, k][:, None] - overlap
        ok = ((pd_req_count[:, k][:, None] == 0)
              | (pd_counts[None, :, k] + new <= pd_max[k]))
        fit = ok if fit is None else fit & ok
    return fit


# ---------------------------------------------------------------------------
# capacity-independent predicates (computed once per batch, MXU matmuls)
# ---------------------------------------------------------------------------


def volume_zone_fit(vz_req: jnp.ndarray, vz_err: jnp.ndarray,
                    labels: jnp.ndarray, has_zone: jnp.ndarray) -> jnp.ndarray:
    """NoVolumeZoneConflict (predicates.go:404-474): nodes with no
    zone/region labels pass (fast-path BEFORE PVC resolution, so resolution
    errors — vz_err — fail only zone-labeled nodes); otherwise every
    (zone-key, value) pair demanded by the pod's bound PVs must be present.
    vz_req [P,L] over the label-pair vocab; labels [N,L]; has_zone [N]."""
    cnt = jnp.einsum("pl,nl->pn", vz_req, labels.astype(jnp.int8),
                     preferred_element_type=jnp.int32)
    need = vz_req.astype(jnp.int32).sum(axis=-1)[:, None]
    return (~has_zone[None, :]) | ((cnt == need) & ~vz_err[:, None])


def pv_affinity_fit(pods: Arrays, labels: jnp.ndarray) -> jnp.ndarray:
    """NoVolumeNodeConflict (predicates.go:1354-1411 + util.go:193): the
    pod's bound PVs' node-affinity requirements, ANDed into one conjunct,
    evaluated like one selector term. Pass-through for pods without PV
    affinity (pvaff_has False)."""
    lab = labels.astype(jnp.int8)
    all_cnt = jnp.einsum("pl,nl->pn", pods["pvaff_req_all"], lab,
                         preferred_element_type=jnp.int32)
    need = pods["pvaff_req_all"].astype(jnp.int32).sum(axis=-1)[:, None]
    forbid_cnt = jnp.einsum("pl,nl->pn", pods["pvaff_forbid"], lab,
                            preferred_element_type=jnp.int32)
    any_cnt = jnp.einsum("pal,nl->pan", pods["pvaff_req_any"], lab,
                         preferred_element_type=jnp.int32)
    any_ok = ((any_cnt > 0) | ~pods["pvaff_any_used"][:, :, None]).all(axis=1)
    ok = ((all_cnt == need) & (forbid_cnt == 0) & any_ok
          & ~pods["pvaff_unsat"][:, None])
    return ok | ~pods["pvaff_has"][:, None]


def selector_fit(pods: Arrays, labels: jnp.ndarray) -> jnp.ndarray:
    """PodMatchNodeSelector + required node affinity (predicates.go:625-696).

    Terms are OR'd; inside a term requirements are AND'd. Compilation into
    req_all / req_any / forbid sets happens host-side (snapshot.PodBatch);
    here it is three int8 matmuls against node labels [N,L] and compares.
    """
    req_all = pods["sel_req_all"]  # [P,T,L]
    req_any = pods["sel_req_any"]  # [P,T,A,L]
    forbid = pods["sel_forbid"]  # [P,T,L]
    lab = labels.astype(jnp.int8)
    all_cnt = jnp.einsum("ptl,nl->ptn", req_all, lab,
                         preferred_element_type=jnp.int32)
    need = req_all.astype(jnp.int32).sum(axis=-1)  # [P,T]
    all_ok = all_cnt == need[:, :, None]
    forbid_cnt = jnp.einsum("ptl,nl->ptn", forbid, lab,
                            preferred_element_type=jnp.int32)
    forbid_ok = forbid_cnt == 0
    any_cnt = jnp.einsum("ptal,nl->ptan", req_any, lab,
                         preferred_element_type=jnp.int32)
    any_ok = ((any_cnt > 0) | ~pods["sel_any_used"][:, :, :, None]).all(axis=2)
    term_ok = (all_ok & forbid_ok & any_ok
               & pods["sel_term_valid"][:, :, None]
               & ~pods["sel_unsat"][:, :, None])
    return term_ok.any(axis=1) | ~pods["has_selector"][:, None]


def taints_fit(intolerated: jnp.ndarray, taints_sched: jnp.ndarray) -> jnp.ndarray:
    """PodToleratesNodeTaints (predicates.go:1241): fail when the node has any
    NoSchedule/NoExecute taint the pod does not tolerate. int8 matmul."""
    cnt = jnp.einsum("pt,nt->pn", intolerated, taints_sched.astype(jnp.int8),
                     preferred_element_type=jnp.int32)
    return cnt == 0


def host_fit(has_host: jnp.ndarray, host_required: jnp.ndarray, n: int) -> jnp.ndarray:
    """PodFitsHost (predicates.go:698-712). [P] -> [P,N]."""
    idx = jnp.arange(n, dtype=jnp.int32)
    return (~has_host[:, None]) | (host_required[:, None] == idx[None, :])


def node_condition_fit(pods: Arrays, nodes: Arrays) -> jnp.ndarray:
    """CheckNodeCondition + pressure predicates (predicates.go:1274-1337).
    Node-side verdicts are precomputed host-side; composition here."""
    ok = nodes["schedulable"] & nodes["valid"]  # [N]
    mem_ok = (~pods["best_effort"][:, None]) | (~nodes["mem_pressure"][None, :])
    disk_ok = ~nodes["disk_pressure"][None, :]
    return ok[None, :] & mem_ok & disk_ok


def static_fits(pods: Arrays, nodes: Arrays) -> jnp.ndarray:
    """All spec-INdependent predicates -> [P,N]. Computed once per batch;
    safe to reuse across the placement scan because nothing here changes as
    pods commit (labels/taints/host are node-spec facts). Node CONDITIONS
    (Ready/pressure/cordon/membership) are deliberately NOT in here since
    ISSUE 8: they flip under churn while the engine's cached precompute
    (waves.precompute) holds a static_fit across kills/flaps/respawns —
    every consumer ANDs node_condition_fit against its FRESH node arrays
    instead."""
    n = nodes["alloc"].shape[0]
    out = (
        selector_fit(pods, nodes["labels"])
        & taints_fit(pods["intolerated"], nodes["taints_sched"])
        & host_fit(pods["has_host"], pods["host_required"], n)
        & volume_zone_fit(pods["vz_req"], pods["vz_err"], nodes["labels"],
                          nodes["has_zone"])
        & pv_affinity_fit(pods, nodes["labels"])
        & ~pods["impossible"][:, None]  # ext resource no node advertises /
        # unresolvable PVC (predicate error in the reference)
    )
    if "policy_fit" in pods:
        # Policy-configured NodeLabelPresence / ServiceAffinity masks,
        # precomputed host-side (ops/policy_algos.py)
        out = out & pods["policy_fit"]
    if "host_fit" in pods:
        # host-check static column (ISSUE 18): the exact label-pure
        # host predicate for classes whose selector/zone/PV shape
        # overflowed the fused encoding, precomputed host-side
        # (PodBatch.host_static_fit) so those classes ride the wave
        # instead of flushing. ANDing exact with the over-approximate
        # terms above keeps the composite exact.
        out = out & pods["host_fit"]
    return out


def fits(pods: Arrays, nodes: Arrays) -> jnp.ndarray:
    """The full predicate chain against a frozen snapshot -> bool [P,N].

    Equivalent of running podFitsOnNode (generic_scheduler.go:234) for every
    (pending pod, node) pair with GeneralPredicates + taints + conditions —
    i.e. the default provider's registered predicates that are modeled so far
    (volume predicates pending; see SURVEY.md §7 step 7).
    """
    from kubernetes_tpu.ops.pallas_kernels import resources_fit_fast
    return (
        static_fits(pods, nodes)
        & node_condition_fit(pods, nodes)
        & resources_fit_fast(pods["req"], pods["zero_req"], nodes["alloc"],
                             nodes["requested"])
        & pod_count_fit(nodes["pod_count"], nodes["allowed_pods"])[None, :]
        & ports_fit(pods["ports"], nodes["port_bitmap"])
        & no_disk_conflict(pods["vol_hard"], pods["vol_ro"],
                           nodes["vol_present"], nodes["vol_rw"])
        & max_pd_fit(pods["pd_req"], pods["pd_req_count"], nodes["pd_kind"],
                     nodes["pd_present"], nodes["pd_counts"], nodes["pd_max"])
    )


fits_jit = jax.jit(fits)
