"""Vectorized priority functions: the pod x node scorer as one fused kernel.

Replaces PrioritizeNodes (reference:
plugin/pkg/scheduler/core/generic_scheduler.go:285-414: 16-way parallel map +
per-priority reduce goroutines) with dense int32 [P, N] score matrices.

Integer semantics are preserved bit-for-bit where the reference uses integer
math (LeastRequested/MostRequested: int64 floor division -> int32 floor
division here, valid because snapshot units keep capacity*10 < 2^31), and
float where the reference uses float64 (BalancedResourceAllocation) — float32
on TPU; divergence is only possible when (1-|diff|)*10 lands within float32
epsilon of an integer, which the tests pin down.

Parity map (reference: plugin/pkg/scheduler/algorithm/priorities/):
  LeastRequestedPriorityMap        least_requested.go:33  -> least_requested
  BalancedResourceAllocationMap    balanced_resource_allocation.go:105 -> balanced_allocation
  MostRequestedPriorityMap         most_requested.go:33   -> most_requested
  TaintTolerationPriorityMap       taint_toleration.go:56 -> taint_toleration (+reduce)
  EqualPriorityMap                 core/generic_scheduler.go:416 -> equal
  (NodeAffinity/SelectorSpread/InterPodAffinity/ImageLocality/
   NodePreferAvoidPods: later milestones — SURVEY.md §7 step 7)

Scores are 0..MAX_PRIORITY(=10) ints per function; the combined score is the
weight-multiplied sum (generic_scheduler.go:341-349,368-375).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.api.types import MAX_PRIORITY

Arrays = Dict[str, jnp.ndarray]


def _unused_score(total: jnp.ndarray, cap: jnp.ndarray) -> jnp.ndarray:
    """((cap - total) * 10) / cap with int floor division; 0 when cap==0 or
    total>cap (least_requested.go:47-57 calculateUnusedScore)."""
    safe_cap = jnp.maximum(cap, 1)
    score = ((cap - total) * MAX_PRIORITY) // safe_cap
    return jnp.where((cap == 0) | (total > cap), 0, score)


def _used_score(total: jnp.ndarray, cap: jnp.ndarray) -> jnp.ndarray:
    """(total * 10) / cap; 0 when cap==0 or total>cap
    (most_requested.go:52-60 calculateUsedScore)."""
    safe_cap = jnp.maximum(cap, 1)
    score = (total * MAX_PRIORITY) // safe_cap
    return jnp.where((cap == 0) | (total > cap), 0, score)


def _totals(pod_nonzero: jnp.ndarray, node_nonzero: jnp.ndarray
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """total = pod nonzero request + node nonzero-requested sum
    (least_requested.go:67-70). [P,2],[N,2] -> ([P,N] cpu, [P,N] mem)."""
    tot = pod_nonzero[:, None, :] + node_nonzero[None, :, :]
    return tot[..., 0], tot[..., 1]


def least_requested(pod_nonzero: jnp.ndarray, node_nonzero: jnp.ndarray,
                    alloc: jnp.ndarray) -> jnp.ndarray:
    """score = (cpu_score + mem_score) / 2, each (cap-req)*10/cap
    (least_requested.go:33-90). alloc [N,R] -> [P,N] int32."""
    tot_cpu, tot_mem = _totals(pod_nonzero, node_nonzero)
    cpu = _unused_score(tot_cpu, alloc[None, :, 0])
    mem = _unused_score(tot_mem, alloc[None, :, 1])
    return (cpu + mem) // 2


def most_requested(pod_nonzero: jnp.ndarray, node_nonzero: jnp.ndarray,
                   alloc: jnp.ndarray) -> jnp.ndarray:
    """(most_requested.go:33-90). Used by the ClusterAutoscalerProvider
    (algorithmprovider/defaults/defaults.go:65)."""
    tot_cpu, tot_mem = _totals(pod_nonzero, node_nonzero)
    cpu = _used_score(tot_cpu, alloc[None, :, 0])
    mem = _used_score(tot_mem, alloc[None, :, 1])
    return (cpu + mem) // 2


def _balanced_score(tot_cpu: jnp.ndarray, tot_mem: jnp.ndarray,
                    cap_cpu: jnp.ndarray, cap_mem: jnp.ndarray) -> jnp.ndarray:
    """10 - |cpuFraction - memFraction|*10, truncated; 0 when either
    fraction >= 1; fraction(cap==0) := 1
    (balanced_resource_allocation.go:51-92,105). Shape-generic — shared by
    the [P,N] kernel below and the wave engine's per-row acceptance window
    so the two stay bit-identical."""
    f32 = jnp.float32
    frac_c = jnp.where(cap_cpu == 0, f32(1.0),
                       tot_cpu.astype(f32) / jnp.maximum(cap_cpu, 1).astype(f32))
    frac_m = jnp.where(cap_mem == 0, f32(1.0),
                       tot_mem.astype(f32) / jnp.maximum(cap_mem, 1).astype(f32))
    diff = jnp.abs(frac_c - frac_m)
    score = ((f32(1.0) - diff) * MAX_PRIORITY).astype(jnp.int32)  # trunc toward 0
    return jnp.where((frac_c >= 1.0) | (frac_m >= 1.0), 0, score)


def balanced_allocation(pod_nonzero: jnp.ndarray, node_nonzero: jnp.ndarray,
                        alloc: jnp.ndarray) -> jnp.ndarray:
    """BalancedResourceAllocationMap [P,N] (balanced_resource_allocation.go)."""
    tot_cpu, tot_mem = _totals(pod_nonzero, node_nonzero)
    return _balanced_score(tot_cpu, tot_mem, alloc[None, :, 0],
                           alloc[None, :, 1])


def taint_toleration(intolerated_pref: jnp.ndarray, taints_pref: jnp.ndarray,
                     fits: jnp.ndarray = None) -> jnp.ndarray:
    """CountIntolerableTaintsPreferNoSchedule + normalizing reduce
    (taint_toleration.go:30-76): map = count of PreferNoSchedule taints the
    pod does NOT tolerate; reduce = 10 * (1 - count/maxCount), and 10 when
    maxCount==0. Integer result via float64-equivalent math: the reference
    computes float64(10)*(1-c/max) then int() truncation — replicated with
    exact integer arithmetic: floor(10*(max-c)/max) only when 10*(max-c) is
    divisible... the reference truncates the float; we use integer floor which
    matches truncation for non-negative values up to float32 rounding."""
    cnt = jnp.einsum("pt,nt->pn", intolerated_pref,
                     taints_pref.astype(jnp.int8),
                     preferred_element_type=jnp.int32)
    # the normalizing max runs over the pod's FILTERED node set only —
    # PrioritizeNodes receives filteredNodes (generic_scheduler.go:121,285)
    masked = cnt if fits is None else jnp.where(fits, cnt, 0)
    max_cnt = masked.max(axis=1, keepdims=True)
    safe = jnp.maximum(max_cnt, 1)
    score = (MAX_PRIORITY * (max_cnt - cnt)) // safe
    return jnp.where(max_cnt == 0, MAX_PRIORITY, score)


def equal(p: int, n: int) -> jnp.ndarray:
    """EqualPriorityMap (generic_scheduler.go:416-424): score 1 everywhere."""
    return jnp.ones((p, n), dtype=jnp.int32)


def node_affinity_counts(pods: Arrays, labels: jnp.ndarray) -> jnp.ndarray:
    """NodeAffinityPriority map phase (node_affinity.go:36-77): per-node sum
    of weights of matching preferred terms -> int32 [P,N]. Same compiled-
    selector matmul structure as predicates.selector_fit; empty terms match
    every node."""
    lab = labels.astype(jnp.int8)
    all_cnt = jnp.einsum("ptl,nl->ptn", pods["pref_req_all"], lab,
                         preferred_element_type=jnp.int32)
    need = pods["pref_req_all"].astype(jnp.int32).sum(axis=-1)
    all_ok = all_cnt == need[:, :, None]
    forbid_cnt = jnp.einsum("ptl,nl->ptn", pods["pref_forbid"], lab,
                            preferred_element_type=jnp.int32)
    any_cnt = jnp.einsum("ptal,nl->ptan", pods["pref_req_any"], lab,
                         preferred_element_type=jnp.int32)
    any_ok = ((any_cnt > 0) | ~pods["pref_any_used"][:, :, :, None]).all(axis=2)
    match = (all_ok & (forbid_cnt == 0) & any_ok
             & ~pods["pref_unsat"][:, :, None]) | pods["pref_empty"][:, :, None]
    match = match & pods["pref_valid"][:, :, None]
    return (match.astype(jnp.int32) * pods["pref_weight"][:, :, None]).sum(axis=1)


def node_affinity(pods: Arrays, labels: jnp.ndarray,
                  fits: jnp.ndarray = None) -> jnp.ndarray:
    """Map + normalizing reduce (node_affinity.go:79-100):
    int(10 * count / maxCount) over the filtered set; all-zero -> 0."""
    cnt = node_affinity_counts(pods, labels)
    masked = cnt if fits is None else jnp.where(fits, cnt, 0)
    mx = masked.max(axis=1, keepdims=True)
    return jnp.where(mx > 0, (MAX_PRIORITY * cnt) // jnp.maximum(mx, 1), 0)


def prefer_avoid(avoid_idx: jnp.ndarray, node_avoid: jnp.ndarray) -> jnp.ndarray:
    """NodePreferAvoidPodsPriority (node_prefer_avoid_pods.go:29-60):
    0 when the node's preferAvoidPods annotation names the pod's RC/RS
    controller, else MaxPriority. avoid_idx [P] (-1 = not RC/RS-owned),
    node_avoid int8 [N,U] -> [P,N]."""
    safe = jnp.maximum(avoid_idx, 0)
    hit = jnp.take(node_avoid, safe, axis=1).T.astype(bool)  # [P,N]
    avoided = hit & (avoid_idx >= 0)[:, None]
    return jnp.where(avoided, 0, MAX_PRIORITY).astype(jnp.int32)


# image_locality.go:30-34 thresholds, quantized to KiB like the snapshot
MIN_IMG_KIB = (23 * 1024 * 1024) >> 10
MAX_IMG_KIB = (1000 * 1024 * 1024) >> 10


def image_locality(img_count: jnp.ndarray, image_sizes: jnp.ndarray
                   ) -> jnp.ndarray:
    """ImageLocalityPriorityMap (image_locality.go:32-66): bucket the summed
    size of the pod's images already present on the node into 0..10.
    img_count int32 [P,I] (containers per image), image_sizes int32 [N,I] KiB."""
    total = jnp.einsum("pi,ni->pn", img_count, image_sizes,
                       preferred_element_type=jnp.int32)
    mid = (MAX_PRIORITY * (total - MIN_IMG_KIB)) // (MAX_IMG_KIB - MIN_IMG_KIB) + 1
    return jnp.where(total < MIN_IMG_KIB, 0,
                     jnp.where(total >= MAX_IMG_KIB, MAX_PRIORITY, mid)
                     ).astype(jnp.int32)


# registry: name -> (fn(pods, nodes, fits) -> [P,N] int32); `fits` is the
# pod's filtered-node mask, consumed only by reduce-normalized priorities
def _lr(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return least_requested(pods["nonzero"], nodes["nonzero"], nodes["alloc"])


def _mr(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return most_requested(pods["nonzero"], nodes["nonzero"], nodes["alloc"])


def _ba(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return balanced_allocation(pods["nonzero"], nodes["nonzero"], nodes["alloc"])


def _tt(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return taint_toleration(pods["intolerated_pref"], nodes["taints_pref"], fits)


def _eq(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return equal(pods["nonzero"].shape[0], nodes["alloc"].shape[0])


def _na(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return node_affinity(pods, nodes["labels"], fits)


def _avoid(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return prefer_avoid(pods["avoid_idx"], nodes["avoid"])


def _img(pods: Arrays, nodes: Arrays, fits) -> jnp.ndarray:
    return image_locality(pods["img_count"], nodes["image_sizes"])


PRIORITY_REGISTRY = {
    "LeastRequestedPriority": _lr,
    "MostRequestedPriority": _mr,
    "BalancedResourceAllocation": _ba,
    "TaintTolerationPriority": _tt,
    "NodeAffinityPriority": _na,
    "NodePreferAvoidPodsPriority": _avoid,
    "ImageLocalityPriority": _img,
    "EqualPriority": _eq,
}

# the two cluster-topology priorities live in ops/affinity.py (they need
# cluster-wide pod/workload state, not just pod x node arrays) — engines
# evaluate them from AffinityData; this module's pod x node score() cannot,
# and raises rather than contributing a silent zero
AFFINITY_PRIORITIES = frozenset({
    "SelectorSpreadPriority", "InterPodAffinityPriority",
})


def score(pods: Arrays, nodes: Arrays,
          priorities: Tuple[Tuple[str, int], ...],
          fits: jnp.ndarray = None) -> jnp.ndarray:
    """Weighted sum over enabled priorities -> int32 [P,N]
    (generic_scheduler.go:368-375 'result[i].Score += score * weight').
    Unknown or out-of-scope priority names raise (VERDICT r1 weak #5:
    silent zeroes made the kernel path quietly weaker than configured)."""
    p = pods["nonzero"].shape[0]
    n = nodes["alloc"].shape[0]
    total = jnp.zeros((p, n), dtype=jnp.int32)
    for name, weight in priorities:
        if name in AFFINITY_PRIORITIES:
            raise KeyError(
                f"{name} needs cluster topology state — evaluate through "
                "the engines (engine/batch.py aff=...) or ops.affinity, "
                "not the pod x node score()")
        total = total + PRIORITY_REGISTRY[name](pods, nodes, fits) * weight
    return total


DEFAULT_PRIORITIES: Tuple[Tuple[str, int], ...] = (
    # defaultPriorities, reference-exact — every weight-1 member of
    # algorithmprovider/defaults/defaults.go:191 plus NodePreferAvoidPods
    # at weight 10000 (defaults.go:205)
    ("SelectorSpreadPriority", 1),
    ("InterPodAffinityPriority", 1),
    ("LeastRequestedPriority", 1),
    ("BalancedResourceAllocation", 1),
    ("NodePreferAvoidPodsPriority", 10000),
    ("NodeAffinityPriority", 1),
    ("TaintTolerationPriority", 1),
)
