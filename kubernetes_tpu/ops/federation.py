"""Fused [C, M] cross-cell routing scores for the federation tier (ISSUE 20).

The per-cell engine's dense-eval idiom, one level up: the front-door
router holds M cell-aggregate columns (federation/aggregate.py) and C
pending pods/gangs, and scores every (candidate, cell) pair in ONE fused
dispatch instead of M wire round-trips per pod. The tensor is tiny —
M is cells (single digits), C is a routing batch — so the win is not
FLOPs, it is the same property the wave path buys: one compiled program,
one host fetch, argmax tie-breaks deterministic by first occurrence.

Scoring mirrors the fast lane's least-loaded rule at cell granularity:
fit = cell ready (not browned out) AND affinity-domain present AND the
candidate's summed (cpu, mem) demand fits the cell's headroom; score =
worst-dimension fractional headroom AFTER placement minus a band-pressure
penalty (pending backlog normalized by ready nodes — Borg's "spare
capacity" spillover signal, PAPERS.md §Borg). Gangs enter as ONE row with
summed demand: their atomicity point never crosses a cell boundary
(§Tiresias), the per-cell quorum fence does the rest.

``route_scores_host`` is the numpy twin (same math, same tie-break) used
for tiny batches where a device dispatch is pure overhead; the A/B test
pins the twins equal so the routing choice is latency policy, never a
semantics fork. The C axis is padded to the r10 bucket ladder by the
router (ops.predicates.bucket): a padded row has zero demand and fits
everywhere, and the router never reads its verdict.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# score floor for unfit (candidate, cell) pairs: real scores are
# fractional headroom in [0, 1] minus a bounded pressure term, so any
# fit cell beats _UNFIT at argmax
_UNFIT = -1e9

# band-pressure weight: one unit of pending-per-ready-node costs the
# same as the full headroom range, so a drowning cell loses to any
# comparably-free quiet one but still wins over cells that don't fit
PRESSURE_W = 1.0


def _route_scores(dem_cpu, dem_mem, cpu_free, mem_free, cpu_cap, mem_cap,
                  pressure, ready, dom_ok):
    """Score C candidates against M cells -> int32 [2, C]: row 0 the
    chosen cell index per candidate (argmax, first occurrence — the
    deterministic tie-break), row 1 the count of cells that fit (row 0
    is meaningful only where row 1 > 0). Stacked so the router's host
    fetch is ONE blessed transfer, not one per output.

    dem_cpu/dem_mem int32 [C] summed candidate demand (millicores, MiB);
    cpu_free/mem_free int32 [M] cell headroom; cpu_cap/mem_cap int32 [M]
    ready-node capacity; pressure float32 [M] pending per ready node;
    ready bool [M] cell routable; dom_ok bool [C, M] affinity-domain
    presence.
    """
    spare_c = (cpu_free[None, :] - dem_cpu[:, None]).astype(jnp.float32)
    spare_m = (mem_free[None, :] - dem_mem[:, None]).astype(jnp.float32)
    fit = (ready[None, :] & dom_ok
           & (spare_c >= 0) & (spare_m >= 0))          # [C, M]
    cap_c = jnp.maximum(cpu_cap, 1).astype(jnp.float32)
    cap_m = jnp.maximum(mem_cap, 1).astype(jnp.float32)
    head = jnp.minimum(spare_c / cap_c[None, :], spare_m / cap_m[None, :])
    score = jnp.where(fit, head - PRESSURE_W * pressure[None, :], _UNFIT)
    choice = jnp.argmax(score, axis=-1).astype(jnp.int32)
    return jnp.stack([choice, fit.astype(jnp.int32).sum(axis=-1)])


route_scores = jax.jit(_route_scores)


def route_scores_host(dem_cpu, dem_mem, cpu_free, mem_free, cpu_cap,
                      mem_cap, pressure, ready, dom_ok) -> np.ndarray:
    """Numpy twin of ``route_scores`` — identical verdicts by test, used
    when the routing batch is too small to amortize a dispatch."""
    dem_cpu = np.asarray(dem_cpu)
    dem_mem = np.asarray(dem_mem)
    spare_c = (cpu_free[None, :] - dem_cpu[:, None]).astype(np.float32)
    spare_m = (mem_free[None, :] - dem_mem[:, None]).astype(np.float32)
    fit = (ready[None, :] & dom_ok
           & (spare_c >= 0) & (spare_m >= 0))
    cap_c = np.maximum(cpu_cap, 1).astype(np.float32)
    cap_m = np.maximum(mem_cap, 1).astype(np.float32)
    head = np.minimum(spare_c / cap_c[None, :], spare_m / cap_m[None, :])
    score = np.where(fit, head - PRESSURE_W * pressure[None, :],
                     np.float32(_UNFIT))
    choice = np.argmax(score, axis=-1).astype(np.int32)
    return np.stack([choice, fit.astype(np.int32).sum(axis=-1)])


__all__ = ["PRESSURE_W", "route_scores", "route_scores_host"]
