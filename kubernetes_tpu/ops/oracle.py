"""Pure-Python object-level oracle: exact reimplementation of the reference's
predicate/priority semantics over api.types objects.

Three jobs:
 1. Golden reference for kernel tests (tests/ compare oracle vs TPU kernels on
    randomized + table-driven fixtures, the strategy of the reference's
    predicates_test.go / priorities_test.go table tests).
 2. Exact host-side verification of device-chosen candidates for pods flagged
    needs_host_check (features the kernels over-approximate).
 3. Readable spec of the semantics, with reference file:line citations.

Python ints are arbitrary precision, so the int64 arithmetic of the Go code
(floor division in calculateUnusedScore etc.) is reproduced exactly.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    MAX_PRIORITY,
    ConditionStatus,
    Node,
    Pod,
    TaintEffect,
)
from kubernetes_tpu.state.node_info import NodeInfo

# ---------------------------------------------------------------------------
# predicates — each returns (fit, reasons)
# ---------------------------------------------------------------------------


def pod_fits_resources(pod: Pod, info: NodeInfo) -> Tuple[bool, List[str]]:
    """reference: predicates.go:556-624 PodFitsResources."""
    node = info.node
    if node is None:
        return False, ["NodeNotFound"]
    fails: List[str] = []
    if len(info.pods) + 1 > node.allowed_pod_number:
        fails.append("InsufficientPods")
    req = pod.resource_request()
    if (req.milli_cpu == 0 and req.memory == 0 and req.nvidia_gpu == 0
            and req.storage_overlay == 0 and req.storage_scratch == 0
            and not req.extended):
        return not fails, fails
    alloc = node.allocatable
    used = info.requested
    if alloc.milli_cpu < req.milli_cpu + used.milli_cpu:
        fails.append("InsufficientCPU")
    if alloc.memory < req.memory + used.memory:
        fails.append("InsufficientMemory")
    if alloc.nvidia_gpu < req.nvidia_gpu + used.nvidia_gpu:
        fails.append("InsufficientGPU")
    scratch_req = req.storage_scratch
    if alloc.storage_overlay == 0:
        scratch_req += req.storage_overlay
        node_scratch = used.storage_overlay + used.storage_scratch
        if alloc.storage_scratch < scratch_req + node_scratch:
            fails.append("InsufficientScratch")
    elif alloc.storage_scratch < scratch_req + used.storage_scratch:
        fails.append("InsufficientScratch")
    if alloc.storage_overlay > 0 and \
            alloc.storage_overlay < req.storage_overlay + used.storage_overlay:
        fails.append("InsufficientOverlay")
    for name, q in req.extended.items():
        if alloc.extended.get(name, 0) < q + used.extended.get(name, 0):
            fails.append(f"Insufficient{name}")
    return not fails, fails


def pod_matches_node_selector(pod: Pod, node: Node) -> bool:
    """reference: predicates.go:640-685 podMatchesNodeLabels."""
    if pod.node_selector:
        for k, v in pod.node_selector.items():
            if node.labels.get(k) != v:
                return False
    na = pod.affinity.node_affinity if pod.affinity else None
    if na is not None and na.required_terms is not None:
        # ORed terms; empty list matches nothing
        if not any(t.matches_labels(node.labels) for t in na.required_terms):
            return False
    return True


def pod_fits_host(pod: Pod, node: Node) -> bool:
    """reference: predicates.go:698-712 PodFitsHost."""
    return not pod.node_name or pod.node_name == node.name


def pod_fits_host_ports(pod: Pod, info: NodeInfo) -> bool:
    """reference: predicates.go:859-878 PodFitsHostPorts."""
    want = pod.used_ports()
    return not any(p in info.used_ports for p in want if p != 0)


def pod_tolerates_node_taints(pod: Pod, node: Node) -> bool:
    """reference: predicates.go:1241-1265; only NoSchedule|NoExecute filter."""
    for taint in node.taints:
        eff = TaintEffect(taint.effect)
        if eff not in (TaintEffect.NO_SCHEDULE, TaintEffect.NO_EXECUTE):
            continue
        if not any(tol.tolerates(taint) for tol in pod.tolerations):
            return False
    return True


def check_node_condition(node: Node) -> bool:
    """reference: predicates.go:1306-1337 CheckNodeConditionPredicate."""
    return node.is_ready()


def check_memory_pressure(pod: Pod, node: Node) -> bool:
    """reference: predicates.go:1274-1294 (best-effort pods only)."""
    if not pod.is_best_effort():
        return True
    return node.condition("MemoryPressure") != ConditionStatus.TRUE


def check_disk_pressure(node: Node) -> bool:
    """reference: predicates.go:1296-1304."""
    return node.condition("DiskPressure") != ConditionStatus.TRUE


def pod_fits(pod: Pod, info: NodeInfo, ctx=None, affinity_meta=None) -> bool:
    """Default-provider predicate chain (defaults.go:118): volume predicates
    + GeneralPredicates + taints + conditions + (with a SchedulingContext)
    MatchInterPodAffinity."""
    node = info.node
    if node is None:
        return False
    res_ok, _ = pod_fits_resources(pod, info)
    ok = (res_ok
          and pod_fits_host(pod, node)
          and pod_fits_host_ports(pod, info)
          and pod_matches_node_selector(pod, node)
          and pod_tolerates_node_taints(pod, node)
          and check_node_condition(node)
          and check_memory_pressure(pod, node)
          and check_disk_pressure(node))
    if ok and pod.volumes:
        from kubernetes_tpu.ops.oracle_volumes import volume_predicates_fit
        ok = volume_predicates_fit(
            pod, info, getattr(ctx, "volume_ctx", None))
    if ok and ctx is not None:
        from kubernetes_tpu.ops.oracle_ext import inter_pod_affinity_fits
        ok = inter_pod_affinity_fits(pod, node, ctx, affinity_meta)
    if ok and ctx is not None \
            and getattr(ctx, "policy_algos", None) is not None \
            and ctx.policy_algos.active:
        # Policy-configured ServiceAffinity / NodeLabelPresence
        ok = ctx.policy_algos.oracle_fit(pod, node, ctx)
    return ok


# ---------------------------------------------------------------------------
# priorities
# ---------------------------------------------------------------------------


def _unused_score(requested: int, capacity: int) -> int:
    """reference: least_requested.go:47-57."""
    if capacity == 0 or requested > capacity:
        return 0
    return ((capacity - requested) * MAX_PRIORITY) // capacity


def _used_score(requested: int, capacity: int) -> int:
    """reference: most_requested.go:52-60."""
    if capacity == 0 or requested > capacity:
        return 0
    return (requested * MAX_PRIORITY) // capacity


def _nonzero_totals(pod: Pod, info: NodeInfo) -> Tuple[int, int]:
    cpu, mem = pod.nonzero_request()
    return cpu + info.nonzero_cpu, mem + info.nonzero_mem


def least_requested_score(pod: Pod, info: NodeInfo) -> int:
    """reference: least_requested.go:33-90."""
    tot_cpu, tot_mem = _nonzero_totals(pod, info)
    alloc = info.allocatable()
    return (_unused_score(tot_cpu, alloc.milli_cpu)
            + _unused_score(tot_mem, alloc.memory)) // 2


def most_requested_score(pod: Pod, info: NodeInfo) -> int:
    """reference: most_requested.go:33-90."""
    tot_cpu, tot_mem = _nonzero_totals(pod, info)
    alloc = info.allocatable()
    return (_used_score(tot_cpu, alloc.milli_cpu)
            + _used_score(tot_mem, alloc.memory)) // 2


def balanced_allocation_score(pod: Pod, info: NodeInfo) -> int:
    """reference: balanced_resource_allocation.go:51-104."""
    tot_cpu, tot_mem = _nonzero_totals(pod, info)
    alloc = info.allocatable()
    frac_c = tot_cpu / alloc.milli_cpu if alloc.milli_cpu else 1.0
    frac_m = tot_mem / alloc.memory if alloc.memory else 1.0
    if frac_c >= 1 or frac_m >= 1:
        return 0
    return int((1 - abs(frac_c - frac_m)) * MAX_PRIORITY)


def taint_toleration_scores(pod: Pod, infos: Sequence[NodeInfo]) -> List[int]:
    """reference: taint_toleration.go:30-76 (map + normalizing reduce)."""
    counts = []
    for info in infos:
        node = info.node
        c = 0
        if node is not None:
            for taint in node.taints:
                if TaintEffect(taint.effect) != TaintEffect.PREFER_NO_SCHEDULE:
                    continue
                if not any(t.tolerates(taint) for t in pod.tolerations):
                    c += 1
        counts.append(c)
    max_c = max(counts) if counts else 0
    if max_c == 0:
        return [MAX_PRIORITY for _ in counts]
    return [int(MAX_PRIORITY * (1 - c / max_c)) for c in counts]


DEFAULT_PRIORITY_WEIGHTS: Tuple[Tuple[str, int], ...] = (
    ("LeastRequestedPriority", 1),
    ("BalancedResourceAllocation", 1),
    ("TaintTolerationPriority", 1),
)


def prioritize(pod: Pod, infos: Sequence[NodeInfo],
               priorities: Tuple[Tuple[str, int], ...] = DEFAULT_PRIORITY_WEIGHTS,
               ctx=None) -> List[int]:
    """Weighted sum across enabled priorities (generic_scheduler.go:368-375).
    Context-dependent priorities (spreading, inter-pod affinity) require a
    SchedulingContext and score 0 without one, mirroring their zero
    contribution when their listers are absent."""
    from kubernetes_tpu.ops import oracle_ext
    n = len(infos)
    totals = [0] * n
    for name, weight in priorities:
        if name == "LeastRequestedPriority":
            per = [least_requested_score(pod, i) for i in infos]
        elif name == "MostRequestedPriority":
            per = [most_requested_score(pod, i) for i in infos]
        elif name == "BalancedResourceAllocation":
            per = [balanced_allocation_score(pod, i) for i in infos]
        elif name == "TaintTolerationPriority":
            per = taint_toleration_scores(pod, infos)
        elif name == "NodeAffinityPriority":
            per = oracle_ext.node_affinity_scores(pod, infos)
        elif name == "NodePreferAvoidPodsPriority":
            per = oracle_ext.prefer_avoid_scores(pod, infos)
        elif name == "ImageLocalityPriority":
            per = oracle_ext.image_locality_scores(pod, infos)
        elif name == "SelectorSpreadPriority":
            per = (oracle_ext.selector_spread_scores(pod, infos, ctx)
                   if ctx is not None else [0] * n)
        elif name == "InterPodAffinityPriority":
            per = (oracle_ext.interpod_affinity_scores(pod, infos, ctx)
                   if ctx is not None else [0] * n)
        elif name == "EqualPriority":
            per = [1] * n
        else:
            raise KeyError(name)
        for i in range(n):
            totals[i] += per[i] * weight
    if ctx is not None and getattr(ctx, "policy_algos", None) is not None \
            and ctx.policy_algos.active:
        # Policy-configured NodeLabel / ServiceAntiAffinity (weights folded)
        per = ctx.policy_algos.oracle_scores(pod, infos, ctx)
        for i in range(n):
            totals[i] += per[i]
    return totals


# ---------------------------------------------------------------------------
# schedule-one (oracle for the engine's sequential semantics)
# ---------------------------------------------------------------------------


class RoundRobin:
    """selectHost's lastNodeIndex counter (generic_scheduler.go:144-160).
    Ties among max-score nodes are broken round-robin; our canonical tie
    order is ascending node index in snapshot order (the reference's order
    after its unstable sort is implementation-defined)."""

    def __init__(self):
        self.counter = 0

    def pick(self, tie_count: int) -> int:
        ix = self.counter % tie_count
        self.counter += 1
        return ix


def schedule_one(pod: Pod, names: List[str], infos: Dict[str, NodeInfo],
                 rr: RoundRobin,
                 priorities: Tuple[Tuple[str, int], ...] = DEFAULT_PRIORITY_WEIGHTS,
                 ctx=None) -> Optional[str]:
    """genericScheduler.Schedule for one pod (generic_scheduler.go:88-142):
    filter -> prioritize -> selectHost. Returns node name or None."""
    meta = None
    if ctx is not None:
        from kubernetes_tpu.ops.oracle_ext import AffinityMeta
        meta = AffinityMeta(pod, ctx)  # once per pod, not per node
    fit_names = [nm for nm in names if pod_fits(pod, infos[nm], ctx, meta)]
    if not fit_names:
        return None
    if len(fit_names) == 1:
        return fit_names[0]
    fit_infos = [infos[nm] for nm in fit_names]
    scores = prioritize(pod, fit_infos, priorities, ctx)
    best = max(scores)
    ties = [nm for nm, s in zip(fit_names, scores) if s == best]
    return ties[rr.pick(len(ties))]
