"""Device-side victim selection for wave-path preemption (ISSUE 14).

The classic preemption pre-filter (engine/preemption.py candidate_mask /
tight_bounds) builds O(total pods) host arrays per round — exactly the
serial residue the wave path exists to kill. This module is its tensor
form: the snapshot maintains per-node PRIORITY-BAND aggregates
(band_cpu / band_mem / band_count, [N, B] with B a small interned vocab
of distinct pod priorities — Borg's bands, PAPERS.md §Borg), and ONE
fused dispatch answers, for every pending preemptor class at once:

  - candidate[c, n]: could evicting some set of strictly-lower-priority
    pods on node n free enough room for class c? (the masked score over
    the same [C, N] shape every other wave kernel speaks)
  - bound[c, n]: the minimal highest-victim-priority that frees enough —
    the exact band form of tight_bounds (evicting whole bands ascending
    by priority stops at the same band as the per-pod prefix, since the
    per-pod prefix that crossed into band v already contains every pod
    below v). Used to rank candidates when the exact host verification
    must be truncated.

Over-approximation contract (the snapshot-kernel pattern, SURVEY §7(e)):
the mask may only ever INCLUDE too much, never exclude a node the exact
oracle would accept — memory is quantized (alloc floors, requested and
band sums ceil), so the comparison carries a +2-quantum slack; assumed
pods ride the bands like bound ones (the host pass filters victims to
store-confirmed pods). False positives cost one exact `_select_victims`
verification each and return None there; a false negative would change
a scheduling outcome, which is why the fuzz A/B in
tests/test_preempt_wave.py pins wave plans == classic plans.

Class-axis shapes are padded to the bucket ladder by the caller
(engine.preempt_scan) — a ragged per-round preemptor count sliced into
this jit would be the GL003 recompile storm.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# padding rows use this priority: no band can sit strictly below it, so
# a padding class has no candidates and commits nothing
PAD_PRIO = -(2 ** 31)
# unused band slots carry this priority: never strictly below any real
# preemptor, so they can't widen a threshold (their sums are zero anyway)
UNUSED_BAND_PRIO = 2 ** 31 - 1
INFEASIBLE = 2 ** 31 - 1
# quantization slack for the memory comparison: alloc floors, requested
# ceils, band sums ceil — raw-feasible can lose at most 2 quanta here
MEM_SLACK = 2


def _victim_scan(need_cpu, need_mem, prio, spare_cpu, spare_mem,
                 pod_count, allowed, band_cpu, band_mem, band_count,
                 band_prio):
    """One fused [C, N] victim pre-filter.

    need_cpu/need_mem [C] int32 (mem floor-quantized), prio [C] int32;
    spare_cpu/spare_mem [N] int32 (alloc - requested, snapshot columns);
    pod_count/allowed [N] int32; band_* [N, B] int32 (mem ceil-quantized);
    band_prio [B] int32. Returns (candidate [C, N] bool, bound [C, N]
    int32 with INFEASIBLE where no threshold works)."""
    # prefix sums over priority thresholds: cum[n, t] = total over bands
    # whose priority <= band_prio[t] — the "evict every band up to t" form
    le = (band_prio[None, :] <= band_prio[:, None]).astype(jnp.int32)
    cum_cpu = jnp.matmul(band_cpu, le.T, preferred_element_type=jnp.int32)
    cum_mem = jnp.matmul(band_mem, le.T, preferred_element_type=jnp.int32)
    cum_cnt = jnp.matmul(band_count, le.T, preferred_element_type=jnp.int32)
    # thresholds a class may use: strictly below its own priority
    thr_ok = band_prio[None, :] < prio[:, None]               # [C, B]
    ok_cpu = (spare_cpu[None, :, None] + cum_cpu[None, :, :]
              >= need_cpu[:, None, None])                     # [C, N, B]
    ok_mem = (spare_mem[None, :, None] + cum_mem[None, :, :] + MEM_SLACK
              >= need_mem[:, None, None])
    ok_cnt = (pod_count[None, :, None] - cum_cnt[None, :, :] + 1
              <= allowed[None, :, None])
    has_victim = cum_cnt[None, :, :] > 0
    ok = (ok_cpu & ok_mem & ok_cnt & has_victim
          & thr_ok[:, None, :])                               # [C, N, B]
    candidate = ok.any(axis=-1)
    bound = jnp.min(jnp.where(ok, band_prio[None, None, :], INFEASIBLE),
                    axis=-1)
    return candidate, bound


victim_scan_jit = jax.jit(_victim_scan)


__all__ = ["INFEASIBLE", "MEM_SLACK", "PAD_PRIO", "UNUSED_BAND_PRIO",
           "victim_scan_jit"]
