"""Sampled power-of-k eval for the Sparrow fast lane (ISSUE 17).

The bulk wave path amortizes its cost over thousands of pods: encoding
build, vocab interning, a [P, N] fused eval. A latency-critical pod can't
wait for any of that. This kernel is the whole device story of the fast
lane: gather k sampled node rows out of the RESIDENT snapshot arrays
(the same buffers `_nodes_on_device` keeps between waves — nothing is
uploaded, nothing is re-encoded) and score the pod against exactly those
k rows. One dispatch, one [1, k] problem, compiled once per (k, N, R)
shape like the r10 ladder.

Admission keeps the kernel tiny by construction: the fast lane only takes
"simple" pods — no affinity, no selector, no tolerations, no host ports,
no volumes, no extended resources (engine/fastlane.py gates this). That
shrinks the predicate chain to resources + pod count + node conditions +
an any-taint check (a toleration-free pod fails on ANY NoSchedule taint,
so the intolerated×taint matmul degenerates to a row-sum), which is
EXACT for the admitted population — and the late-bind fence re-validates
the winner against live cache truth anyway, so a stale score costs a
resample, never a wrong bind.

``sample_eval_host`` is the same math in numpy over the HOST snapshot
arrays. The fast lane uses it whenever a bulk wave is in flight: the CPU
backend executes device programs FIFO per device, so even a microsecond
[1, k] dispatch would queue behind the wave and pay its full latency.
Device and host twins are A/B-pinned equal (tests/test_fastlane.py) so
the routing choice is pure latency policy, never a semantics fork.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.state.snapshot import (
    NUM_BASE_RESOURCES,
    R_CPU,
    R_MEM,
    R_OVERLAY,
    R_SCRATCH,
)

# node-side rows the sampled eval gathers — a strict subset of the
# engine's resident _nodes_on_device buffers (scheduler_engine.py), so
# the device path reads state that is already there
FAST_NODE_KEYS = ("alloc", "requested", "pod_count", "allowed_pods",
                  "schedulable", "valid", "mem_pressure", "disk_pressure",
                  "taints_sched")

# score floor for unfit rows: real scores are fractional headroom in
# [0, 1] (fit guarantees spare >= 0), so -1 can never win argmax
_UNFIT = -1.0


def _sample_eval(idx, req, zero_req, best_effort, nodes):
    """Score one pod against k sampled nodes -> int32 [3].

    idx int32 [k] node row indices; req int32 [R] quantized request row
    (resource_row semantics); zero_req / best_effort bool scalars; nodes
    = the FAST_NODE_KEYS dict of resident arrays. Returns
    [winner_local_index, fit_count, best_score * 1e6] — winner is
    meaningful only when fit_count > 0.
    """
    a = jnp.take(nodes["alloc"], idx, axis=0)          # [k,R]
    r = jnp.take(nodes["requested"], idx, axis=0)      # [k,R]
    total = req[None, :] + r
    ok = total <= a
    # cpu/mem/gpu + extended: plain elementwise (resources_fit layout)
    plain = jnp.concatenate(
        [ok[:, :R_SCRATCH], ok[:, NUM_BASE_RESOURCES:]], axis=-1
    ).all(axis=-1)
    # storage special-case (predicates.go:590-604): no overlay capacity
    # means overlay requests fall back onto scratch space
    alloc_s = a[:, R_SCRATCH]
    alloc_o = a[:, R_OVERLAY]
    pod_s = req[R_SCRATCH]
    pod_o = req[R_OVERLAY]
    node_s = r[:, R_SCRATCH]
    node_o = r[:, R_OVERLAY]
    no_overlay = alloc_o == 0
    scratch_ok = jnp.where(
        no_overlay,
        pod_s + pod_o + node_s + node_o <= alloc_s,
        pod_s + node_s <= alloc_s,
    )
    overlay_ok = no_overlay | (pod_o + node_o <= alloc_o)
    res_ok = (plain & scratch_ok & overlay_ok) | zero_req
    count_ok = (jnp.take(nodes["pod_count"], idx) + 1
                <= jnp.take(nodes["allowed_pods"], idx))
    cond_ok = jnp.take(nodes["schedulable"], idx) & jnp.take(nodes["valid"], idx)
    mem_ok = (~best_effort) | (~jnp.take(nodes["mem_pressure"], idx))
    disk_ok = ~jnp.take(nodes["disk_pressure"], idx)
    # toleration-free admission: ANY NoSchedule/NoExecute taint fails
    taint_free = jnp.take(nodes["taints_sched"], idx, axis=0).astype(
        jnp.int32).sum(axis=-1) == 0
    fit = res_ok & count_ok & cond_ok & mem_ok & disk_ok & taint_free
    # power-of-k choice: the least-loaded fit sample by worst-dimension
    # fractional headroom AFTER placement
    spare_c = (a[:, R_CPU] - total[:, R_CPU]).astype(jnp.float32)
    spare_m = (a[:, R_MEM] - total[:, R_MEM]).astype(jnp.float32)
    cap_c = jnp.maximum(a[:, R_CPU], 1).astype(jnp.float32)
    cap_m = jnp.maximum(a[:, R_MEM], 1).astype(jnp.float32)
    score = jnp.where(fit, jnp.minimum(spare_c / cap_c, spare_m / cap_m),
                      _UNFIT)
    win = jnp.argmax(score).astype(jnp.int32)
    return jnp.stack([win, fit.astype(jnp.int32).sum(),
                      (jnp.max(score) * 1e6).astype(jnp.int32)])


sample_eval = jax.jit(_sample_eval)


def sample_eval_host(idx, req, zero_req, best_effort, nodes) -> np.ndarray:
    """Numpy twin of ``sample_eval`` over the HOST snapshot arrays —
    bit-identical verdicts by test (same inputs -> same [3] output), used
    when a wave owns the device (FIFO execution would stall the fast pod
    behind it) and for resample retries."""
    idx = np.asarray(idx)
    a = nodes["alloc"][idx]
    r = nodes["requested"][idx]
    total = req[None, :] + r
    ok = total <= a
    plain = np.concatenate(
        [ok[:, :R_SCRATCH], ok[:, NUM_BASE_RESOURCES:]], axis=-1
    ).all(axis=-1)
    alloc_s = a[:, R_SCRATCH]
    alloc_o = a[:, R_OVERLAY]
    pod_s = req[R_SCRATCH]
    pod_o = req[R_OVERLAY]
    node_s = r[:, R_SCRATCH]
    node_o = r[:, R_OVERLAY]
    no_overlay = alloc_o == 0
    scratch_ok = np.where(
        no_overlay,
        pod_s + pod_o + node_s + node_o <= alloc_s,
        pod_s + node_s <= alloc_s,
    )
    overlay_ok = no_overlay | (pod_o + node_o <= alloc_o)
    res_ok = (plain & scratch_ok & overlay_ok) | zero_req
    count_ok = nodes["pod_count"][idx] + 1 <= nodes["allowed_pods"][idx]
    cond_ok = nodes["schedulable"][idx] & nodes["valid"][idx]
    mem_ok = (not best_effort) | (~nodes["mem_pressure"][idx])
    disk_ok = ~nodes["disk_pressure"][idx]
    taint_free = nodes["taints_sched"][idx].astype(
        np.int32).sum(axis=-1) == 0
    fit = res_ok & count_ok & cond_ok & mem_ok & disk_ok & taint_free
    spare_c = (a[:, R_CPU] - total[:, R_CPU]).astype(np.float32)
    spare_m = (a[:, R_MEM] - total[:, R_MEM]).astype(np.float32)
    cap_c = np.maximum(a[:, R_CPU], 1).astype(np.float32)
    cap_m = np.maximum(a[:, R_MEM], 1).astype(np.float32)
    score = np.where(fit, np.minimum(spare_c / cap_c, spare_m / cap_m),
                     np.float32(_UNFIT))
    win = np.int32(np.argmax(score))
    return np.array([win, fit.astype(np.int32).sum(),
                     np.int32(score.max() * 1e6)], dtype=np.int32)


__all__ = ["FAST_NODE_KEYS", "sample_eval", "sample_eval_host"]
