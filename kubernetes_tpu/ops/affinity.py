"""Inter-pod affinity + selector spreading as device kernels.

The two reference algorithms the round-1 build left on the host path
(VERDICT r1 #2/#3), re-designed as topology-incidence tensor ops:

  InterPodAffinityMatches   predicates.go:982-1146 (+ symmetry check
                            satisfiesExistingPodsAntiAffinity :1146,
                            self-match bootstrap :1210-1230)
  CalculateInterPodAffinityPriority  interpod_affinity.go:119-240
  CalculateSpreadPriority   selector_spreading.go:98-185 (2/3 zone blend)

Design (SURVEY.md §7 step 2): a topology DOMAIN is a (label-key, label-value)
pair — exactly the snapshot's label-pair vocabulary — so "node n is in
domain d" is the existing multi-hot labels[N, L] matrix, and "pod x shares a
topology with pod y under key k" becomes vector algebra over L:

  - static side (existing cluster pods): each pending CLASS gets per-term
    ALLOWED-domain vectors (required affinity), a FORBIDDEN-domain vector
    (own required anti-affinity + the symmetry check against existing pods'
    required anti-affinity terms), and a signed WEIGHT-per-domain vector
    (the priority). All are [·, L]; hitting them against labels[N, L] is one
    MXU matmul for the whole batch.

  - dynamic side (pods committed earlier in the SAME batch — the reference
    sees these because scheduleOne is sequential): the placement scan
    carries per-class domain occupancy commdom[C, L] (how many committed
    class-d pods sit in domain l) plus committed[C, N] / comm_cnt[C].
    Class-to-class term matching m_aff/m_anti/mp/mq is precomputed host-side
    (class keys cover namespace+labels, so class-level matching is exact),
    and each scan step contracts occupancy with the key-masked match
    matrices to reproduce, bit-for-bit, what the sequential reference would
    have seen.

Integer semantics: priority counts are integer sums (term weights are ints),
so the 0..10 normalization int(MAX*(c-min)/(max-min)) is computed in exact
integer floor division — equal to the reference's float64 truncation for
every reachable input (quotients are rationals with denominator >= 1e-9
away from integers unless exact). SelectorSpread's zone blend is defined
here as the EXACT rational floor((10(M-c)/M + 2*10(Mz-zc)/Mz) / 3) over
int32 — a deliberate, documented deviation from the reference's float64
arithmetic on its rounding crumbs (see spread_score), which frees the
whole engine from jax.enable_x64.

Slot limits: classes with more required/preferred terms than the static slot
shapes fall back to the exact host path (PodBatch.needs_host_check), like
every other over-approximation in the snapshot layer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.types import MAX_PRIORITY, Node, Pod
from kubernetes_tpu.ops.oracle_ext import (
    ZONE_LABEL,
    ZONE_REGION_LABEL,
    _own_terms,
    term_matches_pod,
)

Arrays = Dict[str, jnp.ndarray]

# static slot shapes (power-of-2-ish; overflow -> host path)
S_REQ_AFF = 4   # own required affinity terms
S_REQ_ANTI = 4  # own required anti-affinity terms
S_PREF = 8      # own preferred (anti-)affinity terms
S_OUT = 8       # outgoing terms of a class (hard-aff + preferred) that
                # score against OTHER pending classes once committed


def _pref_terms(pod: Pod) -> List[Tuple[int, object, bool]]:
    """(weight, term, is_anti) for the pod's preferred terms."""
    out = []
    if pod.affinity is not None:
        if pod.affinity.pod_affinity is not None:
            for w, t in pod.affinity.pod_affinity.preferred_terms:
                out.append((w, t, False))
        if pod.affinity.pod_anti_affinity is not None:
            for w, t in pod.affinity.pod_anti_affinity.preferred_terms:
                out.append((w, t, True))
    return out


def _out_terms(pod: Pod, hard_weight: int) -> List[Tuple[int, object]]:
    """Signed (weight, term) list of a pod's terms that contribute score to
    OTHER pods once this pod is placed (interpod_affinity.go:161-205: the
    existing pod's required affinity at hardPodAffinityWeight, preferred
    affinity at +w, preferred anti-affinity at -w)."""
    out = []
    if pod.affinity is not None:
        pa = pod.affinity.pod_affinity
        if pa is not None:
            if hard_weight > 0:
                for t in pa.required_terms:
                    out.append((hard_weight, t))
            for w, t in pa.preferred_terms:
                out.append((w, t))
        paa = pod.affinity.pod_anti_affinity
        if paa is not None:
            for w, t in paa.preferred_terms:
                out.append((-w, t))
    return out


def _has_affinity(pod: Pod) -> bool:
    return pod.has_pod_affinity()


def spec_overflow(pod: Pod, hard_weight: int) -> bool:
    """True iff this pod's term counts exceed the static slot shapes — the
    spec-only precondition of ``AffinityData.overflow`` (domain-independent:
    no cluster state consulted). Callers use it to bail to the classic path
    BEFORE paying collect_pod_pairs/intern/ClassBatch/AffinityData for a
    chunk whose verdict is already known to be overflow."""
    return (len(_own_terms(pod, anti=False)) > S_REQ_AFF
            or len(_own_terms(pod, anti=True)) > S_REQ_ANTI
            or len(_pref_terms(pod)) > S_PREF
            or len(_out_terms(pod, hard_weight)) > S_OUT)


def _term_topology_keys(pod: Pod) -> List[str]:
    """Every topology key any (anti-)affinity term of `pod` references."""
    keys = []
    a = pod.affinity
    if a is None:
        return keys
    for pa in (a.pod_affinity, a.pod_anti_affinity):
        if pa is None:
            continue
        for t in pa.required_terms:
            if t.topology_key:
                keys.append(t.topology_key)
        for _w, t in pa.preferred_terms:
            if t.topology_key:
                keys.append(t.topology_key)
    return keys


def collect_pod_pairs(infos) -> Tuple[list, list]:
    """(all_pairs, aff_pairs): every bound pod with its node, and the
    pods-with-affinity subset (node_info.go PodsWithAffinity). The single
    source for both the engine's and the extender's AffinityData inputs."""
    all_pairs, aff_pairs = [], []
    for info in infos.values():
        for q in info.pods:
            all_pairs.append((q, info.node))
        for q in info.pods_with_affinity:
            aff_pairs.append((q, info.node))
    return all_pairs, aff_pairs


def intern_topology_pairs(snap, pending_pods: Sequence[Pod],
                          aff_pods) -> None:
    """Intern every (topology_key, node_value) pair reachable from ANY
    affinity term — the pending pods' own terms AND the existing
    pods_with_affinity terms (the symmetry + priority side).

    The snapshot's label vocab is demand-driven by pod SELECTORS
    (snapshot.py compile_requirements); a topology key referenced only by an
    affinity term would otherwise have no domain columns, making
    AffinityData.domain_id silently return -1 and the constraint evaporate —
    the r2 symmetry-violation bug (ref semantics: predicates.go:1146
    satisfiesExistingPodsAntiAffinity must hold for every placement).
    Must run after ClusterSnapshot.refresh() (needs the node label index)
    and before PodBatch/ClassBatch construction (which finalizes the label
    matrix)."""
    keys = set()
    for pod in pending_pods:
        keys.update(_term_topology_keys(pod))
    for pod, _node in aff_pods:
        keys.update(_term_topology_keys(pod))
    for key in keys:
        for v in snap.node_values_for_key(key):
            snap.ensure_label_pair(key, v)


class AffinityData:
    """Host-side builder of the class-level device arrays.

    reps        class representative pods (real classes, unpadded)
    snap        ClusterSnapshot (label vocab + node order must be current)
    all_pods    [(pod, node)] every bound pod with its node
    aff_pods    subset carrying pod (anti-)affinity (PodsWithAffinity list)
    workloads   Service/RC/RS/StatefulSet selector objects
    c_pad       padded class-axis size (engine's bucketed class count)
    """

    def __init__(self, reps: Sequence[Pod], snap, all_pods, aff_pods,
                 workloads: Sequence = (), hard_weight: int = 1,
                 c_pad: Optional[int] = None):
        C0 = len(reps)
        C = c_pad if c_pad is not None else C0
        assert C >= C0
        L = snap.labels.shape[1]
        N = snap.labels.shape[0]
        vocab = snap.label_vocab
        self.num_classes = C0

        self.fail_all = np.zeros(C, dtype=bool)
        self.overflow = np.zeros(C, dtype=bool)
        self.forbid_static = np.zeros((C, L), dtype=np.int8)
        self.aff_active = np.zeros((C, S_REQ_AFF), dtype=bool)
        self.aff_allow = np.zeros((C, S_REQ_AFF, L), dtype=np.int8)
        self.aff_has_static = np.zeros((C, S_REQ_AFF), dtype=bool)
        self.aff_self = np.zeros((C, S_REQ_AFF), dtype=bool)
        self.aff_keymask = np.zeros((C, S_REQ_AFF, L), dtype=np.int8)
        self.anti_active = np.zeros((C, S_REQ_ANTI), dtype=bool)
        self.anti_keymask = np.zeros((C, S_REQ_ANTI, L), dtype=np.int8)
        self.m_aff = np.zeros((C, S_REQ_AFF, C), dtype=np.int8)
        self.m_anti = np.zeros((C, S_REQ_ANTI, C), dtype=np.int8)

        self.prio_static = np.zeros((C, L), dtype=np.int32)
        self.p_w = np.zeros((C, S_PREF), dtype=np.int32)
        self.p_keymask = np.zeros((C, S_PREF, L), dtype=np.int8)
        self.mp = np.zeros((C, S_PREF, C), dtype=np.int8)
        self.q_w = np.zeros((C, S_OUT), dtype=np.int32)
        self.q_keymask = np.zeros((C, S_OUT, L), dtype=np.int8)
        self.mq = np.zeros((C, S_OUT, C), dtype=np.int8)

        self.sp_static = np.zeros((C, N), dtype=np.int32)
        self.sp_cls = np.zeros((C, C), dtype=np.int8)
        self.sp_has = np.zeros(C, dtype=bool)

        def keymask(key: str) -> np.ndarray:
            m = np.zeros(L, dtype=np.int8)
            for idx in vocab.by_key.get(key, []):
                if idx < L:
                    m[idx] = 1
            return m

        def domain_id(node: Optional[Node], key: str) -> int:
            if node is None or not key:
                return -1
            val = node.labels.get(key)
            if val is None:
                return -1
            return vocab.get(key, val)

        # ---------------- fits side -------------------------------------
        any_required = False
        for c, rep in enumerate(reps):
            own_aff = _own_terms(rep, anti=False)
            own_anti = _own_terms(rep, anti=True)
            if len(own_aff) > S_REQ_AFF or len(own_anti) > S_REQ_ANTI:
                self.overflow[c] = True
                continue
            if own_aff or own_anti:
                any_required = True
            for s, term in enumerate(own_aff):
                if not term.topology_key:
                    self.fail_all[c] = True  # predicates.go:1015
                    continue
                self.aff_active[c, s] = True
                self.aff_keymask[c, s] = keymask(term.topology_key)
                self.aff_self[c, s] = term_matches_pod(term, rep, rep)
                for existing, enode in all_pods:
                    if term_matches_pod(term, rep, existing):
                        self.aff_has_static[c, s] = True
                        d = domain_id(enode, term.topology_key)
                        if d >= 0:
                            self.aff_allow[c, s, d] = 1
                for d2, rep2 in enumerate(reps):
                    if term_matches_pod(term, rep, rep2):
                        self.m_aff[c, s, d2] = 1
            for a, term in enumerate(own_anti):
                if not term.topology_key:
                    self.fail_all[c] = True
                    continue
                self.anti_active[c, a] = True
                self.anti_keymask[c, a] = keymask(term.topology_key)
                for existing, enode in all_pods:
                    if term_matches_pod(term, rep, existing):
                        d = domain_id(enode, term.topology_key)
                        if d >= 0:
                            self.forbid_static[c, d] = 1
                for d2, rep2 in enumerate(reps):
                    if term_matches_pod(term, rep, rep2):
                        self.m_anti[c, a, d2] = 1
            # symmetry: existing pods' required anti-affinity matching c
            # (metadata.go matchingAntiAffinityTerms)
            for existing, enode in aff_pods:
                for term in _own_terms(existing, anti=True):
                    if term_matches_pod(term, existing, rep):
                        any_required = True
                        if not term.topology_key:
                            self.fail_all[c] = True  # oracle: empty key fails
                            continue
                        d = domain_id(enode, term.topology_key)
                        if d >= 0:
                            self.forbid_static[c, d] = 1

        # ---------------- priority side ---------------------------------
        any_prio = False
        for c, rep in enumerate(reps):
            prefs = _pref_terms(rep)
            if len(prefs) > S_PREF:
                self.overflow[c] = True
                continue
            if prefs:
                any_prio = True
            for t, (w, term, is_anti) in enumerate(prefs):
                sw = -w if is_anti else w
                if w == 0:
                    continue
                self.p_w[c, t] = sw
                self.p_keymask[c, t] = keymask(term.topology_key)
                for existing, enode in all_pods:
                    if term_matches_pod(term, rep, existing):
                        d = domain_id(enode, term.topology_key)
                        if d >= 0:
                            self.prio_static[c, d] += sw
                for d2, rep2 in enumerate(reps):
                    if term_matches_pod(term, rep, rep2):
                        self.mp[c, t, d2] = 1
            # existing pods' terms scoring THIS class (static part)
            for existing, enode in aff_pods:
                for sw, term in _out_terms(existing, hard_weight):
                    if sw != 0 and term_matches_pod(term, existing, rep):
                        d = domain_id(enode, term.topology_key)
                        if d >= 0:
                            self.prio_static[c, d] += sw
        # committed classes' outgoing terms scoring pending classes
        for d2, rep2 in enumerate(reps):
            outs = _out_terms(rep2, hard_weight)
            if len(outs) > S_OUT:
                self.overflow[d2] = True
                continue
            for u, (sw, term) in enumerate(outs):
                if sw == 0:
                    continue
                self.q_w[d2, u] = sw
                self.q_keymask[d2, u] = keymask(term.topology_key)
                for c, rep in enumerate(reps):
                    if term_matches_pod(term, rep2, rep):
                        self.mq[d2, u, c] = 1

        # ---------------- selector spreading ----------------------------
        for c, rep in enumerate(reps):
            selectors = [w for w in workloads if w.selects(rep)]
            if not selectors:
                continue
            self.sp_has[c] = True
            name_to_col = snap.node_index
            for existing, enode in all_pods:
                if existing.namespace != rep.namespace or existing.deleted:
                    continue
                if any(w.selects(existing) for w in selectors):
                    col = name_to_col.get(enode.name if enode else "", -1)
                    if col >= 0:
                        self.sp_static[c, col] += 1
            for d2, rep2 in enumerate(reps):
                if rep2.namespace == rep.namespace \
                        and any(w.selects(rep2) for w in selectors):
                    self.sp_cls[c, d2] = 1

        # ---------------- zones (for the spread blend) ------------------
        zone_keys: Dict[str, int] = {}
        zone_id = np.full(N, -1, dtype=np.int32)
        for col, lbls in enumerate(snap._row_labels):
            region = lbls.get(ZONE_REGION_LABEL, "")
            zone = lbls.get(ZONE_LABEL, "")
            if not region and not zone:
                continue
            zk = region + ":\x00:" + zone
            zone_id[col] = zone_keys.setdefault(zk, len(zone_keys))
        ZN = max(1, len(zone_keys))
        Z = np.zeros((N, ZN), dtype=np.int8)
        for col in range(N):
            if zone_id[col] >= 0:
                Z[col, zone_id[col]] = 1
        self.Z = Z
        self.node_has_zone = zone_id >= 0

        self.fits_needed = any_required or self.fail_all.any()
        # prio_needed gates on NONZERO contributions, not mere presence of
        # affinity-carrying pods: a cluster of required-anti-only pods (no
        # preferred terms, no outgoing score terms) produces identically
        # zero InterPodAffinity counts, and tracing the whole priority side
        # through the scan for a guaranteed zero is pure per-step cost.
        # Exactness: counts can only come from prio_static (static matches),
        # p_w x own-preferred occupancy, or q_w x incoming occupancy — all
        # three all-zero forces counts == 0 and interpod_score(0) == 0.
        self.prio_needed = any_prio or bool(
            self.prio_static.any() or self.p_w.any() or self.q_w.any())
        self.spread_needed = bool(self.sp_has.any())
        # required (anti-)affinity classes must schedule sequentially (their
        # fits depend on every prior in-batch commit) -> wave mode routes
        # them to the strict scan. Classes with a nonzero STATIC forbid row
        # (an existing pod's required anti-affinity matches them — symmetry,
        # predicates.go:1146) also serialize: the wave fits path doesn't
        # evaluate affinity masks, and a plain pod forbidden from a topology
        # by a bound guard pod must not slip through the throughput path.
        self.serialize = (self.aff_active.any(axis=1)
                          | self.anti_active.any(axis=1) | self.fail_all
                          | self.forbid_static.any(axis=1))

        # ---------------- wave-path classification (ISSUE 3) --------------
        # The pipelined wave engine re-evaluates required-anti constraints
        # per WAVE from [C, L] topology-occupancy counters (waves.py). That
        # is exact for a class iff:
        #   - forbidden domains only GROW as pods commit (anti occupancy and
        #     the symmetry row are monotone), so a wave-start mask is valid
        #     for every pod placed under it and "fits nowhere" is final —
        #     the same monotonicity that makes capacity verdicts exact;
        #   - within one wave, per-node conflict resolution commits a single
        #     class per node, so cross-class anti violations inside a wave
        #     need two nodes SHARING a topology domain — excluded by
        #     requiring every key on the class's required-anti surface (own
        #     terms AND incoming terms that target it) to have SINGLETON
        #     domains (each (key, value) label column on at most one node:
        #     the hostname shape);
        #   - a self-anti class additionally commits at most one pod per
        #     node per wave (wave_gate -> the `special` discipline), so its
        #     own same-node FIFO run cannot collide with itself.
        # Own required AFFINITY is never wave-safe (a bootstrapping group
        # evaluated against one frozen mask would scatter instead of
        # co-locating), nor is fail_all/overflow. Those classes keep the
        # strict scan — but as a SEEDED TAIL after the wave pass (engine
        # harvest), never silently through the throughput path.
        anti_target = self.m_anti.any(axis=(0, 1))        # [C] targeted by
        # some pending class's required anti term (symmetry side)
        relevant = (self.aff_active.any(axis=1) | self.anti_active.any(axis=1)
                    | anti_target | self.forbid_static.any(axis=1)
                    | self.fail_all)
        strict = (self.overflow | self.fail_all
                  | self.aff_active.any(axis=1))
        # singleton-domain test per label column over the CURRENT node set
        multi_col = snap.domain_node_counts() > 1                   # [L]
        term_multi = (self.anti_keymask.astype(bool)
                      & multi_col[None, None, :]).any(axis=2)       # [C, A]
        own_multi = (term_multi & self.anti_active).any(axis=1)
        in_multi = (self.m_anti.astype(bool)
                    & term_multi[:, :, None]).any(axis=(0, 1))      # [C]
        # (forbid_static needs no width gate: it is CONSTANT inside the
        # wave mask, so it is exact at any domain width — only domains that
        # GROW from in-batch commits carry the within-wave hazard)
        strict |= relevant & (own_multi | in_multi)
        self.wave_strict = relevant & strict
        iota_c = np.arange(C)
        self_anti = self.m_anti[iota_c, :, iota_c].any(axis=1)
        self.wave_gate = relevant & ~strict & self_anti
        self.wave_relevant = relevant

    def device_arrays(self) -> Arrays:
        """Zero-copy upload of the STATIC class arrays — nothing mutates
        them after __init__, so the alias is safe; GRAFT_SANITIZE=1 seals
        the host sources to make that lifecycle claim crash-enforced."""
        from kubernetes_tpu.analysis.sanitize import upload_frozen
        out = {}
        for k in ("fail_all", "forbid_static", "aff_active", "aff_allow",
                  "aff_has_static", "aff_self", "aff_keymask", "anti_active",
                  "anti_keymask", "m_aff", "m_anti", "prio_static", "p_w",
                  "p_keymask", "mp", "q_w", "q_keymask", "mq", "sp_static",
                  "sp_cls", "sp_has", "Z", "node_has_zone", "wave_gate"):
            out[k] = upload_frozen(getattr(self, k))
        return out


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------


def precompute_static(aff: Arrays, labels: jnp.ndarray) -> Arrays:
    """Batch-wide static matmuls against the node-domain incidence
    (labels int8 [N, L]) — the MXU part, once per batch."""
    lab = labels.astype(jnp.int8)
    # [C,S,L] x [N,L] -> [C,S,N]
    allow_hit = jnp.einsum("csl,nl->csn", aff["aff_allow"], lab,
                           preferred_element_type=jnp.int32) > 0
    forbid_hit = jnp.einsum("cl,nl->cn", aff["forbid_static"], lab,
                            preferred_element_type=jnp.int32) > 0
    prio_counts = jnp.einsum("cl,nl->cn", aff["prio_static"],
                             lab.astype(jnp.int32),
                             preferred_element_type=jnp.int32)
    return {"allow_hit": allow_hit, "forbid_hit": forbid_hit,
            "prio_counts": prio_counts}


def step_fits(aff: Arrays, pre: Arrays, c: jnp.ndarray,
              commdom: jnp.ndarray, comm_cnt: jnp.ndarray,
              labels: jnp.ndarray) -> jnp.ndarray:
    """InterPodAffinity predicate for pod class c against the current scan
    carry. [N] bool. Mirrors inter_pod_affinity_fits (oracle_ext.py)."""
    lab = labels.astype(jnp.int32)
    active = aff["aff_active"][c]          # [S]
    # dynamic occupancy of committed matching pods: [S,C] x [C,L] -> [S,L]
    occ = jnp.einsum("sc,cl->sl", aff["m_aff"][c].astype(jnp.int32), commdom)
    occ = occ * aff["aff_keymask"][c].astype(jnp.int32)
    dyn_hit = jnp.einsum("sl,nl->sn", occ, lab) > 0        # [S,N]
    dyn_total = aff["m_aff"][c].astype(jnp.int32) @ comm_cnt  # [S]
    static_hit = pre["allow_hit"][c]       # [S,N]
    has_static = aff["aff_has_static"][c]  # [S]
    bootstrap = (aff["aff_self"][c] & ~has_static
                 & (dyn_total == 0))       # [S] first of a self-ref group
    ok_s = (~active[:, None]) | static_hit | dyn_hit | bootstrap[:, None]
    ok = ok_s.all(axis=0)                  # [N]
    # own anti (dynamic part; static folded into forbid_static)
    occa = jnp.einsum("ac,cl->al", aff["m_anti"][c].astype(jnp.int32), commdom)
    occa = occa * aff["anti_keymask"][c].astype(jnp.int32)
    anti_dyn = (jnp.einsum("al,nl->an", occa, lab) > 0) \
        & aff["anti_active"][c][:, None]
    # symmetry vs committed pods' required anti terms matching c:
    # sym_occ[l] = sum_{d,a} m_anti[d,a,c] * anti_keymask[d,a,l] * commdom[d,l]
    m_in = aff["m_anti"][:, :, c].astype(jnp.int32)        # [C,A]
    sym_occ = (m_in[:, :, None] * aff["anti_keymask"].astype(jnp.int32)
               * commdom[:, None, :]).sum(axis=(0, 1))     # [L]
    sym_hit = (sym_occ @ lab.T) > 0                        # [N]
    forbidden = pre["forbid_hit"][c] | anti_dyn.any(axis=0) | sym_hit
    return ok & ~forbidden & ~aff["fail_all"][c]


def step_prio_counts(aff: Arrays, pre: Arrays, c: jnp.ndarray,
                     commdom: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """InterPodAffinity weighted counts for class c, [N] int32 (before the
    0..10 normalization)."""
    lab = labels.astype(jnp.int32)
    counts = pre["prio_counts"][c]
    # own preferred terms vs committed pods
    occp = jnp.einsum("tc,cl->tl", aff["mp"][c].astype(jnp.int32), commdom)
    occp = occp * aff["p_keymask"][c].astype(jnp.int32)
    per_t = jnp.einsum("tl,nl->tn", occp, lab)             # [T,N]
    counts = counts + (aff["p_w"][c][:, None] * per_t).sum(axis=0)
    # committed classes' outgoing terms scoring c:
    # occq[l] = sum_{d,u} q_w[d,u] * mq[d,u,c] * q_keymask[d,u,l] * commdom[d,l]
    mq_in = aff["mq"][:, :, c].astype(jnp.int32)           # [C,U]
    wq = aff["q_w"] * mq_in                                # [C,U]
    occq = (wq[:, :, None] * aff["q_keymask"].astype(jnp.int32)
            * commdom[:, None, :]).sum(axis=(0, 1))        # [L]
    counts = counts + occq @ lab.T
    return counts


def step_fits_all(aff: Arrays, pre: Arrays, commdom: jnp.ndarray,
                  comm_cnt: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Class-vectorized ``step_fits``: the required (anti-)affinity mask
    for EVERY class against one occupancy carry, [C, N] bool — row c is
    bit-identical to ``step_fits(aff, pre, c, ...)``. The conflict-round
    tail evaluates all of a round's classes in one shot instead of
    indexing per pod inside a scan; the einsums just keep the class axis
    the per-class forms contract away."""
    lab = labels.astype(jnp.int32)
    m_aff = aff["m_aff"].astype(jnp.int32)
    occ = jnp.einsum("csd,dl->csl", m_aff, commdom) \
        * aff["aff_keymask"].astype(jnp.int32)
    dyn_hit = jnp.einsum("csl,nl->csn", occ, lab) > 0       # [C,S,N]
    dyn_total = jnp.einsum("csd,d->cs", m_aff, comm_cnt)    # [C,S]
    bootstrap = (aff["aff_self"] & ~aff["aff_has_static"]
                 & (dyn_total == 0))                        # [C,S]
    ok = ((~aff["aff_active"][:, :, None]) | pre["allow_hit"] | dyn_hit
          | bootstrap[:, :, None]).all(axis=1)              # [C,N]
    m_anti = aff["m_anti"].astype(jnp.int32)
    occa = jnp.einsum("cad,dl->cal", m_anti, commdom) \
        * aff["anti_keymask"].astype(jnp.int32)
    anti_dyn = (jnp.einsum("cal,nl->can", occa, lab) > 0) \
        & aff["anti_active"][:, :, None]
    sym_occ = jnp.einsum("dac,dal->cl", m_anti,
                         aff["anti_keymask"].astype(jnp.int32)
                         * commdom[:, None, :])              # [C,L]
    sym_hit = jnp.einsum("cl,nl->cn", sym_occ, lab) > 0
    forbidden = pre["forbid_hit"] | anti_dyn.any(axis=1) | sym_hit
    return ok & ~forbidden & ~aff["fail_all"][:, None]


def step_prio_counts_all(aff: Arrays, pre: Arrays, commdom: jnp.ndarray,
                         labels: jnp.ndarray) -> jnp.ndarray:
    """Class-vectorized ``step_prio_counts``: InterPodAffinity weighted
    counts for every class, [C, N] int32, row-identical to the per-class
    form."""
    lab = labels.astype(jnp.int32)
    counts = pre["prio_counts"]
    occp = jnp.einsum("ctd,dl->ctl", aff["mp"].astype(jnp.int32), commdom) \
        * aff["p_keymask"].astype(jnp.int32)
    per_t = jnp.einsum("ctl,nl->ctn", occp, lab)            # [C,T,N]
    counts = counts + (aff["p_w"][:, :, None] * per_t).sum(axis=1)
    # occq[r, l] = sum_{d,u} q_w[d,u] * mq[d,u,r] * q_keymask[d,u,l]
    #            * commdom[d,l] — committed classes' outgoing terms
    occq = jnp.einsum("du,dur,dul,dl->rl", aff["q_w"],
                      aff["mq"].astype(jnp.int32),
                      aff["q_keymask"].astype(jnp.int32), commdom)
    counts = counts + jnp.einsum("rl,nl->rn", occq, lab)
    return counts


def interpod_score(counts: jnp.ndarray, fits: jnp.ndarray) -> jnp.ndarray:
    """0..10 normalization over the filtered set (interpod_affinity.go:224-
    239): max clamped >= 0, min clamped <= 0, integer floor division equals
    the reference's float64 truncation for integer counts. Shape-generic:
    [..., N] with the node axis last (per-step [N] or frozen [C, N])."""
    masked_max = jnp.where(fits, counts, jnp.int32(-(2 ** 31 - 1))) \
        .max(axis=-1, keepdims=True)
    masked_min = jnp.where(fits, counts, jnp.int32(2 ** 31 - 1)) \
        .min(axis=-1, keepdims=True)
    mx = jnp.maximum(masked_max, 0)
    mn = jnp.minimum(masked_min, 0)
    rng = mx - mn
    return jnp.where(rng > 0,
                     (MAX_PRIORITY * (counts - mn)) // jnp.maximum(rng, 1),
                     0).astype(jnp.int32)


def step_spread_counts(aff: Arrays, c: jnp.ndarray,
                       committed: jnp.ndarray) -> jnp.ndarray:
    """Matching-pod counts per node for class c: static existing pods plus
    committed in-batch pods of selector-matching classes. [N] int32."""
    dyn = aff["sp_cls"][c].astype(jnp.int32) @ committed   # [N]
    return aff["sp_static"][c] + dyn


# Saturation caps keeping the exact-rational blend inside int32: per-node
# matching-pod counts cap at 2^11-1 (a 110-pods-per-node reference node
# cannot reach it), zone sums at 2^15-1. Worst-case numerator is then
# 10*M*Mz + 20*Mz*M = 30*2^26 < 2^31. The oracle applies the SAME caps, so
# engine==oracle holds everywhere, including (unreachable) saturation.
SPREAD_NODE_COUNT_CAP = (1 << 11) - 1
SPREAD_ZONE_COUNT_CAP = (1 << 15) - 1


def spread_score(aff: Arrays, has_sel: jnp.ndarray, counts: jnp.ndarray,
                 fits: jnp.ndarray) -> jnp.ndarray:
    """selector_spreading.go:134-185, with the zone blend defined as the
    EXACT rational floor instead of the reference's float64 arithmetic:

        score = floor( 10(M-c)/M * 1/3  +  2/3 * 10(Mz-zc)/Mz )
              = (10(M-c)*Mz + 20(Mz-zc)*M) // (3*M*Mz)

    computed in pure int32 — no float64, so nothing forces
    jax.enable_x64 anywhere in the engine (r4 VERDICT weak #3). This is a
    deliberate, documented deviation from the Go reference on float64
    rounding crumbs: trunc(f64 blend) differs from the exact floor in
    ~0.03% of small-count configurations (measured 179/670,761 over
    M,Mz<=40 — e.g. all-counts-equal yields the mathematically-right 7
    where Go's 6.999999999999999 truncates to 6). The oracle implements
    the same exact-rational spec, so differential fuzz stays bit-exact.
    Shape-generic: counts/fits [..., N], has_sel [...]. Returns int32
    scores [..., N]."""
    counts = jnp.minimum(jnp.where(fits, counts, 0),
                         SPREAD_NODE_COUNT_CAP)
    max_node = counts.max(axis=-1, keepdims=True)
    zmat = aff["Z"].astype(jnp.int32)                      # [N, ZN]
    # per-zone sums over FITTING nodes only (capped like the node counts)
    zc = jnp.minimum(jnp.einsum("...n,nz->...z", counts, zmat),
                     SPREAD_ZONE_COUNT_CAP)
    node_zone = aff["node_has_zone"]                       # [N]
    has_sel = has_sel[..., None]
    have_zones = (fits & node_zone).any(axis=-1, keepdims=True) & has_sel
    zone_seen = jnp.einsum("...n,nz->...z",
                           (fits & node_zone).astype(jnp.int32), zmat) > 0
    max_zone = jnp.where(zone_seen, zc, 0).max(axis=-1, keepdims=True)
    node_zc = jnp.einsum("...z,nz->...n", zc, zmat)        # own-zone sum
    ten = jnp.int32(MAX_PRIORITY)
    node_scored = (max_node > 0) & has_sel
    # r1 = fscore as a rational r1n/r1d (10/1 when unscored)
    r1n = jnp.where(node_scored, ten * (max_node - counts), ten)
    r1d = jnp.where(node_scored, jnp.maximum(max_node, 1), 1)
    fscore = r1n // r1d
    # z = zscore rational zn/zd (0/1 when the zone axis is empty)
    zone_scored = max_zone > 0
    zn = jnp.where(zone_scored, ten * (max_zone - node_zc), 0)
    zd = jnp.where(zone_scored, jnp.maximum(max_zone, 1), 1)
    blended = (r1n * zd + 2 * zn * r1d) // (3 * r1d * zd)
    use_blend = have_zones & node_zone
    return jnp.where(use_blend, blended, fscore).astype(jnp.int32)
