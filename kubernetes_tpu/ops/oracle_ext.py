"""Oracle part 2: inter-pod affinity, selector spreading, and the remaining
priorities — exact object-level reimplementations (float64 semantics match Go).

Reference parity:
  InterPodAffinityMatches         predicates.go:982-1060 (+ symmetry check
                                  satisfiesExistingPodsAntiAffinity :1146,
                                  self-match bootstrap :1210-1230)
  CalculateInterPodAffinityPriority interpod_affinity.go:119-240
  CalculateSpreadPriority         selector_spreading.go:98-185 (2/3 zone weight)
  CalculateNodeAffinityPriority   node_affinity.go:36-100 (map + max reduce)
  CalculateNodePreferAvoidPods    node_prefer_avoid_pods.go:29-60
  ImageLocalityPriorityMap        image_locality.go:32-90 (23MB-1GB buckets)
  NodesHaveSameTopologyKey        priorities/util/topologies.go:50-70
  GetZoneKey                      pkg/util/node/node.go:115-132
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    MAX_PRIORITY,
    Node,
    Pod,
    PodAffinityTerm,
    WorkloadObject,
)
from kubernetes_tpu.state.node_info import NodeInfo

ZONE_REGION_LABEL = "failure-domain.beta.kubernetes.io/region"
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
from kubernetes_tpu.api.annotations import AVOID_PODS_ANNOTATION  # noqa: E402

MB = 1024 * 1024
MIN_IMG_SIZE = 23 * MB
MAX_IMG_SIZE = 1000 * MB


class SchedulingContext:
    """Cluster-wide state the object-level algorithms read beyond a single
    NodeInfo: every bound pod (with its node), and workload objects for
    spreading. Built from the cache's info map."""

    def __init__(self, infos: Dict[str, NodeInfo],
                 workloads: Sequence[WorkloadObject] = (),
                 hard_pod_affinity_weight: int = 1,
                 volume_ctx=None, policy_algos=None):
        self.infos = infos
        self.workloads = list(workloads)
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        # PV/PVC mirror for the volume predicates (state/volumes.VolumeContext)
        self.volume_ctx = volume_ctx
        # Policy-configured parameterized algorithms (ops/policy_algos.py)
        self.policy_algos = policy_algos
        self._all_pods: Optional[List[Tuple[Pod, Optional[Node]]]] = None
        self._affinity_pods: Optional[List[Tuple[Pod, Optional[Node]]]] = None

    def invalidate(self) -> None:
        """Call after mutating infos (e.g. an assume landed)."""
        self._all_pods = None
        self._affinity_pods = None

    def all_pods(self) -> List[Tuple[Pod, Optional[Node]]]:
        if self._all_pods is None:
            out = []
            for info in self.infos.values():
                for p in info.pods:
                    out.append((p, info.node))
            self._all_pods = out
        return self._all_pods

    def affinity_pods(self) -> List[Tuple[Pod, Optional[Node]]]:
        """Existing pods carrying any pod (anti-)affinity — the
        PodsWithAffinity fast list (node_info.go)."""
        if self._affinity_pods is None:
            out = []
            for info in self.infos.values():
                for p in info.pods_with_affinity:
                    out.append((p, info.node))
            self._affinity_pods = out
        return self._affinity_pods


class AffinityMeta:
    """Per-pending-pod precompute shared across all candidate nodes — the
    predicate-metadata analog (predicates/metadata.go:39
    matchingAntiAffinityTerms + per-term existing-pod match lists)."""

    def __init__(self, pod: Pod, ctx: "SchedulingContext"):
        # existing pods' required anti-affinity terms that MATCH this pod
        self.matching_anti: List[Tuple[PodAffinityTerm, Optional[Node]]] = []
        for existing, enode in ctx.affinity_pods():
            for term in _own_terms(existing, anti=True):
                if term_matches_pod(term, existing, pod):
                    self.matching_anti.append((term, enode))
        # for each of the pod's own required terms: matching existing pods
        self.own_aff: List[Tuple[PodAffinityTerm, List[Optional[Node]], bool]] = []
        self.own_anti: List[Tuple[PodAffinityTerm, List[Optional[Node]]]] = []
        aff = pod.affinity
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
            all_pods = ctx.all_pods()
            for term in _own_terms(pod, anti=False):
                matches = [enode for existing, enode in all_pods
                           if term_matches_pod(term, pod, existing)]
                self.own_aff.append((term, matches,
                                     term_matches_pod(term, pod, pod)))
            for term in _own_terms(pod, anti=True):
                matches = [enode for existing, enode in all_pods
                           if term_matches_pod(term, pod, existing)]
                self.own_anti.append((term, matches))


def nodes_same_topology(a: Optional[Node], b: Optional[Node], key: str) -> bool:
    """topologies.go:50-70 — empty key or missing label on either -> False."""
    if not key or a is None or b is None:
        return False
    va = a.labels.get(key)
    vb = b.labels.get(key)
    return va is not None and vb is not None and va == vb


def get_zone_key(node: Optional[Node]) -> str:
    """node.go:115-132."""
    if node is None:
        return ""
    region = node.labels.get(ZONE_REGION_LABEL, "")
    zone = node.labels.get(ZONE_LABEL, "")
    if not region and not zone:
        return ""
    return region + ":\x00:" + zone


def term_namespaces(owner: Pod, term: PodAffinityTerm) -> List[str]:
    """topologies.go GetNamespacesFromPodAffinityTerm."""
    return list(term.namespaces) if term.namespaces else [owner.namespace]


def term_matches_pod(term: PodAffinityTerm, owner: Pod, target: Pod) -> bool:
    """PodMatchesTermsNamespaceAndSelector; nil selector matches nothing
    (LabelSelectorAsSelector(nil) -> labels.Nothing())."""
    if target.namespace not in term_namespaces(owner, term):
        return False
    if term.label_selector is None:
        return False
    return term.label_selector.matches(target.labels)


# ---------------------------------------------------------------------------
# inter-pod affinity predicate
# ---------------------------------------------------------------------------


def _own_terms(pod: Pod, anti: bool) -> List[PodAffinityTerm]:
    aff = pod.affinity
    if aff is None:
        return []
    pa = aff.pod_anti_affinity if anti else aff.pod_affinity
    return list(pa.required_terms) if pa is not None else []


def inter_pod_affinity_fits(pod: Pod, node: Node, ctx: SchedulingContext,
                            meta: Optional[AffinityMeta] = None) -> bool:
    """predicates.go:982-1060. `meta` is the once-per-pod precompute
    (AffinityMeta); without it, one is built on the fly."""
    if meta is None:
        meta = AffinityMeta(pod, ctx)
    # 1. symmetry: no existing pod's required anti-affinity may be violated
    for term, enode in meta.matching_anti:
        if not term.topology_key:
            return False  # empty key invalid for required anti-aff
        if nodes_same_topology(node, enode, term.topology_key):
            return False
    aff = pod.affinity
    if aff is None or (aff.pod_affinity is None and aff.pod_anti_affinity is None):
        return True
    # 2. pod's own required affinity terms
    for term, matches, self_match in meta.own_aff:
        if not term.topology_key:
            return False
        on_node = any(nodes_same_topology(node, enode, term.topology_key)
                      for enode in matches)
        if not on_node:
            if matches:  # matching pod exists somewhere else
                return False
            # bootstrap: first pod of a self-referencing group may land
            # (predicates.go:1210-1230)
            if not self_match:
                return False
    # 3. pod's own required anti-affinity terms
    for term, matches in meta.own_anti:
        if not term.topology_key:
            return False
        if any(nodes_same_topology(node, enode, term.topology_key)
               for enode in matches):
            return False
    return True


# ---------------------------------------------------------------------------
# inter-pod affinity priority
# ---------------------------------------------------------------------------


def interpod_affinity_scores(pod: Pod, filtered: Sequence[NodeInfo],
                             ctx: SchedulingContext) -> List[int]:
    """interpod_affinity.go:119-240. `filtered` is the post-predicate node
    list; existing pods from the whole cluster contribute."""
    counts: Dict[str, float] = {}
    nodes = [i.node for i in filtered if i.node is not None]

    def process(term: PodAffinityTerm, owner: Pod, target: Pod,
                fixed: Optional[Node], weight: float) -> None:
        if weight == 0 or not term_matches_pod(term, owner, target):
            return
        for n in nodes:
            if nodes_same_topology(n, fixed, term.topology_key):
                counts[n.name] = counts.get(n.name, 0.0) + weight

    aff = pod.affinity
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    for existing, enode in ctx.all_pods():
        eaff = existing.affinity
        if pa is not None:
            for w, term in pa.preferred_terms:
                process(term, pod, existing, enode, float(w))
        if paa is not None:
            for w, term in paa.preferred_terms:
                process(term, pod, existing, enode, -float(w))
        if eaff is not None and eaff.pod_affinity is not None:
            if ctx.hard_pod_affinity_weight > 0:
                for term in eaff.pod_affinity.required_terms:
                    process(term, existing, pod, enode,
                            float(ctx.hard_pod_affinity_weight))
            for w, term in eaff.pod_affinity.preferred_terms:
                process(term, existing, pod, enode, float(w))
        if eaff is not None and eaff.pod_anti_affinity is not None:
            for w, term in eaff.pod_anti_affinity.preferred_terms:
                process(term, existing, pod, enode, -float(w))

    max_c = max([counts.get(n.name, 0.0) for n in nodes], default=0.0)
    max_c = max(max_c, 0.0)
    min_c = min([counts.get(n.name, 0.0) for n in nodes], default=0.0)
    min_c = min(min_c, 0.0)
    out = []
    for n in nodes:
        if max_c - min_c > 0:
            out.append(int(MAX_PRIORITY * ((counts.get(n.name, 0.0) - min_c)
                                           / (max_c - min_c))))
        else:
            out.append(0)
    return out


# ---------------------------------------------------------------------------
# selector spreading
# ---------------------------------------------------------------------------


def pod_selectors(pod: Pod, workloads: Sequence[WorkloadObject]
                  ) -> List[WorkloadObject]:
    """getSelectors (selector_spreading.go:59): every Service/RC/RS/SS whose
    selector matches the pod."""
    return [w for w in workloads if w.selects(pod)]


def selector_spread_scores(pod: Pod, filtered: Sequence[NodeInfo],
                           ctx: SchedulingContext) -> List[int]:
    """selector_spreading.go:98-185."""
    from kubernetes_tpu.ops.affinity import (
        SPREAD_NODE_COUNT_CAP,
        SPREAD_ZONE_COUNT_CAP,
    )
    selectors = pod_selectors(pod, ctx.workloads)
    nodes = [i.node for i in filtered if i.node is not None]
    counts: Dict[str, int] = {}
    counts_by_zone: Dict[str, int] = {}
    max_by_node = 0
    if selectors:
        for info in filtered:
            node = info.node
            if node is None:
                continue
            count = 0
            for np in info.pods:
                if np.namespace != pod.namespace or np.deleted:
                    continue
                if any(w.selects(np) for w in selectors):
                    count += 1
            count = min(count, SPREAD_NODE_COUNT_CAP)
            counts[node.name] = count
            max_by_node = max(max_by_node, count)
            zone = get_zone_key(node)
            if zone:
                counts_by_zone[zone] = counts_by_zone.get(zone, 0) + count
    for z in counts_by_zone:
        counts_by_zone[z] = min(counts_by_zone[z], SPREAD_ZONE_COUNT_CAP)
    have_zones = bool(counts_by_zone)
    max_by_zone = max(counts_by_zone.values(), default=0)
    out = []
    for node in nodes:
        # exact-rational spec (see ops/affinity.py spread_score: deliberate
        # deviation from the reference's float64 rounding crumbs): the
        # score is floor of r1n/r1d blended 1/3:2/3 with zn/zd, over ints
        if max_by_node > 0:
            r1n = MAX_PRIORITY * (max_by_node - counts.get(node.name, 0))
            r1d = max_by_node
        else:
            r1n, r1d = MAX_PRIORITY, 1
        zone = get_zone_key(node)
        if have_zones and zone:
            if max_by_zone > 0:
                zn = MAX_PRIORITY * (max_by_zone
                                     - counts_by_zone.get(zone, 0))
                zd = max_by_zone
            else:
                zn, zd = 0, 1
            out.append((r1n * zd + 2 * zn * r1d) // (3 * r1d * zd))
        else:
            out.append(r1n // r1d)
    return out


# ---------------------------------------------------------------------------
# node affinity (preferred) priority
# ---------------------------------------------------------------------------


def node_affinity_scores(pod: Pod, filtered: Sequence[NodeInfo]) -> List[int]:
    """node_affinity.go:36-100: sum weights of matching preferred terms, then
    normalize by max -> 0..10 (no min subtraction)."""
    counts = []
    na = pod.affinity.node_affinity if pod.affinity else None
    for info in filtered:
        node = info.node
        count = 0
        if node is not None and na is not None:
            for weight, term in na.preferred_terms:
                if weight == 0:
                    continue
                # empty term matches all objects (node_affinity.go:51 comment);
                # NodeSelectorTerm.matches_labels returns False on empty, so
                # special-case it here
                if not term.match_expressions or term.matches_labels(node.labels):
                    count += weight
        counts.append(count)
    max_c = max(counts, default=0)
    if max_c <= 0:
        return [0 for _ in counts]
    return [int(MAX_PRIORITY * (c / max_c)) for c in counts]


# ---------------------------------------------------------------------------
# node prefer-avoid-pods priority
# ---------------------------------------------------------------------------


def node_avoids_pod(node: Node, pod: Pod) -> bool:
    """node_prefer_avoid_pods.go:29-60 + GetAvoidPodsFromNodeAnnotations
    (parsing shared with the snapshot path — api/annotations.py)."""
    if pod.owner_kind not in ("ReplicationController", "ReplicaSet"):
        return False
    from kubernetes_tpu.api.annotations import parse_avoid_annotation
    return (pod.owner_kind, pod.owner_uid) in \
        parse_avoid_annotation(node.annotations)


def prefer_avoid_scores(pod: Pod, filtered: Sequence[NodeInfo]) -> List[int]:
    out = []
    for info in filtered:
        node = info.node
        if node is None or not node_avoids_pod(node, pod):
            out.append(MAX_PRIORITY)
        else:
            out.append(0)
    return out


# ---------------------------------------------------------------------------
# image locality priority
# ---------------------------------------------------------------------------


def image_locality_scores(pod: Pod, filtered: Sequence[NodeInfo]) -> List[int]:
    """image_locality.go:32-90."""
    out = []
    for info in filtered:
        node = info.node
        total = 0
        if node is not None:
            for c in pod.containers:
                for img in node.images:
                    if c.image in img.names:
                        total += img.size_bytes
                        break
        if total == 0 or total < MIN_IMG_SIZE:
            out.append(0)
        elif total >= MAX_IMG_SIZE:
            out.append(MAX_PRIORITY)
        else:
            out.append(int(MAX_PRIORITY * (total - MIN_IMG_SIZE)
                           // (MAX_IMG_SIZE - MIN_IMG_SIZE)) + 1)
    return out
