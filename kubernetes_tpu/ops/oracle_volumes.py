"""Exact object-level volume predicates (golden reference for the kernels).

Parity map (reference: plugin/pkg/scheduler/algorithm/predicates/predicates.go):
  NoDiskConflict          :183-196 (+ isVolumeConflict :128-177)
  MaxPDVolumeCount        :198-323 (EBS/GCEPD/AzureDisk filters :324-374)
  NoVolumeZoneConflict    :376-474
  NoVolumeNodeConflict    :1345-1411 (PersistentLocalVolumes-gated)
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import Pod, Volume, VolumeKind
from kubernetes_tpu.state.node_info import NodeInfo
from kubernetes_tpu.state import volumes as volmod
from kubernetes_tpu.state.volumes import (
    UnresolvedVolume,
    VolumeContext,
    max_pd_volumes,
    node_zone_check,
    pd_id_sets,
    pv_affinity_requirements,
    zone_constraints,
)
from kubernetes_tpu.utils import features


def _is_volume_conflict(vol: Volume, existing_pod: Pod) -> bool:
    """predicates.go:128-177 isVolumeConflict."""
    kind = VolumeKind(vol.kind)
    if kind not in (VolumeKind.GCE_PD, VolumeKind.AWS_EBS, VolumeKind.RBD,
                    VolumeKind.ISCSI):
        return False
    for ev in existing_pod.volumes:
        ekind = VolumeKind(ev.kind)
        if kind == VolumeKind.GCE_PD and ekind == VolumeKind.GCE_PD:
            if (vol.volume_id == ev.volume_id
                    and not (vol.read_only and ev.read_only)):
                return True
        if kind == VolumeKind.AWS_EBS and ekind == VolumeKind.AWS_EBS:
            if vol.volume_id == ev.volume_id:
                return True
        if kind == VolumeKind.ISCSI and ekind == VolumeKind.ISCSI:
            if (vol.volume_id == ev.volume_id
                    and not (vol.read_only and ev.read_only)):
                return True
        if kind == VolumeKind.RBD and ekind == VolumeKind.RBD:
            if (set(vol.monitors) & set(ev.monitors)
                    and vol.pool == ev.pool and vol.image == ev.image
                    and not (vol.read_only and ev.read_only)):
                return True
    return False


def no_disk_conflict(pod: Pod, info: NodeInfo) -> bool:
    """predicates.go:183-196."""
    for v in pod.volumes:
        for ep in info.pods:
            if _is_volume_conflict(v, ep):
                return False
    return True


def max_pd_volume_count(pod: Pod, info: NodeInfo, ctx: VolumeContext,
                        limits: Optional[Tuple[int, int, int]] = None
                        ) -> List[bool]:
    """-> per-filter verdicts [ebs_ok, gce_ok, azure_ok]
    (predicates.go:285-323 MaxPDVolumeCountChecker.predicate, one checker
    per filter in the default provider)."""
    if limits is None:
        limits = max_pd_volumes()
    if not pod.volumes:
        return [True, True, True]
    new_sets = pd_id_sets(pod, ctx)
    out: List[bool] = []
    existing_sets = None
    for k, limit in enumerate(limits):
        new = new_sets[k]
        if not new:
            out.append(True)  # quick return (predicates.go:297-300)
            continue
        if existing_sets is None:
            existing_sets = [set() for _ in volmod.PD_KINDS]
            for ep in info.pods:
                for kk, vid in volmod.pd_filter_ids(ep, ctx):
                    existing_sets[kk].add(vid)
        existing = existing_sets[k]
        num_new = len(new - existing)
        out.append(len(existing) + num_new <= limit)
    return out


def no_volume_zone_conflict(pod: Pod, info: NodeInfo,
                            ctx: VolumeContext) -> bool:
    """predicates.go:404-474. Raises UnresolvedVolume where the reference
    returns a scheduling error."""
    if not pod.volumes or info.node is None:
        return info.node is not None
    node_zone = {k: v for k, v in info.node.labels.items()
                 if k in (volmod.ZONE_LABEL, volmod.REGION_LABEL)}
    if not node_zone:
        return True  # fast-path (predicates.go:425-430)
    return node_zone_check(info.node.labels, zone_constraints(pod, ctx))


def no_volume_node_conflict(pod: Pod, info: NodeInfo,
                            ctx: VolumeContext) -> bool:
    """predicates.go:1354-1411, gated on PersistentLocalVolumes."""
    if not features.enabled("PersistentLocalVolumes"):
        return True
    if not pod.volumes or info.node is None:
        return info.node is not None
    try:
        reqs = pv_affinity_requirements(pod, ctx)
    except UnresolvedVolume:
        raise
    labels = info.node.labels
    return all(r.matches_labels(labels) for r in reqs)


def volume_predicates_fit(pod: Pod, info: NodeInfo,
                          ctx: Optional[VolumeContext]) -> bool:
    """The default provider's four volume predicates ANDed
    (defaults.go:118-127: NoVolumeZoneConflict, MaxEBS/GCEPD/AzureDisk,
    NoDiskConflict, NoVolumeNodeConflict). UnresolvedVolume -> not fit
    (the reference propagates the error, failing the schedule attempt)."""
    if not pod.volumes:
        return True
    ctx = ctx or volmod.EMPTY_VOLUME_CONTEXT
    try:
        if not no_volume_zone_conflict(pod, info, ctx):
            return False
        if not all(max_pd_volume_count(pod, info, ctx)):
            return False
        if not no_disk_conflict(pod, info):
            return False
        if not no_volume_node_conflict(pod, info, ctx):
            return False
    except UnresolvedVolume:
        return False
    return True
