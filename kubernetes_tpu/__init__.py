"""kubernetes_tpu: a TPU-native cluster-scheduling framework.

See README.md for the architecture and SURVEY.md for the reference analysis.
"""

import os as _os


def _enable_persistent_compile_cache() -> None:
    """Opt-out persistent XLA compilation cache: the placement kernels cost
    seconds to compile per shape bucket; caching them on disk makes fresh
    processes (benches, tests, sidecars) start warm. Disable with
    KUBERNETES_TPU_NO_COMPILE_CACHE=1 or by setting your own cache dir."""
    if _os.environ.get("KUBERNETES_TPU_NO_COMPILE_CACHE"):
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update(
                "jax_compilation_cache_dir",
                _os.path.expanduser("~/.cache/kubernetes_tpu/xla"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
    except Exception:  # pragma: no cover - cache is an optimization only
        pass


_enable_persistent_compile_cache()
