"""kubernetes_tpu: a TPU-native cluster-scheduling framework.

See README.md for the architecture and SURVEY.md for the reference analysis.
"""

import os as _os


def _enable_persistent_compile_cache() -> None:
    """Opt-out persistent XLA compilation cache: the placement kernels cost
    seconds to compile per shape bucket; caching them on disk makes fresh
    processes (benches, tests, sidecars) start warm. Set via environment so
    importing the package costs nothing — jax reads these when (if) it is
    imported. Disable with KUBERNETES_TPU_NO_COMPILE_CACHE=1 or override by
    setting your own cache dir."""
    if _os.environ.get("KUBERNETES_TPU_NO_COMPILE_CACHE"):
        return
    _os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        _os.path.expanduser("~/.cache/kubernetes_tpu/xla"))
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           "0.5")


_enable_persistent_compile_cache()
