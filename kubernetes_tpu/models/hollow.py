"""Hollow-cluster generator: the kubemark-equivalent load rig.

The reference benchmarks against (a) scheduler_perf's fake nodes/pods
(test/integration/scheduler_perf/scheduler_test.go:42-68: 4 CPU / 32Gi /
110-pod nodes, trivial pods) and (b) kubemark hollow nodes
(cmd/kubemark/hollow-node.go — real kubelet logic, faked externalities).
This module generates equivalent synthetic clusters and the workload profiles
of BASELINE.json's five configs, loaded through the apiserver-lite.
"""

from __future__ import annotations

import copy
import random
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    Node,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOperator,
    make_node,
    make_pod,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Mi = 1024 * 1024
Gi = 1024 * Mi

ZONES = ["zone-a", "zone-b", "zone-c"]


# ------------------------------------------------------------- columnar
# ISSUE 12: at 50k nodes / 300k pods the per-object constructor path
# (make_pod -> Container -> Pod, ~20us each) costs seconds of pure
# setup per sweep point — enough to drown the measurement it feeds. The
# bulk builders go columnar: ONE template object per distinct spec,
# then a tight shallow-copy materialization per name. The templates'
# spec members (containers, tolerations, condition lists) are shared —
# every consumer treats pod/node SPEC as immutable (the churn harness
# rebuilds via dataclasses.replace; schedulers write only node_name /
# annotations, which each copy owns fresh).


def _stamp(p: Pod, name: str, prefix: str,
           labels: Optional[Dict[str, str]] = None) -> Pod:
    """Fresh per-pod identity on a shallow template copy (name, uid,
    labels, annotations); spec members stay shared with the template.
    The '_class_key' pop is LOAD-BEARING: a copied template would
    otherwise keep the template's memoized class key and silently
    misclassify every pod of the profile."""
    p.name = name
    p.uid = prefix + name
    p.labels = {} if labels is None else labels
    p.annotations = {}
    p.__dict__.pop("_class_key", None)
    return p


def _materialize_pods(template: Pod, names: List[str], namespace: str,
                      labels: Optional[List[Dict[str, str]]] = None
                      ) -> List[Pod]:
    """Shallow-copy `template` per name; per-pod identity fields (name,
    uid, labels, annotations) are fresh, spec members shared."""
    prefix = namespace + "/"
    cc = copy.copy
    return [_stamp(cc(template), nm, prefix,
                   labels[i] if labels is not None else None)
            for i, nm in enumerate(names)]


def hollow_nodes(n: int, seed: int = 0, heterogeneous: bool = False,
                 gpu_fraction: float = 0.0, taint_fraction: float = 0.0
                 ) -> List[Node]:
    """scheduler_perf node shape by default (scheduler_test.go:49-68).
    The homogeneous no-gpu/no-taint shape (every scale sweep point)
    materializes from one template columnar-style; heterogeneous/gpu/
    tainted clusters keep the per-node constructor (seeded rng per
    node — identical output to every prior round)."""
    if not heterogeneous and gpu_fraction == 0.0 and taint_fraction == 0.0:
        template = make_node("hollow-node-0", cpu=4000, memory=32 * Gi,
                             pods=110)
        out: List[Node] = []
        cc = copy.copy
        for i in range(n):
            node = cc(template)
            node.name = f"hollow-node-{i}"
            node.labels = {
                "kubernetes.io/hostname": node.name,
                "failure-domain.beta.kubernetes.io/zone":
                    ZONES[i % len(ZONES)],
            }
            out.append(node)
        return out
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        if heterogeneous:
            cpu = rng.choice([2000, 4000, 8000, 16000, 32000])
            mem = rng.choice([8, 16, 32, 64, 128]) * Gi
        else:
            cpu, mem = 4000, 32 * Gi
        gpu = 8 if rng.random() < gpu_fraction else 0
        taints = []
        if gpu and taint_fraction:
            taints.append(Taint("nvidia.com/gpu", "present", TaintEffect.NO_SCHEDULE))
        elif rng.random() < taint_fraction:
            taints.append(Taint("dedicated", "infra", TaintEffect.NO_SCHEDULE))
        labels = {
            "kubernetes.io/hostname": f"hollow-node-{i}",
            "failure-domain.beta.kubernetes.io/zone": ZONES[i % len(ZONES)],
        }
        if gpu:
            labels["accelerator"] = "nvidia"
        nodes.append(make_node(f"hollow-node-{i}", cpu=cpu, memory=mem, pods=110,
                               gpu=gpu, labels=labels, taints=taints))
    return nodes


def density_pods(n: int, seed: int = 0, namespace: str = "bench") -> List[Pod]:
    """Config 1: uniform small pods (the 'nginx' density workload —
    scheduler_perf creates pods with no requests; we give them the classic
    100m/500Mi shape so bin-packing is exercised). Columnar: one spec
    template, shallow-copied per name."""
    template = make_pod("density-0", namespace=namespace, cpu=100,
                        memory=500 * Mi)
    return _materialize_pods(template, [f"density-{i}" for i in range(n)],
                             namespace)


def binpack_pods(n: int, seed: int = 0, namespace: str = "bench") -> List[Pod]:
    """Config 2: mixed-size pods for PodFitsResources + BalancedResourceAllocation.
    Columnar: one template per shape, rng draws the shape sequence only."""
    rng = random.Random(seed)
    shapes = [(100, 128 * Mi), (250, 512 * Mi), (500, 1 * Gi), (1000, 2 * Gi),
              (2000, 4 * Gi)]
    templates = [make_pod(f"binpack-shape-{j}", namespace=namespace,
                          cpu=cpu, memory=mem)
                 for j, (cpu, mem) in enumerate(shapes)]
    prefix = namespace + "/"
    cc = copy.copy
    return [_stamp(cc(templates[rng.randrange(len(shapes))]),
                   f"binpack-{i}", prefix)
            for i in range(n)]


def affinity_pods(n: int, seed: int = 0, namespace: str = "bench") -> List[Pod]:
    """Config 3: selector/affinity-heavy (zone spreads via node selectors;
    inter-pod affinity lands when that kernel arrives)."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        pod = make_pod(f"affinity-{i}", namespace=namespace, cpu=100, memory=256 * Mi,
                       labels={"app": f"svc-{i % 20}"})
        if rng.random() < 0.5:
            pod.node_selector = {
                "failure-domain.beta.kubernetes.io/zone": rng.choice(ZONES)}
        out.append(pod)
    return out


HOSTNAME_KEY = "kubernetes.io/hostname"
ZONE_KEY = "failure-domain.beta.kubernetes.io/zone"


def mixed_affinity_pods(n: int, seed: int = 0,
                        namespace: str = "bench") -> List[Pod]:
    """ISSUE 3 headline mix: a density drain where required pod
    (anti-)affinity is a first-class share of the load instead of a
    corner case.

      15%  "one replica per host": required anti-affinity on the hostname
           key against the pod's own app label (6 apps) — the shape the
           wave path's per-topology occupancy counters absorb.
       2%  "pack into one zone": required affinity on the zone key against
           the pod's own app (4 apps) — zone domains span many nodes and
           the group bootstraps from nothing, so these route to the
           seeded strict tail, never the throughput path.
       5%  plain pods LABELED like the anti apps — anti-affinity TARGETS:
           their placements must respect the symmetry check against every
           committed iso pod (predicates.go:1146) per wave.
      78%  plain density pods (distinct app labels, no interactions).
    """
    # columnar: one template per (kind, app) — the Affinity objects are
    # shared per app (spec, read-only to every consumer)
    t_small = make_pod("mixed-t0", namespace=namespace, cpu=100,
                       memory=256 * Mi)
    t_big = make_pod("mixed-t1", namespace=namespace, cpu=100,
                     memory=500 * Mi)
    iso_aff = {}
    for a in range(6):
        app = f"iso-{a}"
        iso_aff[app] = Affinity(pod_anti_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": app}),
                namespaces=[], topology_key=HOSTNAME_KEY)]))
    pack_aff = {}
    for a in range(4):
        app = f"pack-{a}"
        pack_aff[app] = Affinity(pod_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": app}),
                namespaces=[], topology_key=ZONE_KEY)]))
    prefix = namespace + "/"
    out: List[Pod] = []
    cc = copy.copy
    for i in range(n):
        r = i % 100
        if r < 15:
            app = f"iso-{r % 6}"
            p = _stamp(cc(t_small), f"mixed-iso-{i}", prefix,
                       {"app": app})
            p.affinity = iso_aff[app]
        elif r < 17:
            app = f"pack-{i % 4}"
            p = _stamp(cc(t_small), f"mixed-pack-{i}", prefix,
                       {"app": app})
            p.affinity = pack_aff[app]
        elif r < 22:
            p = _stamp(cc(t_big), f"mixed-tgt-{i}", prefix,
                       {"app": f"iso-{r % 6}"})
        else:
            p = _stamp(cc(t_big), f"mixed-web-{i}", prefix,
                       {"app": f"web-{i % 8}"})
        out.append(p)
    return out


def churn_pods(n: int, seed: int = 0, namespace: str = "bench") -> List[Pod]:
    """ISSUE 8 churn-hardening mix: the density stream with enough
    affinity structure that node churn exercises every invalidation path
    instead of only capacity rows.

       6%  "one replica per host" anti-affinity pods (4 apps) — their
           topology views are what Protean delta-patching protects; a
           node kill mid-wave is what the liveness fence protects.
      10%  plain pods LABELED like the anti apps — anti-affinity TARGETS:
           their churn (binds, evictions) is the patchable foreign-event
           stream (a plain target entering/leaving a node patches one
           forbid row; it must NOT rebuild AffinityData wholesale).
      84%  plain density pods — the no-op patch majority.
    """
    t_small = make_pod("churn-t0", namespace=namespace, cpu=100,
                       memory=256 * Mi)
    t_big = make_pod("churn-t1", namespace=namespace, cpu=100,
                     memory=500 * Mi)
    anti_aff = {}
    for a in range(4):
        app = f"churn-iso-{a}"
        anti_aff[app] = Affinity(pod_anti_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": app}),
                namespaces=[], topology_key=HOSTNAME_KEY)]))
    prefix = namespace + "/"
    out: List[Pod] = []
    cc = copy.copy
    for i in range(n):
        r = i % 100
        if r < 6:
            app = f"churn-iso-{r % 4}"
            p = _stamp(cc(t_small), f"churn-anti-{i}", prefix,
                       {"app": app})
            p.affinity = anti_aff[app]
        elif r < 16:
            p = _stamp(cc(t_big), f"churn-tgt-{i}", prefix,
                       {"app": f"churn-iso-{r % 4}"})
        else:
            p = _stamp(cc(t_big), f"churn-web-{i}", prefix,
                       {"app": f"web-{i % 8}"})
        out.append(p)
    return out


# Borg-shaped priority bands (ISSUE 14): free/best-effort, batch,
# prod, system — the four-tier shape PAPERS.md §Borg describes. Values
# spread far apart so the bands are unambiguous in audits.
PRIORITY_BANDS = {"free": 0, "batch": 100, "prod": 1000, "system": 10000}


def priority_churn_pods(n: int, seed: int = 0,
                        namespace: str = "bench") -> List[Pod]:
    """ISSUE 14 overcommit mix: the arrival stream that makes
    displacement load-bearing. Offered against a deliberately
    UNDERSIZED cluster, the low bands fill it first and the high bands
    can only land by evicting — every preemption path (device victim
    scan, atomic evict+bind, victim requeue-and-age) runs at rate.

      45%  free (priority 0)      — the evictable floor; 200m/256Mi
      30%  batch (priority 100)   — evicts free when the cluster fills
      20%  prod (priority 1000)   — evicts batch and free
       5%  system (priority 10000) — evicts everything below

    Interleaved by index so bands arrive MIXED (a high-band pod is
    always chasing capacity the earlier low-band stream consumed).
    Columnar like every other profile: one template per band, shallow
    copies, priorities part of the spec class key."""
    templates = {}
    for band, prio in PRIORITY_BANDS.items():
        t = make_pod(f"prio-{band}-0", namespace=namespace, cpu=200,
                     memory=256 * Mi)
        t.priority = prio
        t.priority_class = band
        templates[band] = t
    prefix = namespace + "/"
    out: List[Pod] = []
    cc = copy.copy
    for i in range(n):
        r = i % 100
        if r < 45:
            band = "free"
        elif r < 75:
            band = "batch"
        elif r < 95:
            band = "prod"
        else:
            band = "system"
        p = _stamp(cc(templates[band]), f"prio-{band}-{i}", prefix,
                   {"band": band})
        out.append(p)
    return out


def hetero_gpu_pods(n: int, seed: int = 0, namespace: str = "bench") -> List[Pod]:
    """Config 5: GPU/extended-resource requests + tolerations on 10k
    heterogeneous nodes."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        if rng.random() < 0.3:
            pod = make_pod(f"hetero-{i}", namespace=namespace, cpu=1000,
                           memory=4 * Gi, gpu=rng.choice([1, 2, 4, 8]))
            pod.tolerations = [Toleration("nvidia.com/gpu",
                                          TolerationOperator.EXISTS, "", None)]
        else:
            pod = make_pod(f"hetero-{i}", namespace=namespace,
                           cpu=rng.choice([100, 500, 2000]),
                           memory=rng.choice([256 * Mi, 1 * Gi, 8 * Gi]))
        out.append(pod)
    return out


def gang_pods(n: int, seed: int = 0, namespace: str = "bench",
              gang_size: int = 8) -> List[Pod]:
    """BASELINE.json config 4: coscheduled batch jobs — n pods in gangs of
    `gang_size` (scheduling.k8s.io/group-name), all-or-nothing placement.
    Every ~16th gang is provably infeasible (one member requests more CPU
    than any node has) so atomic rollback is exercised, not just the happy
    path."""
    from kubernetes_tpu.engine.gang import (
        GANG_MIN_AVAILABLE_ANNOTATION,
        GANG_NAME_ANNOTATION,
    )
    out: List[Pod] = []
    rng = random.Random(seed)
    n_gangs = (n + gang_size - 1) // gang_size
    for g in range(n_gangs):
        infeasible = g % 16 == 15
        for m in range(min(gang_size, n - g * gang_size)):
            cpu = 100 if not (infeasible and m == 0) else 1_000_000
            pod = make_pod(f"gang-{g:04d}-{m:02d}", namespace=namespace,
                           cpu=cpu, memory=128 * Mi,
                           labels={"job": f"job-{g:04d}"})
            pod.annotations[GANG_NAME_ANNOTATION] = f"job-{g:04d}"
            pod.annotations[GANG_MIN_AVAILABLE_ANNOTATION] = str(
                min(gang_size, n - g * gang_size))
            out.append(pod)
    rng.shuffle(out)  # members arrive interleaved, like real job storms
    return out


def gang_mix_pods(n: int, seed: int = 0,
                  namespace: str = "bench") -> List[Pod]:
    """ISSUE 5 gang storm: ~20% of the pods arrive in 8–64-member gangs
    (scheduling.k8s.io/group-name with a FULL-SIZE quorum annotation — the
    strictest all-or-nothing contract); the rest is the `mixed_affinity`
    stream (hostname anti, zone co-location groups, symmetry targets,
    density). The blend is the point: when a gang-bearing chunk flushes
    the pipeline (the pre-ISSUE 5 routing), it drags the stream's
    affinity classes back through the CLASSIC path — per-chunk
    AffinityData rebuilds and the full-label-axis strict scan, the exact
    costs PROFILE_r08 measured as the PR-start collapse — so "gangs stop
    flushing" is worth far more than the gangs themselves. Every gang pod
    shares ONE spec class (annotations are identity, not spec —
    state/classes.pod_class_key), so the wave encoding's class axis stays
    flat no matter how many gangs ride a chunk; the shuffle interleaves
    members across arrival order, so gangs complete their quorum
    mid-drain and join whatever chunk releases them."""
    from kubernetes_tpu.engine.gang import (
        GANG_MIN_AVAILABLE_ANNOTATION,
        GANG_NAME_ANNOTATION,
    )
    rng = random.Random(seed)
    sizes = [8, 16, 32, 64]
    n_gang = n // 5
    out: List[Pod] = []
    g = 0
    i = 0
    while i < n_gang:
        size = min(sizes[g % len(sizes)], n_gang - i)
        for m in range(size):
            p = make_pod(f"gmix-gang-{g:04d}-{m:02d}", namespace=namespace,
                         cpu=100, memory=256 * Mi, labels={"app": "gangmix"})
            p.annotations[GANG_NAME_ANNOTATION] = f"gmix-{g:04d}"
            p.annotations[GANG_MIN_AVAILABLE_ANNOTATION] = str(size)
            out.append(p)
        i += size
        g += 1
    out.extend(mixed_affinity_pods(n - n_gang, seed=seed,
                                   namespace=namespace))
    rng.shuffle(out)  # members arrive interleaved, like real job storms
    return out


PROFILES = {
    "density": density_pods,
    "binpack": binpack_pods,
    "affinity": affinity_pods,
    "mixed_affinity": mixed_affinity_pods,
    "churn": churn_pods,
    "priority_churn": priority_churn_pods,
    "hetero": hetero_gpu_pods,
    "gang": gang_pods,
    "gang_mix": gang_mix_pods,
}


def load_cluster(api: ApiServerLite, nodes: List[Node], pods: List[Pod]) -> None:
    for node in nodes:
        api.create("Node", node)
    for pod in pods:
        api.create("Pod", pod)
