"""FlightRecorder: a bounded, preallocated ring of typed per-wave events.

Every PROFILE_r*.md since r06 has been hand-built attribution — the r13
churn dip needed a same-box HEAD-vs-PR A/B to blame box contention, the
r14 8-device p99 swing shipped as an "honesty data point" because
nothing recorded where a wave's milliseconds went. Borg's operability
rests on every task self-publishing health for introspection; Sparrow's
evaluation hinges on per-task latency decomposition (PAPERS.md). The
always-on engine gets both built in: the hot paths emit one typed event
per WAVE (never per pod) into a preallocated ring, and the exporter in
``perfetto.py`` renders the ring as a loadable timeline.

Cost model — the reason this can stay armed in production:

- OFF (the default): emit sites guard on ``RECORDER.enabled`` — one
  attribute load and a branch; ``record()`` is never called, no clock
  is read, nothing allocates. Exact no-op.
- ON: one uncontended lock acquire + six scalar writes into
  preallocated numpy arrays per event, at wave cadence (tens of events
  per second at the 20k pods/s headline). bench.py measures this as a
  recorder-on/off A/B on the arrival headline (telemetry_overhead_pct
  in the BENCH artifact) instead of asserting it.

The recorder is HOST-side pure: events carry monotonic timestamps and
host ints already in hand — it never touches a device value (fetching
one to "log" it would be exactly the GL002 hidden-sync hazard; the
graftlint fixture pins that the shipped shape stays silent and a
fetching variant fires).

Event kinds (the per-wave vocabulary of the pipelined engine):

    DISPATCH    one wave admitted + its fused eval launched async.
                wave=id, a=pods admitted, b=gangs riding; dur=dispatch
                host span (encode reuse, patch flush, upload).
    HARVEST     one wave's device→host sync + fence + assume. wave=id,
                a=pods bound, b=pods fenced (capacity+liveness);
                t stamps the device-block START, dur=the residual
                device block (pipeline.device_block) — so t+dur is the
                device-eval lane's right edge.
    FENCE_REQUEUE  the fence threw rows back. a=capacity conflicts,
                b=liveness requeues.
    PATCH       Protean delta invalidation absorbed churn into the
                cached encoding. a=foreign rows patched, b=label rows.
    BIND_FLUSH  one bulk bind write. wave=id (-1 on the classic
                round), a=pods bound, b=bind errors; dur=write span.
    DEGRADED    streaming loop mode transition. a=1 enter / 0 exit,
                b=breach streak at the flip.
    CHURN_OP    one injected churn op applied (testing/churn.py).
                a=op-kind code (CHURN_OP_CODES), b=1.
    PREEMPT_PROPOSE  one wave-path preemption round planned (ISSUE 14).
                wave=the harvested wave that surfaced the preemptors,
                a=preemptors considered, b=plans produced; dur=the
                planning span (device victim scan + exact verify).
    PREEMPT_COMMIT   one plan committed atomically at the store.
                wave=id, a=victims evicted, b=node row of the bind;
                dur=propose -> commit-complete (the preemption latency
                sample the bench percentiles).
    PREEMPT_ROLLBACK one plan refused/errored — nothing of it binds.
                wave=id, a=victims planned, b=1 when the error was the
                landed-timeout ambiguity's injected shape (0 plain).
    VICTIM_REQUEUE   a commit's victims re-entered the pending pool.
                wave=id, a=victim count, b=lowest victim priority.
    SLO_ALERT   the SLO engine's multiwindow burn-rate alert flipped
                (ISSUE 15). a=1 enter / 0 exit, b=fast-window burn rate
                x100 at the flip — the page lands on the same timeline
                as the waves that caused it.
    FASTLANE    one fast-lane pod bound through the sampled-eval path
                (ISSUE 17). wave=-1 (the fast lane rides between
                waves), a=attempts used (1 = first sample won the
                fence), b=1 device eval / 0 host twin; dur=pop ->
                bind-complete — the sub-10 ms span itself.
"""

from __future__ import annotations

import os
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Dict, List

import numpy as np

# ------------------------------------------------------------ event kinds

DISPATCH = 0
HARVEST = 1
FENCE_REQUEUE = 2
PATCH = 3
BIND_FLUSH = 4
DEGRADED = 5
CHURN_OP = 6
PREEMPT_PROPOSE = 7
PREEMPT_COMMIT = 8
PREEMPT_ROLLBACK = 9
VICTIM_REQUEUE = 10
SLO_ALERT = 11
FASTLANE = 12

KIND_NAMES = ("dispatch", "harvest", "fence_requeue", "patch",
              "bind_flush", "degraded", "churn_op", "preempt_propose",
              "preempt_commit", "preempt_rollback", "victim_requeue",
              "slo_alert", "fastlane")

# churn-op kind -> small int for the CHURN_OP event's `a` field
CHURN_OP_CODES = {"kill": 0, "respawn": 1, "flap_down": 2, "flap_up": 3,
                  "cordon": 4, "uncordon": 5, "relabel": 6, "evict": 7}
CHURN_OP_NAMES = {v: k for k, v in CHURN_OP_CODES.items()}


class FlightRecorder:
    """Bounded ring of typed per-wave events, preallocated up front.

    Storage is six parallel numpy arrays (kind/wave/t0/dur/a/b) written
    under one lock — no allocation, no dict, no string per event. The
    ring overwrites oldest-first past ``capacity``; ``dropped`` counts
    what the window lost (never silent truncation)."""

    __slots__ = ("capacity", "enabled", "_lock", "_kind", "_wave", "_t0",
                 "_dur", "_a", "_b", "_total", "_wave_seq")

    def __init__(self, capacity: int = 0):
        if capacity <= 0:
            capacity = int(os.environ.get("GRAFT_FLIGHT_CAPACITY", 65536))
        self.capacity = max(int(capacity), 8)
        self.enabled = False
        self._lock = lockcheck.make_lock("FlightRecorder._lock")
        self._kind = np.zeros(self.capacity, dtype=np.int8)
        self._wave = np.zeros(self.capacity, dtype=np.int64)
        self._t0 = np.zeros(self.capacity, dtype=np.float64)
        self._dur = np.zeros(self.capacity, dtype=np.float64)
        self._a = np.zeros(self.capacity, dtype=np.int64)
        self._b = np.zeros(self.capacity, dtype=np.int64)
        self._total = 0
        self._wave_seq = 0

    # ------------------------------------------------------------ control

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._total = 0
            self._wave_seq = 0

    # ------------------------------------------------------------- record

    def next_wave(self) -> int:
        """Allocate a monotonically increasing wave id (dispatch calls
        this once per wave; harvest/bind-flush reference it)."""
        with self._lock:
            self._wave_seq += 1
            return self._wave_seq

    def record(self, kind: int, wave: int = -1, t0: float = 0.0,
               dur: float = 0.0, a: int = 0, b: int = 0) -> None:
        """Append one event. Callers pass timestamps they already hold
        (``time.monotonic`` is the ring's one timebase); when ``t0`` is
        0.0 the record instant is stamped here."""
        if t0 == 0.0:
            t0 = time.monotonic()
        with self._lock:
            i = self._total % self.capacity
            self._kind[i] = kind
            self._wave[i] = wave
            self._t0[i] = t0
            self._dur[i] = dur
            self._a[i] = a
            self._b[i] = b
            self._total += 1

    # ------------------------------------------------------------ reading

    def snapshot(self, last: int = 0) -> List[Dict]:
        """The ring's events as dicts, oldest→newest; ``last`` bounds the
        tail (0 = everything retained)."""
        with self._lock:
            n = min(self._total, self.capacity)
            start = self._total - n
            if last and last < n:
                start = self._total - last
                n = last
            idx = np.arange(start, start + n) % self.capacity
            kinds = self._kind[idx]
            waves = self._wave[idx]
            t0s = self._t0[idx]
            durs = self._dur[idx]
            a_s = self._a[idx]
            b_s = self._b[idx]
        return [{"kind": KIND_NAMES[int(k)], "wave": int(w),
                 "t": float(t), "dur": float(d), "a": int(a), "b": int(b)}
                for k, w, t, d, a, b in zip(kinds, waves, t0s, durs,
                                            a_s, b_s)]

    def stats(self) -> Dict[str, int]:
        """Ring health for the telemetry registry: totals, window loss,
        and the wave-id high-water mark."""
        with self._lock:
            return {"events": self._total,
                    "dropped": max(self._total - self.capacity, 0),
                    "capacity": self.capacity,
                    "enabled": int(self.enabled),
                    "wave_seq": self._wave_seq}


# process-wide ring, disabled unless armed: the emit sites in the
# engine/streaming/bind paths all guard on RECORDER.enabled.
# GRAFT_FLIGHT_RECORDER=1 arms it at import (the CLI and ad-hoc
# debugging knob; bench.py flips it programmatically for the A/B).
RECORDER = FlightRecorder()
if os.environ.get("GRAFT_FLIGHT_RECORDER", "0") == "1":
    RECORDER.enable()


__all__ = ["BIND_FLUSH", "CHURN_OP", "CHURN_OP_CODES", "CHURN_OP_NAMES",
           "DEGRADED", "DISPATCH", "FASTLANE", "FENCE_REQUEUE",
           "FlightRecorder",
           "HARVEST", "KIND_NAMES", "PATCH", "PREEMPT_COMMIT",
           "PREEMPT_PROPOSE", "PREEMPT_ROLLBACK", "RECORDER",
           "SLO_ALERT", "VICTIM_REQUEUE"]
