"""Bench-trajectory trend reader (ISSUE 15 satellite): the BENCH_r*.json
artifacts become a queryable trajectory instead of sixteen files a human
diffs by hand.

    python bench.py --trend
    python -m kubernetes_tpu.observability --trend [--root DIR]
                                                   [--band 0.30]

Reads every BENCH_r*.json under the repo root (the driver-written
{cmd, rc, parsed} shape and the bench's own artifacts alike) plus
PROGRESS.jsonl, renders a headline-metric trend table, and flags
regressions: the LATEST round's value against the nearest earlier round
carrying the same metric, beyond the documented ±30% box-noise band
(PROFILE_r10.md — the 2-core CI box moves knees ±30% run to run, so a
smaller delta is noise, a larger one is a finding). Exit status is the
CI contract: 0 clean, 1 when any headline metric regressed past the
band, 2 on usage/IO errors.

Pure stdlib — no jax import, safe to run anywhere (including the
lint-gate CI leg).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

# (artifact key, short label, direction) — direction "up" = bigger is
# better, "down" = smaller is better, None = informational only (never
# flags; overhead percentages swing sign with box noise)
HEADLINE_METRICS: Tuple[Tuple[str, str, Optional[str]], ...] = (
    ("value", "drain pods/s", "up"),
    ("arrival_sustained_pods_s", "arrival sust/s", "up"),
    ("arrival_p99_create_to_bound_ms", "arrival p99 ms", "down"),
    ("multi_frontend_pods_s", "fleet inproc/s", "up"),
    ("multi_frontend_binwire_pods_s", "fleet binwire/s", "up"),
    ("churn_vs_quiet", "churn/quiet", "up"),
    # ISSUE 16: aggregate scheduleOnes/s of the M-process fleet (the
    # multiproc_N scenarios) — absent before r18, the gate tolerates
    # missing history and starts enforcing from the first round it
    # appears in
    ("multiproc_pods_s", "multiproc agg/s", "up"),
    # ISSUE 17: the Sparrow fast tier's p99 create->bound and the bulk
    # stream's sustained fraction under mixed criticality — absent
    # before r19; the gate tolerates missing history like multiproc
    ("fastlane_p99_ms", "fastlane p99 ms", "down"),
    ("mixed_bulk_sustained", "mixed bulk frac", "up"),
    # ISSUE 18: the rolling-update scenario — update completion time and
    # the replacement pods' p99 create->bound on the loaded stream —
    # absent before r20; the gate tolerates missing history like
    # multiproc/fastlane
    ("rolling_update_completion_s", "rollout done s", "down"),
    ("rolling_replacement_p99_ms", "rollout p99 ms", "down"),
    ("telemetry_overhead_pct", "recorder ovh %", None),
    ("podtrace_overhead_pct", "podtrace ovh %", None),
    # ISSUE 20: the federation tier — aggregate nodes behind the front
    # door, router admission p99 on top of per-cell create->bound, and
    # pods spilled-then-bound under a cell brownout — absent before r21;
    # the gate tolerates missing history like multiproc/fastlane
    ("federation_agg_nodes", "fed agg nodes", "up"),
    ("federation_router_p99_ms", "fed router p99 ms", "down"),
    ("federation_spillover_bound", "fed spill bound", "up"),
)

NOISE_BAND = 0.30

# cpus-aware band (ISSUE 20 satellite): metrics whose level is set by
# how much housekeeping can OVERLAP the stream core, mapped to the
# artifact key carrying their same-box attribution A/B. On a 1-core box
# fault handling serializes behind the stream, so the churn ratio sits
# structurally lower than any multi-core bar — the r19/r20 0.37-0.39
# readings against the 2-core r11 0.66 were box shape, not code (the
# same-box placebo A/B in bench.measure_churn carries the attribution).
# A 1-core regression on these metrics is annotated and NOT gated,
# exactly like box_change — but ONLY when the round's artifact actually
# carries the attribution evidence; a bare 1-cpu drop still gates.
SINGLE_CORE_LENIENT = {"churn_vs_quiet": "churn_attribution"}


def load_rounds(root: str) -> List[Tuple[int, Dict]]:
    """Every BENCH_r<NN>.json under root as (round, parsed) — tolerant
    of both the driver shape ({"parsed": {...}}) and a bare dict."""
    out: List[Tuple[int, Dict]] = []
    for name in os.listdir(root):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if not m:
            continue
        try:
            with open(os.path.join(root, name), "r",
                      encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed", doc) if isinstance(doc, dict) else None
        if isinstance(parsed, dict):
            out.append((int(m.group(1)), parsed))
    out.sort()
    return out


def _metric(parsed: Dict, key: str) -> Optional[float]:
    v = parsed.get(key)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def round_cpus(parsed: Dict) -> Optional[int]:
    """The CPU count the round ran on: top-level ``cpus`` (every r19+
    scenario records it) with the r18 fallback (only the multiproc
    scenario disclosed the box shape back then)."""
    v = parsed.get("cpus")
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        mp = parsed.get("multiproc")
        v = mp.get("cpus") if isinstance(mp, dict) else None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return int(v)


def find_regressions(rounds: List[Tuple[int, Dict]],
                     band: float = NOISE_BAND) -> List[Dict]:
    """Latest round vs the nearest EARLIER round carrying each headline
    metric; a delta past the band in the bad direction is a
    regression. A regression whose two rounds ran on DIFFERENT CPU
    counts carries a ``box_change`` annotation (\"2 -> 1 cpus\") — the
    r18 churn_vs_quiet 0.45 \"dip\" was exactly this, a 2-core round
    compared against a 1-core one, not a code regression."""
    if len(rounds) < 2:
        return []
    latest_r, latest = rounds[-1]
    regs: List[Dict] = []
    for key, label, direction in HEADLINE_METRICS:
        if direction is None:
            continue
        cur = _metric(latest, key)
        if cur is None:
            continue
        prev = prev_r = prev_parsed = None
        for r, parsed in reversed(rounds[:-1]):
            prev = _metric(parsed, key)
            if prev is not None:
                prev_r, prev_parsed = r, parsed
                break
        if prev is None or prev == 0:
            continue
        bad = (cur < prev * (1.0 - band)) if direction == "up" \
            else (cur > prev * (1.0 + band))
        if bad:
            reg = {"metric": key, "label": label,
                   "round": latest_r, "vs_round": prev_r,
                   "current": cur, "previous": prev,
                   "ratio": round(cur / prev, 3),
                   "direction": direction}
            cur_cpus = round_cpus(latest)
            prev_cpus = round_cpus(prev_parsed)
            if cur_cpus is not None and prev_cpus is not None \
                    and cur_cpus != prev_cpus:
                reg["box_change"] = f"{prev_cpus} -> {cur_cpus} cpus"
            elif key in SINGLE_CORE_LENIENT and cur_cpus == 1 \
                    and isinstance(
                        latest.get(SINGLE_CORE_LENIENT[key]), dict):
                reg["single_core_band"] = (
                    "1-cpu box: housekeeping serializes behind the "
                    f"stream core — see {SINGLE_CORE_LENIENT[key]} "
                    "in the artifact")
            regs.append(reg)
    return regs


def render_table(rounds: List[Tuple[int, Dict]]) -> str:
    cols = [k for k, _l, _d in HEADLINE_METRICS
            if any(_metric(p, k) is not None for _r, p in rounds)]
    labels = {k: l for k, l, _d in HEADLINE_METRICS}
    head = ["round"] + [labels[k] for k in cols]
    body: List[List[str]] = []
    for r, parsed in rounds:
        row = [f"r{r:02d}"]
        for k in cols:
            v = _metric(parsed, k)
            row.append("-" if v is None else
                       (f"{v:.2f}" if abs(v) < 100 else f"{v:.0f}"))
        body.append(row)
    widths = [max(len(head[i]), *(len(row[i]) for row in body))
              for i in range(len(head))]
    lines = ["  ".join(h.rjust(w) for h, w in zip(head, widths))]
    for row in body:
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


def progress_summary(root: str) -> str:
    """One line per driver round from PROGRESS.jsonl (last entry wins):
    the repo-growth trajectory beside the perf one."""
    path = os.path.join(root, "PROGRESS.jsonl")
    if not os.path.exists(path):
        return ""
    last: Dict[int, Dict] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if isinstance(e, dict) and "round" in e:
                    last[int(e["round"])] = e
    except OSError:
        return ""
    if not last:
        return ""
    lines = ["progress (PROGRESS.jsonl, last sample per round):"]
    for r in sorted(last):
        e = last[r]
        lines.append(f"  round {r:2d}: loc={e.get('loc', '?')} "
                     f"commits={e.get('commits', '?')} "
                     f"turns={e.get('turns', '?')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench.py --trend",
        description="render the BENCH_r*.json headline trend and flag "
                    "regressions beyond the box-noise band (nonzero "
                    "exit: CI contract)")
    ap.add_argument("--root", default=None,
                    help="directory holding BENCH_r*.json + "
                         "PROGRESS.jsonl (default: the repo root)")
    ap.add_argument("--band", type=float, default=NOISE_BAND,
                    help="relative noise band (default 0.30 — the "
                         "documented 2-core box swing)")
    args = ap.parse_args(argv)
    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if not os.path.isdir(root):
        print(f"trend: no such directory {root}", file=sys.stderr)
        return 2
    rounds = load_rounds(root)
    if not rounds:
        print(f"trend: no BENCH_r*.json under {root}", file=sys.stderr)
        return 2
    print(render_table(rounds))
    prog = progress_summary(root)
    if prog:
        print(prog)
    regs = find_regressions(rounds, band=args.band)
    fatal = [g for g in regs
             if "box_change" not in g and "single_core_band" not in g]
    if regs:
        print(f"\nREGRESSIONS past the ±{args.band:.0%} band:")
        for g in regs:
            arrow = "v" if g["direction"] == "up" else "^"
            note = ""
            if "box_change" in g:
                # a box-shape change (the runner moved between CPU
                # shapes) explains the delta — report it, don't gate on
                # it (the r18 churn_vs_quiet lesson)
                note = f"  [box change: {g['box_change']} — not gated]"
            elif "single_core_band" in g:
                note = f"  [{g['single_core_band']} — not gated]"
            print(f"  {arrow} {g['label']} ({g['metric']}): "
                  f"r{g['round']:02d}={g['current']:.2f} vs "
                  f"r{g['vs_round']:02d}={g['previous']:.2f} "
                  f"(x{g['ratio']}){note}")
        if fatal:
            return 1
    print(f"\nno regressions past the ±{args.band:.0%} band "
          f"(latest r{rounds[-1][0]:02d} vs trajectory)")
    return 0


__all__ = ["HEADLINE_METRICS", "NOISE_BAND", "SINGLE_CORE_LENIENT",
           "find_regressions", "load_rounds", "main", "progress_summary",
           "render_table", "round_cpus"]
