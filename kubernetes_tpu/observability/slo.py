"""SLO burn-rate monitor (ISSUE 15): the engine notices its own p99
drifting before a human reads a PROFILE_r*.md.

Borg's SLO-driven operation is the model: an always-on service is
operated against an explicit objective, and the thing that pages is the
rate at which the ERROR BUDGET burns — not a raw threshold that flaps on
every slow minute. The objective here is the latency SLO the streaming
engine has carried since r10: a fraction ``target`` (default 99%) of
pods bind within ``budget_s`` (default the 250 ms micro-wave budget) of
first admission.

Mechanics (the multiwindow burn-rate discipline, SRE workbook ch.5):

- every bound pod's create->bound span is one observation — a span over
  budget consumes error budget, one under it does not. observe_batch
  rides the scheduler's existing per-wave latency list, so the SLO sees
  ALL pods, not the tracer's sampled subset;
- observations land in a preallocated ring of per-second buckets
  (good/bad counters + a bounded latency histogram per bucket), so
  memory is O(slow_window / bucket) regardless of offered rate and a
  scrape never walks samples;
- ``burn_fast`` / ``burn_slow`` = (bad fraction over the window) /
  (1 - target): burn 1.0 means exactly on budget, N means the budget
  burns N times too fast. The alert condition requires BOTH windows hot
  (fast >= alert_burn AND slow >= 1.0) — a single slow wave cannot
  page, a sustained regression cannot hide;
- alert state FLIPS are recorded on the flight-recorder ring
  (SLO_ALERT events) so the page lands on the same timeline as the
  waves that caused it, and counted in the span registry;
- ``p99_ms`` is the rolling fast-window p99 from the bucketed
  histograms (value resolution = the bucket ladder, ~sqrt(2) steps —
  an SLO gauge, not a bench number; the bench keeps its exact
  creator-stamped percentiles).

Served identically by HTTP ``/debug/slo``, the binary STATS verb and
``VerdictService.debug_snapshot`` (transport parity test-pinned), and
folded into every TelemetryRegistry snapshot as ``slo.*`` gauges.

Host-pure: observations are floats the scheduler already computed;
nothing here touches a device value (graftlint-pinned beside the
tracer).
"""

from __future__ import annotations

import os
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Dict, List, Optional

import numpy as np

from kubernetes_tpu.observability import recorder as flightrec
from kubernetes_tpu.observability.recorder import RECORDER


def _latency_edges() -> np.ndarray:
    # 1 ms .. ~23 s in sqrt(2) steps: fine enough that a p99 gauge moves
    # when the tail moves, coarse enough that a bucket row is 30 floats
    out = [0.001 * (2 ** (i / 2.0)) for i in range(30)]
    return np.asarray(out)


class SLOMonitor:
    """Rolling multiwindow latency-SLO engine over per-second buckets."""

    def __init__(self, budget_s: float = 0.0, target: float = 0.0,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0, bucket_s: float = 1.0,
                 alert_burn: float = 10.0, now=time.monotonic,
                 recorder=RECORDER):
        if budget_s <= 0:
            budget_s = float(os.environ.get("GRAFT_SLO_BUDGET_MS",
                                            250.0)) / 1e3
        if target <= 0:
            target = float(os.environ.get("GRAFT_SLO_TARGET", 0.99))
        self.budget_s = float(budget_s)
        self.target = min(max(float(target), 0.5), 0.9999)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.bucket_s = max(float(bucket_s), 1e-3)
        self.alert_burn = float(alert_burn)
        self.enabled = False
        self._now = now
        self._recorder = recorder
        self._edges = _latency_edges()
        n = int(self.slow_window_s / self.bucket_s) + 2
        self._n = n
        self._good = np.zeros(n, dtype=np.int64)
        self._bad = np.zeros(n, dtype=np.int64)
        self._hist = np.zeros((n, len(self._edges) + 1), dtype=np.int64)
        self._epoch = np.full(n, -1, dtype=np.int64)  # bucket epoch held
        self._lock = lockcheck.make_lock("SLOMonitor._lock")
        self.alert = False
        self.alerts_total = 0

    # ------------------------------------------------------------ control

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._good[:] = 0
            self._bad[:] = 0
            self._hist[:] = 0
            self._epoch[:] = -1
            self.alert = False
            self.alerts_total = 0

    # ------------------------------------------------------------ observe

    def observe_batch(self, values: List[float],
                      t: Optional[float] = None) -> None:
        """One wave's worth of create->bound spans (seconds). Vectorized:
        one searchsorted + one slot update per call, at wave cadence."""
        if not values:
            return
        now = self._now() if t is None else t
        arr = np.asarray(values, dtype=np.float64)
        idx = np.searchsorted(self._edges, arr, side="left")
        binned = np.bincount(idx, minlength=len(self._edges) + 1)
        bad = int((arr > self.budget_s).sum())
        epoch = int(now / self.bucket_s)
        slot = epoch % self._n
        with self._lock:
            if self._epoch[slot] != epoch:
                self._good[slot] = 0
                self._bad[slot] = 0
                self._hist[slot] = 0
                self._epoch[slot] = epoch
            self._good[slot] += len(values) - bad
            self._bad[slot] += bad
            self._hist[slot] += binned
            self._update_alert_locked(epoch)

    # --------------------------------------------------------------- math

    def _window_mask_locked(self, epoch: int, window_s: float):
        w = max(int(window_s / self.bucket_s), 1)
        return (self._epoch > epoch - w) & (self._epoch <= epoch)

    def _burn_locked(self, epoch: int, window_s: float) -> float:
        m = self._window_mask_locked(epoch, window_s)
        good = int(self._good[m].sum())
        bad = int(self._bad[m].sum())
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / (1.0 - self.target)

    def _p99_locked(self, epoch: int, window_s: float) -> float:
        m = self._window_mask_locked(epoch, window_s)
        hist = self._hist[m].sum(axis=0)
        total = int(hist.sum())
        if total == 0:
            return 0.0
        cum = np.cumsum(hist)
        i = int(np.searchsorted(cum, max(int(0.99 * total), 1)))
        i = min(i, len(self._edges) - 1)
        return float(self._edges[i])

    def _update_alert_locked(self, epoch: int) -> None:
        fast = self._burn_locked(epoch, self.fast_window_s)
        slow = self._burn_locked(epoch, self.slow_window_s)
        hot = fast >= self.alert_burn and slow >= 1.0
        if hot == self.alert:
            return
        self.alert = hot
        if hot:
            self.alerts_total += 1
        from kubernetes_tpu.utils.trace import COUNTERS
        COUNTERS.inc("slo.alert_enter" if hot else "slo.alert_exit")
        if self._recorder.enabled:
            self._recorder.record(flightrec.SLO_ALERT,
                                  a=1 if hot else 0,
                                  b=int(min(fast, 1e6) * 100))

    # ------------------------------------------------------------ reading

    def snapshot(self) -> Dict[str, float]:
        """The /debug/slo payload — identical on every transport, and
        the slo.* gauge fold of every TelemetryRegistry snapshot."""
        epoch = int(self._now() / self.bucket_s)
        with self._lock:
            mf = self._window_mask_locked(epoch, self.fast_window_s)
            ms = self._window_mask_locked(epoch, self.slow_window_s)
            return {
                "enabled": int(self.enabled),
                "budget_ms": round(self.budget_s * 1e3, 3),
                "target": self.target,
                "alert_burn": self.alert_burn,
                "p99_ms": round(self._p99_locked(
                    epoch, self.fast_window_s) * 1e3, 3),
                "burn_fast": round(self._burn_locked(
                    epoch, self.fast_window_s), 4),
                "burn_slow": round(self._burn_locked(
                    epoch, self.slow_window_s), 4),
                "fast_good": int(self._good[mf].sum()),
                "fast_bad": int(self._bad[mf].sum()),
                "slow_good": int(self._good[ms].sum()),
                "slow_bad": int(self._bad[ms].sum()),
                "alert": int(self.alert),
                "alerts_total": self.alerts_total,
            }


# process-wide monitor, disabled unless armed (the scheduler's bound
# paths guard on SLO.enabled — exact no-op off). GRAFT_SLO=1 arms at
# import; bench.py arms it with the tracer for the podtrace A/B arm.
SLO = SLOMonitor()

# the fast tier's own objective (ISSUE 17): latency-critical pods are
# operated against a 10 ms budget, not the bulk 250 ms — per-tier burn
# rates so a bulk backlog can't hide a fast-lane regression (and vice
# versa). Armed by the same GRAFT_SLO knob; folded as slo.fast.* and
# served under "fast" in every /debug/slo payload.
SLO_FAST = SLOMonitor(
    budget_s=float(os.environ.get("GRAFT_SLO_FAST_BUDGET_MS", 10.0)) / 1e3,
    target=float(os.environ.get("GRAFT_SLO_FAST_TARGET", 0.99)))

if os.environ.get("GRAFT_SLO", "0") == "1":
    SLO.enable()
    SLO_FAST.enable()


__all__ = ["SLO", "SLO_FAST", "SLOMonitor"]
