"""CLI: record a drain under the flight recorder and export a timeline.

    python -m kubernetes_tpu.observability --trace out.json
    python -m kubernetes_tpu.observability --trace out.json \\
        --nodes 5000 --pods 30000 --profile density
    python -m kubernetes_tpu.observability --events raw.json --last 200
    python -m kubernetes_tpu.observability --vars
    python -m kubernetes_tpu.observability --trend [--band 0.30]

--trace runs the pipelined drain (warmup pass first so compiles never
pollute the window), records every wave, and writes the Chrome
trace-event JSON — load it in chrome://tracing or ui.perfetto.dev to
see the host-tail / device-eval overlap as lanes; with GRAFT_PODTRACE=1
the tracer's tail-exemplar pods render as additional per-pod phase
lanes. --events dumps the raw recorder ring instead; --vars prints a
telemetry-registry snapshot of the recorded run. --trend renders the
BENCH_r*.json headline trajectory and exits nonzero on a regression
past the box-noise band (observability/trend.py — the CI contract;
pure stdlib, runs without jax). Exit 0 on success, 1 on a trend
regression, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _record_drain(n_nodes: int, n_pods: int, profile: str, chunk: int,
                  overlap: bool, warm: bool):
    """One pipelined drain with the recorder armed; returns
    (events, elapsed_s, totals, scheduler)."""
    # persistent compile cache, same discipline as bench.py: set before
    # the first jax import traces a kernel
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache"))
    from kubernetes_tpu.engine.scheduler import Scheduler
    from kubernetes_tpu.models.hollow import (
        PROFILES,
        hollow_nodes,
        load_cluster,
    )
    from kubernetes_tpu.observability.recorder import RECORDER
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    def build():
        api = ApiServerLite(max_log=max(200_000, 3 * (n_nodes + n_pods)))
        load_cluster(api, hollow_nodes(n_nodes), PROFILES[profile](n_pods))
        sched = Scheduler(api, record_events=False)
        sched.start()
        return sched

    if warm:
        build().run_until_drained(max_batch=chunk, overlap=overlap)
    sched = build()
    RECORDER.clear()
    RECORDER.enable()
    try:
        t0 = time.monotonic()
        totals = sched.run_until_drained(max_batch=chunk, overlap=overlap)
        elapsed = time.monotonic() - t0
    finally:
        RECORDER.disable()
    return RECORDER.snapshot(), elapsed, totals, sched


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.observability",
        description="flight-recorder CLI: record a pipelined drain and "
                    "export a Perfetto/chrome://tracing timeline")
    ap.add_argument("--trace", metavar="OUT.json",
                    help="write the Chrome trace-event timeline here")
    ap.add_argument("--events", metavar="OUT.json",
                    help="dump the raw recorder ring here instead")
    ap.add_argument("--vars", action="store_true",
                    help="print a telemetry-registry snapshot of the run")
    ap.add_argument("--last", type=int, default=0,
                    help="bound the exported event tail (0 = all)")
    ap.add_argument("--nodes", type=int,
                    default=int(os.environ.get("BENCH_NODES", 5000)))
    ap.add_argument("--pods", type=int,
                    default=int(os.environ.get("BENCH_PODS", 30000)))
    ap.add_argument("--profile",
                    default=os.environ.get("BENCH_PROFILE", "density"))
    ap.add_argument("--chunk", type=int, default=0,
                    help="fixed wave size (0 = auto)")
    ap.add_argument("--no-overlap", action="store_true",
                    help="sequential debug mode (the lanes serialize)")
    ap.add_argument("--no-warm", action="store_true",
                    help="skip the warmup drain (compiles land in the "
                         "recorded window)")
    ap.add_argument("--trend", action="store_true",
                    help="render the BENCH_r*.json headline trend and "
                         "exit nonzero on a regression (no jax, no "
                         "drain)")
    ap.add_argument("--root", default=None,
                    help="trend: directory holding the artifacts")
    ap.add_argument("--band", type=float, default=None,
                    help="trend: relative noise band (default 0.30)")
    args = ap.parse_args(argv)
    if args.trend:
        from kubernetes_tpu.observability import trend
        targv = []
        if args.root:
            targv += ["--root", args.root]
        if args.band is not None:
            targv += ["--band", str(args.band)]
        return trend.main(targv)
    if not (args.trace or args.events or args.vars):
        ap.print_usage(sys.stderr)
        print("nothing to do: pass --trace, --events and/or --vars, "
              "or --trend", file=sys.stderr)
        return 2

    events, elapsed, totals, sched = _record_drain(
        args.nodes, args.pods, args.profile, args.chunk,
        overlap=not args.no_overlap, warm=not args.no_warm)
    if args.last:
        events = events[-args.last:]
    print(f"recorded {len(events)} events over {elapsed:.3f}s "
          f"(bound={totals['bound']}, "
          f"fence_requeued={totals.get('fence_requeued', 0)})",
          file=sys.stderr)
    if args.trace:
        from kubernetes_tpu.observability.perfetto import (
            add_pod_lanes,
            build_chrome_trace,
            overlap_seconds,
        )
        from kubernetes_tpu.observability.podtrace import TRACER
        trace = build_chrome_trace(events)
        n_pods = 0
        if TRACER.enabled:
            # tail-exemplar pod lanes (ISSUE 15): the slowest sampled
            # pods of the recorded drain, phase-decomposed, aligned to
            # the ring's time base so each pod overlays the waves it
            # actually crossed
            exemplars = TRACER.snapshot()["exemplars"]
            t_base = min((e["t"] for e in events), default=None)
            add_pod_lanes(trace, exemplars, t_base=t_base)
            n_pods = len(exemplars)
        with open(args.trace, "w", encoding="utf-8") as f:
            json.dump(trace, f)
            f.write("\n")
        hidden = overlap_seconds(events)
        print(f"wrote {args.trace}: {len(trace['traceEvents'])} trace "
              f"events ({n_pods} exemplar pod lanes), "
              f"{hidden * 1e3:.1f}ms of host work hidden under "
              f"device-eval windows", file=sys.stderr)
    if args.events:
        with open(args.events, "w", encoding="utf-8") as f:
            json.dump(events, f, indent=1)
            f.write("\n")
        print(f"wrote {args.events}", file=sys.stderr)
    if args.vars:
        # the scheduler's own registry: histograms + spans + any stream
        # gauges a loop registered during the run
        print(json.dumps(sched.telemetry.snapshot(), indent=1,
                         sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
