"""Flight recorder + unified telemetry (ISSUE 13).

Three pieces, all host-side pure (no jax import, no device fetches —
the graftlint GL002 fixture pins that the recorder never becomes a
hidden sync):

- ``recorder``: a bounded, preallocated ring of typed per-wave events
  (dispatch / harvest / fence-requeue / patch / bind-flush /
  degraded-transition / churn-op), wired through the engine's
  dispatch_waves/harvest_waves, the streaming loop, and both bind
  paths. Exact no-op when disabled; one lock + six scalar array writes
  per WAVE (not per pod) when on.
- ``registry``: the unified telemetry registry folding the span
  counters (utils/trace.py COUNTERS), SchedulerMetrics histograms,
  the ad-hoc service counter dicts, and live gauges (quantum, backlog,
  degraded state, commit/snapshot generations) into one labeled
  namespace with a single snapshot() and a single Prometheus render.
  Every introspection transport — HTTP ``/debug/vars``, the binary
  wire's STATS verb, ``VerdictService.debug_snapshot`` — serves THIS.
- ``perfetto``: a Chrome trace-event exporter rendering the recorder
  ring as host / device / fence lanes, so the pipeline-overlap
  attribution profile_bench.py approximates becomes a loadable
  timeline (``python -m kubernetes_tpu.observability --trace out.json``
  then chrome://tracing or ui.perfetto.dev).
"""

from kubernetes_tpu.observability.recorder import RECORDER, FlightRecorder
from kubernetes_tpu.observability.registry import TelemetryRegistry

__all__ = ["FlightRecorder", "RECORDER", "TelemetryRegistry"]
