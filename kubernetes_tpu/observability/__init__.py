"""Flight recorder + unified telemetry (ISSUE 13).

Three pieces, all host-side pure (no jax import, no device fetches —
the graftlint GL002 fixture pins that the recorder never becomes a
hidden sync):

- ``recorder``: a bounded, preallocated ring of typed per-wave events
  (dispatch / harvest / fence-requeue / patch / bind-flush /
  degraded-transition / churn-op), wired through the engine's
  dispatch_waves/harvest_waves, the streaming loop, and both bind
  paths. Exact no-op when disabled; one lock + six scalar array writes
  per WAVE (not per pod) when on.
- ``registry``: the unified telemetry registry folding the span
  counters (utils/trace.py COUNTERS), SchedulerMetrics histograms,
  the ad-hoc service counter dicts, and live gauges (quantum, backlog,
  degraded state, commit/snapshot generations) into one labeled
  namespace with a single snapshot() and a single Prometheus render.
  Every introspection transport — HTTP ``/debug/vars``, the binary
  wire's STATS verb, ``VerdictService.debug_snapshot`` — serves THIS.
- ``perfetto``: a Chrome trace-event exporter rendering the recorder
  ring as host / device / fence lanes, so the pipeline-overlap
  attribution profile_bench.py approximates becomes a loadable
  timeline (``python -m kubernetes_tpu.observability --trace out.json``
  then chrome://tracing or ui.perfetto.dev).

Pod-level black box (ISSUE 15), same host-pure discipline:

- ``podtrace``: head-sampled per-pod lifecycle timelines stamped at
  the queue/dispatch/harvest/fence/bind/preempt seams and joined
  across transports by a trace context; completion feeds a telescoping
  critical-path decomposition (phase sums == create->bound exactly)
  and a slowest-K tail-exemplar reservoir per window.
- ``slo``: the multiwindow burn-rate SLO engine over every bound pod's
  create->bound span — rolling p99, fast/slow burn gauges, alert flips
  on the flight-recorder ring. Both serve identically on every
  transport (HTTP /debug/pods + /debug/slo, the binary STATS verb,
  VerdictService.debug_snapshot) and fold into every registry
  snapshot.
- ``trend``: the BENCH_r*.json trajectory reader behind
  ``bench.py --trend`` (regression flags past the box-noise band,
  nonzero exit for CI).
"""

from kubernetes_tpu.observability.podtrace import TRACER, PodTracer
from kubernetes_tpu.observability.recorder import RECORDER, FlightRecorder
from kubernetes_tpu.observability.registry import TelemetryRegistry
from kubernetes_tpu.observability.slo import SLO, SLOMonitor

__all__ = ["FlightRecorder", "PodTracer", "RECORDER", "SLO",
           "SLOMonitor", "TRACER", "TelemetryRegistry"]
