"""Chrome trace-event (Perfetto) exporter for the flight recorder ring.

Renders the recorder's per-wave events as a timeline with three lanes —
the attribution profile_bench.py approximates with wrapped functions
becomes a picture you load in chrome://tracing or ui.perfetto.dev:

- **host** lane: per-wave dispatch spans (encode reuse, patch flush,
  upload) and bind-flush spans (the bulk write + result tail) — the
  host tail wave k+1's device time is supposed to hide.
- **device** lane: per-wave device-eval windows, reconstructed from the
  recorder's own stamps as [dispatch end → harvest block end] — exactly
  the async window JAX owns the wave for. With the pipeline two deep,
  wave k+1's device span visibly overlaps wave k's bind-flush on the
  host lane; in `overlap=False` debug mode the lanes serialize. That
  picture IS the r14 overlap attribution, automated.
- **fence** lane: instant markers for fence requeues, Protean patches,
  degraded-mode transitions and churn ops — the churn story lands on
  the same time axis as the waves it perturbed.

Format: the Chrome trace-event JSON object form ({"traceEvents": [...]})
with "X" complete events for spans, "i" instants for markers, and "M"
metadata naming the process/threads. Timestamps are microseconds
relative to the first event (monotonic origin is arbitrary anyway).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from kubernetes_tpu.observability import recorder as rec

PID = 1
TID_HOST = 1
TID_DEVICE = 2
TID_FENCE = 3
TID_PREEMPT = 4

_THREADS = ((TID_HOST, "host"), (TID_DEVICE, "device"),
            (TID_FENCE, "fence"), (TID_PREEMPT, "preempt"))


def build_chrome_trace(events: List[Dict]) -> Dict:
    """Recorder snapshot (``RECORDER.snapshot()``) → Chrome trace dict."""
    out: List[Dict] = [
        {"ph": "M", "pid": PID, "name": "process_name",
         "args": {"name": "tpu-sched engine"}},
    ]
    for tid, name in _THREADS:
        out.append({"ph": "M", "pid": PID, "tid": tid,
                    "name": "thread_name", "args": {"name": name}})
    if not events:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t_base = min(e["t"] for e in events)

    def us(t: float) -> float:
        return round((t - t_base) * 1e6, 1)

    # device lane windows need the dispatch/harvest pair per wave id
    dispatch_end: Dict[int, float] = {}
    for e in events:
        kind = e["kind"]
        if kind == "dispatch":
            dispatch_end[e["wave"]] = e["t"] + e["dur"]
            out.append({"ph": "X", "pid": PID, "tid": TID_HOST,
                        "name": f"dispatch w{e['wave']}",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"pods": e["a"], "gangs": e["b"]}})
        elif kind == "harvest":
            block_end = e["t"] + e["dur"]
            start = dispatch_end.get(e["wave"], e["t"])
            out.append({"ph": "X", "pid": PID, "tid": TID_DEVICE,
                        "name": f"device-eval w{e['wave']}",
                        "ts": us(start),
                        "dur": max(round((block_end - start) * 1e6, 1),
                                   0.1),
                        "args": {"bound": e["a"], "fenced": e["b"],
                                 "residual_block_ms":
                                     round(e["dur"] * 1e3, 3)}})
        elif kind == "bind_flush":
            out.append({"ph": "X", "pid": PID, "tid": TID_HOST,
                        "name": f"bind-flush w{e['wave']}"
                        if e["wave"] >= 0 else "bind-flush (classic)",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"bound": e["a"], "bind_errors": e["b"]}})
        elif kind == "fence_requeue":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "t",
                        "name": f"fence-requeue w{e['wave']}",
                        "ts": us(e["t"]),
                        "args": {"conflicts": e["a"],
                                 "liveness": e["b"]}})
        elif kind == "patch":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "t",
                        "name": "patch", "ts": us(e["t"]),
                        "args": {"foreign_rows": e["a"],
                                 "label_rows": e["b"]}})
        elif kind == "degraded":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "p",
                        "name": "degraded-enter" if e["a"]
                        else "degraded-exit",
                        "ts": us(e["t"]),
                        "args": {"breach_streak": e["b"]}})
        elif kind == "churn_op":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "t",
                        "name": "churn:" + rec.CHURN_OP_NAMES.get(
                            e["a"], str(e["a"])),
                        "ts": us(e["t"]), "args": {}})
        elif kind == "preempt_propose":
            # victim selection as a SPAN on its own lane (ISSUE 14): the
            # device scan + exact verify shows on the timeline next to
            # the harvest that surfaced the preemptors
            out.append({"ph": "X", "pid": PID, "tid": TID_PREEMPT,
                        "name": f"victim-select w{e['wave']}",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"preemptors": e["a"], "plans": e["b"]}})
        elif kind == "preempt_commit":
            out.append({"ph": "X", "pid": PID, "tid": TID_PREEMPT,
                        "name": f"preempt-commit w{e['wave']}",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"victims": e["a"], "node_row": e["b"]}})
        elif kind == "preempt_rollback":
            out.append({"ph": "i", "pid": PID, "tid": TID_PREEMPT,
                        "s": "t", "name": f"preempt-rollback w{e['wave']}",
                        "ts": us(e["t"]),
                        "args": {"victims_planned": e["a"],
                                 "landed_timeout": e["b"]}})
        elif kind == "victim_requeue":
            out.append({"ph": "i", "pid": PID, "tid": TID_PREEMPT,
                        "s": "t", "name": f"victim-requeue w{e['wave']}",
                        "ts": us(e["t"]),
                        "args": {"victims": e["a"],
                                 "lowest_priority": e["b"]}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(events: List[Dict], path: str) -> Dict:
    """Write the Chrome trace JSON for a recorder snapshot; returns the
    trace dict (tests assert on lanes/overlap without re-reading)."""
    trace = build_chrome_trace(events)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    return trace


def overlap_seconds(events: List[Dict]) -> float:
    """Total host-work seconds hidden under device-eval windows — the
    quantitative half of the overlap picture (the r14 attribution as a
    number): sum over host spans of their intersection with device-eval
    windows of OTHER waves. O(n log n): host spans intersect the merged
    union of device windows, minus the same wave's own window (one batch
    owns the device at a time, so a wave's window overlapping another
    wave's is negligible — and a full-ring export must not pay an
    all-pairs Python loop over tens of thousands of spans)."""
    import bisect

    device: List = []
    host: List = []
    dispatch_end: Dict[int, float] = {}
    dev_by_wave: Dict[int, tuple] = {}
    for e in events:
        if e["kind"] == "dispatch":
            dispatch_end[e["wave"]] = e["t"] + e["dur"]
            host.append((e["t"], e["t"] + e["dur"], e["wave"]))
        elif e["kind"] == "harvest":
            start = dispatch_end.get(e["wave"], e["t"])
            device.append((start, e["t"] + e["dur"]))
            dev_by_wave[e["wave"]] = (start, e["t"] + e["dur"])
        elif e["kind"] == "bind_flush":
            host.append((e["t"], e["t"] + e["dur"], e["wave"]))
    if not device or not host:
        return 0.0
    # merged union of device windows + prefix lengths for O(log n) probes
    device.sort()
    merged = [list(device[0])]
    for d0, d1 in device[1:]:
        if d0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], d1)
        else:
            merged.append([d0, d1])
    starts = [m[0] for m in merged]
    prefix = [0.0]
    for m0, m1 in merged:
        prefix.append(prefix[-1] + (m1 - m0))

    def measure_upto(x: float) -> float:
        """Union length of the merged device windows within (-inf, x]."""
        k = bisect.bisect_right(starts, x) - 1
        if k < 0:
            return 0.0
        m0, m1 = merged[k]
        return prefix[k] + min(max(x - m0, 0.0), m1 - m0)

    total = 0.0
    for h0, h1, hw in host:
        covered = measure_upto(h1) - measure_upto(h0)
        own = dev_by_wave.get(hw)
        if own is not None:
            covered -= max(min(h1, own[1]) - max(h0, own[0]), 0.0)
        total += min(max(covered, 0.0), h1 - h0)
    return total


__all__ = ["build_chrome_trace", "export_chrome_trace", "overlap_seconds"]
