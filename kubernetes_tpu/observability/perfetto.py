"""Chrome trace-event (Perfetto) exporter for the flight recorder ring.

Renders the recorder's per-wave events as a timeline with three lanes —
the attribution profile_bench.py approximates with wrapped functions
becomes a picture you load in chrome://tracing or ui.perfetto.dev:

- **host** lane: per-wave dispatch spans (encode reuse, patch flush,
  upload) and bind-flush spans (the bulk write + result tail) — the
  host tail wave k+1's device time is supposed to hide.
- **device** lane: per-wave device-eval windows, reconstructed from the
  recorder's own stamps as [dispatch end → harvest block end] — exactly
  the async window JAX owns the wave for. With the pipeline two deep,
  wave k+1's device span visibly overlaps wave k's bind-flush on the
  host lane; in `overlap=False` debug mode the lanes serialize. That
  picture IS the r14 overlap attribution, automated.
- **fence** lane: instant markers for fence requeues, Protean patches,
  degraded-mode transitions, churn ops and SLO-alert flips — the churn
  story lands on the same time axis as the waves it perturbed.

Flow arrows (ISSUE 15 satellite): every wave's dispatch → device-eval →
bind-flush chain carries Chrome flow events (``ph`` s/t/f with the wave
id), so following one wave across the host and device lanes is a click,
not a visual scan; the span events carry ``span_ms`` args alongside
their pod counts.

Pod lanes (ISSUE 15): ``add_pod_lanes`` renders the tracer's slowest-K
tail exemplars as one lane per pod — each consecutive-event delta drawn
as a phase span (the SAME labels as podtrace.decompose, so the picture
and the window aggregate can never disagree), wire hops and fence
requeues as instants.

Format: the Chrome trace-event JSON object form ({"traceEvents": [...]})
with "X" complete events for spans, "i" instants for markers, and "M"
metadata naming the process/threads. Timestamps are microseconds
relative to the first event (monotonic origin is arbitrary anyway).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from kubernetes_tpu.observability import recorder as rec

PID = 1
TID_HOST = 1
TID_DEVICE = 2
TID_FENCE = 3
TID_PREEMPT = 4
TID_FASTLANE = 5

_THREADS = ((TID_HOST, "host"), (TID_DEVICE, "device"),
            (TID_FENCE, "fence"), (TID_PREEMPT, "preempt"),
            (TID_FASTLANE, "fastlane"))


def build_chrome_trace(events: List[Dict]) -> Dict:
    """Recorder snapshot (``RECORDER.snapshot()``) → Chrome trace dict."""
    out: List[Dict] = [
        {"ph": "M", "pid": PID, "name": "process_name",
         "args": {"name": "tpu-sched engine"}},
    ]
    for tid, name in _THREADS:
        out.append({"ph": "M", "pid": PID, "tid": tid,
                    "name": "thread_name", "args": {"name": name}})
    if not events:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t_base = min(e["t"] for e in events)

    def us(t: float) -> float:
        return round((t - t_base) * 1e6, 1)

    # device lane windows need the dispatch/harvest pair per wave id;
    # the flow arrows (dispatch → device-eval → bind-flush of one wave)
    # need an anchor instant inside each span
    dispatch_end: Dict[int, float] = {}
    flow_anchor: Dict[int, List] = {}  # wave -> [(tid, ts_us), ...]
    for e in events:
        kind = e["kind"]
        if kind == "dispatch":
            dispatch_end[e["wave"]] = e["t"] + e["dur"]
            out.append({"ph": "X", "pid": PID, "tid": TID_HOST,
                        "name": f"dispatch w{e['wave']}",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"pods": e["a"], "gangs": e["b"],
                                 "span_ms": round(e["dur"] * 1e3, 3)}})
            flow_anchor.setdefault(e["wave"], []).append(
                (TID_HOST, us(e["t"])))
        elif kind == "harvest":
            block_end = e["t"] + e["dur"]
            start = dispatch_end.get(e["wave"], e["t"])
            out.append({"ph": "X", "pid": PID, "tid": TID_DEVICE,
                        "name": f"device-eval w{e['wave']}",
                        "ts": us(start),
                        "dur": max(round((block_end - start) * 1e6, 1),
                                   0.1),
                        "args": {"bound": e["a"], "fenced": e["b"],
                                 "span_ms": round((block_end - start)
                                                  * 1e3, 3),
                                 "residual_block_ms":
                                     round(e["dur"] * 1e3, 3)}})
            flow_anchor.setdefault(e["wave"], []).append(
                (TID_DEVICE, us(start)))
        elif kind == "bind_flush":
            out.append({"ph": "X", "pid": PID, "tid": TID_HOST,
                        "name": f"bind-flush w{e['wave']}"
                        if e["wave"] >= 0 else "bind-flush (classic)",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"bound": e["a"], "bind_errors": e["b"],
                                 "span_ms": round(e["dur"] * 1e3, 3)}})
            if e["wave"] >= 0:
                flow_anchor.setdefault(e["wave"], []).append(
                    (TID_HOST, us(e["t"])))
        elif kind == "fence_requeue":
            if e["wave"] < 0:
                # wire fence conflict (ISSUE 16): no wave owns it — a
                # remote scheduler process raced the bind fence and
                # lost; b carries the typed reason code
                from kubernetes_tpu.observability import podtrace as pt
                rn = pt.REASON_NAMES[e["b"]] \
                    if 0 <= e["b"] < len(pt.REASON_NAMES) else str(e["b"])
                out.append({"ph": "i", "pid": PID, "tid": TID_FENCE,
                            "s": "t", "name": f"fence-conflict:{rn}",
                            "ts": us(e["t"]),
                            "args": {"conflicts": e["a"],
                                     "reason": rn}})
            else:
                out.append({"ph": "i", "pid": PID, "tid": TID_FENCE,
                            "s": "t",
                            "name": f"fence-requeue w{e['wave']}",
                            "ts": us(e["t"]),
                            "args": {"conflicts": e["a"],
                                     "liveness": e["b"]}})
        elif kind == "patch":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "t",
                        "name": "patch", "ts": us(e["t"]),
                        "args": {"foreign_rows": e["a"],
                                 "label_rows": e["b"]}})
        elif kind == "degraded":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "p",
                        "name": "degraded-enter" if e["a"]
                        else "degraded-exit",
                        "ts": us(e["t"]),
                        "args": {"breach_streak": e["b"]}})
        elif kind == "churn_op":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "t",
                        "name": "churn:" + rec.CHURN_OP_NAMES.get(
                            e["a"], str(e["a"])),
                        "ts": us(e["t"]), "args": {}})
        elif kind == "preempt_propose":
            # victim selection as a SPAN on its own lane (ISSUE 14): the
            # device scan + exact verify shows on the timeline next to
            # the harvest that surfaced the preemptors
            out.append({"ph": "X", "pid": PID, "tid": TID_PREEMPT,
                        "name": f"victim-select w{e['wave']}",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"preemptors": e["a"], "plans": e["b"]}})
        elif kind == "preempt_commit":
            out.append({"ph": "X", "pid": PID, "tid": TID_PREEMPT,
                        "name": f"preempt-commit w{e['wave']}",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"victims": e["a"], "node_row": e["b"]}})
        elif kind == "preempt_rollback":
            out.append({"ph": "i", "pid": PID, "tid": TID_PREEMPT,
                        "s": "t", "name": f"preempt-rollback w{e['wave']}",
                        "ts": us(e["t"]),
                        "args": {"victims_planned": e["a"],
                                 "landed_timeout": e["b"]}})
        elif kind == "victim_requeue":
            out.append({"ph": "i", "pid": PID, "tid": TID_PREEMPT,
                        "s": "t", "name": f"victim-requeue w{e['wave']}",
                        "ts": us(e["t"]),
                        "args": {"victims": e["a"],
                                 "lowest_priority": e["b"]}})
        elif kind == "fastlane":
            # one span per fast-lane pod, pop → bind-complete (ISSUE 17):
            # the sub-10ms tier gets its own lane so its spans read
            # against the micro-waves they threaded between; a is the
            # attempts consumed, b the eval route (1 device, 0 host twin)
            out.append({"ph": "X", "pid": PID, "tid": TID_FASTLANE,
                        "name": "fast-bind",
                        "ts": us(e["t"]), "dur": round(e["dur"] * 1e6, 1),
                        "args": {"attempts": e["a"],
                                 "eval": "device" if e["b"] else "host",
                                 "span_ms": round(e["dur"] * 1e3, 3)}})
        elif kind == "slo_alert":
            out.append({"ph": "i", "pid": PID, "tid": TID_FENCE, "s": "p",
                        "name": "slo-alert-enter" if e["a"]
                        else "slo-alert-exit",
                        "ts": us(e["t"]),
                        "args": {"burn_fast_x100": e["b"]}})
    # flow arrows: one chain per wave through its recorded stages, in
    # stage order (dispatch → device-eval → bind-flush). Chrome binds a
    # flow event to the slice enclosing (tid, ts), so each anchor is the
    # span's own start instant.
    for wave, anchors in sorted(flow_anchor.items()):
        if len(anchors) < 2:
            continue
        for i, (tid, ts) in enumerate(anchors):
            ph = "s" if i == 0 else ("f" if i == len(anchors) - 1
                                     else "t")
            ev = {"ph": ph, "pid": PID, "tid": tid, "cat": "wave",
                  "id": wave, "name": f"wave w{wave}", "ts": ts}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# pod-exemplar lane tids start far above the fixed lanes
TID_POD_BASE = 16

# scheduler-process lane tids: above the pod lanes (a trace with both
# keeps 240 pod exemplars before the ranges could meet)
TID_PROC_BASE = 256


def add_process_lanes(trace: Dict, workers: List[Dict],
                      base_tid: int = TID_PROC_BASE,
                      t_base: Optional[float] = None) -> Dict:
    """Append one lane per scheduler PROCESS (ISSUE 16) to a built
    trace: a ``run_process_fleet`` worker result renders its binds and
    relists as spans and its fence conflicts as instant markers.

    ``t_base`` is the server RING's time origin (min event t of the
    main lanes): worker event stamps are CLOCK_MONOTONIC, which is
    system-wide on Linux, so with the ring's t_base each process lane
    aligns with the fence-conflict instants the shared cell recorded
    for it. Without it the lanes align against the earliest worker
    event (self-consistent across processes, but not ring-aligned).
    Returns the trace for chaining."""
    out = trace["traceEvents"]
    if t_base is None:
        t_base = min((ev["t"] for w in workers
                      for ev in w.get("events", ())), default=0.0)
    for lane, w in enumerate(workers):
        tid = base_tid + lane
        wid = w.get("worker", lane)
        c = w.get("counts", {})
        out.append({"ph": "M", "pid": PID, "tid": tid,
                    "name": "thread_name",
                    "args": {"name": f"sched-proc {wid} "
                                     f"({c.get('binds', 0)} binds, "
                                     f"{c.get('conflicts', 0)} "
                                     f"conflicts)"}})
        for ev in w.get("events", ()):
            ts = round((ev["t"] - t_base) * 1e6, 1)
            if ev["kind"] == "conflict":
                out.append({"ph": "i", "pid": PID, "tid": tid, "s": "t",
                            "name": "fence-conflict:"
                                    + ev.get("reason", "?"),
                            "ts": ts,
                            "args": {k: v for k, v in ev.items()
                                     if k not in ("kind", "t", "dur")}})
            else:  # bind / relist: work spans on the process timeline
                out.append({"ph": "X", "pid": PID, "tid": tid,
                            "name": ev["kind"], "ts": ts,
                            "dur": max(round(ev.get("dur", 0.0) * 1e6,
                                             1), 0.1),
                            "args": {k: v for k, v in ev.items()
                                     if k not in ("kind", "t", "dur")}})
    return trace


def add_pod_lanes(trace: Dict, exemplars: List[Dict],
                  base_tid: int = TID_POD_BASE,
                  t_base: Optional[float] = None) -> Dict:
    """Append one lane per tail-exemplar pod (podtrace snapshot
    ``exemplars`` entries) to a built trace: consecutive-event deltas as
    phase spans labeled EXACTLY like podtrace.decompose, instants for
    the zero-width stamps. ``t_base`` is the RING's time origin (the
    min event t the main lanes were rendered against) so a pod's lane
    aligns with the waves it actually crossed; without it the lanes
    align against the earliest exemplar instead (self-consistent, but
    not wave-aligned). Returns the trace for chaining."""
    from kubernetes_tpu.observability import podtrace as pt
    kind_code = {nm: i for i, nm in enumerate(pt.KIND_NAMES)}
    out = trace["traceEvents"]
    if t_base is None:
        t_base = min((ex.get("t0", 0.0) for ex in exemplars),
                     default=0.0)
    for lane, ex in enumerate(exemplars):
        tid = base_tid + lane
        off_us = round((ex.get("t0", t_base) - t_base) * 1e6, 1)
        out.append({"ph": "M", "pid": PID, "tid": tid,
                    "name": "thread_name",
                    "args": {"name": f"pod {ex['key']} "
                                     f"({ex['span_ms']:.1f}ms)"}})
        evs = ex["events"]
        requeued = False
        for i in range(1, len(evs)):
            prev, cur = evs[i - 1], evs[i]
            pk = kind_code.get(prev["kind"], -1)
            ck = kind_code.get(cur["kind"], -1)
            ph = pt.phase_of(pk, ck, requeued)
            if ck == pt.FENCE_REQUEUED:
                requeued = True
            out.append({"ph": "X", "pid": PID, "tid": tid, "name": ph,
                        "ts": round(off_us + prev["t_ms"] * 1e3, 1),
                        "dur": max(round((cur["t_ms"] - prev["t_ms"])
                                         * 1e3, 1), 0.1),
                        "args": {"to": cur["kind"], "a": cur["a"],
                                 "b": cur["b"]}})
        for ev in evs:
            out.append({"ph": "i", "pid": PID, "tid": tid, "s": "t",
                        "name": ev["kind"],
                        "ts": round(off_us + ev["t_ms"] * 1e3, 1),
                        "args": {"a": ev["a"], "b": ev["b"]}})
    return trace


def export_chrome_trace(events: List[Dict], path: str) -> Dict:
    """Write the Chrome trace JSON for a recorder snapshot; returns the
    trace dict (tests assert on lanes/overlap without re-reading)."""
    trace = build_chrome_trace(events)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    return trace


def overlap_seconds(events: List[Dict]) -> float:
    """Total host-work seconds hidden under device-eval windows — the
    quantitative half of the overlap picture (the r14 attribution as a
    number): sum over host spans of their intersection with device-eval
    windows of OTHER waves. O(n log n): host spans intersect the merged
    union of device windows, minus the same wave's own window (one batch
    owns the device at a time, so a wave's window overlapping another
    wave's is negligible — and a full-ring export must not pay an
    all-pairs Python loop over tens of thousands of spans)."""
    import bisect

    device: List = []
    host: List = []
    dispatch_end: Dict[int, float] = {}
    dev_by_wave: Dict[int, tuple] = {}
    for e in events:
        if e["kind"] == "dispatch":
            dispatch_end[e["wave"]] = e["t"] + e["dur"]
            host.append((e["t"], e["t"] + e["dur"], e["wave"]))
        elif e["kind"] == "harvest":
            start = dispatch_end.get(e["wave"], e["t"])
            device.append((start, e["t"] + e["dur"]))
            dev_by_wave[e["wave"]] = (start, e["t"] + e["dur"])
        elif e["kind"] == "bind_flush":
            host.append((e["t"], e["t"] + e["dur"], e["wave"]))
    if not device or not host:
        return 0.0
    # merged union of device windows + prefix lengths for O(log n) probes
    device.sort()
    merged = [list(device[0])]
    for d0, d1 in device[1:]:
        if d0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], d1)
        else:
            merged.append([d0, d1])
    starts = [m[0] for m in merged]
    prefix = [0.0]
    for m0, m1 in merged:
        prefix.append(prefix[-1] + (m1 - m0))

    def measure_upto(x: float) -> float:
        """Union length of the merged device windows within (-inf, x]."""
        k = bisect.bisect_right(starts, x) - 1
        if k < 0:
            return 0.0
        m0, m1 = merged[k]
        return prefix[k] + min(max(x - m0, 0.0), m1 - m0)

    total = 0.0
    for h0, h1, hw in host:
        covered = measure_upto(h1) - measure_upto(h0)
        own = dev_by_wave.get(hw)
        if own is not None:
            covered -= max(min(h1, own[1]) - max(h0, own[0]), 0.0)
        total += min(max(covered, 0.0), h1 - h0)
    return total


__all__ = ["add_pod_lanes", "add_process_lanes", "build_chrome_trace",
           "export_chrome_trace", "overlap_seconds"]
