"""Pod-level black box (ISSUE 15): sampled per-pod lifecycle tracing.

The flight recorder (recorder.py) answers "what did wave k do"; nothing
answered "why was THIS pod slow" — every tail investigation since r06
was a hand-built join of creator stamps against wave instants. Sparrow's
evaluation (PAPERS.md) rests on per-task latency decomposition; Borg's
operability on tasks self-publishing their own state. This module gives
every SAMPLED pod a typed event timeline stamped at the seams the pod
actually crosses:

    ENQUEUED        admitted to the scheduling queue (a=1 on a backoff
                    requeue, 0 on first admission).
    POPPED          left the queue in one admission batch (a=batch size
                    = the realized quantum, b=this pod's pop round).
    WAVE_DISPATCHED rode a fused wave eval (a=wave id).
    HARVESTED       its wave's device->host sync + fence completed and
                    the pod SURVIVED (a=wave id).
    FENCE_REQUEUED  the fence threw it back (a=typed reason code — see
                    REASON_NAMES: capacity / affinity / liveness / gang
                    / stale-encoding).
    GANG_GATED      parked below gang quorum (a=members waiting).
    PREEMPT_VICTIM  planned as a preemption victim (a=preemptor node
                    row when known).
    EVICTED         a committed preemption unbound it (it re-enters as
                    an ordinary arrival — the next ENQUEUED continues
                    the same timeline).
    BOUND           bind write confirmed (terminal: the timeline
                    completes, feeds the critical-path aggregate, and
                    competes for the tail-exemplar reservoir).
    WIRE_HOP        one transport hop of a fleet scheduleOne (a=
                    transport code — WIRE_HTTP/WIRE_BINARY/
                    WIRE_EMBEDDED, b=verb code HOP_FILTER/HOP_BIND).
    CREATED         wire-ingress birth stamp (a frontend beginning a
                    trace before any queue exists).

Cost model (the reason this can stay armed in production):

- OFF (the default): every emit site guards on ``TRACER.enabled`` —
  one attribute load and a branch; nothing allocates, no clock is read.
  Exact no-op.
- ON: HEAD-SAMPLING admits 1-in-``sample`` pods by a deterministic
  crc32 of the pod key (crc32(key) & mask == 0 — stable across
  processes, so a creator and a scheduler agree without coordination);
  non-sampled pods cost one dict probe per seam. Sampled timelines are
  bounded three ways: ``max_live`` concurrent timelines (past it, new
  begins are DROPPED and counted — never silent), ``max_events`` per
  timeline (fence-requeue loops cannot grow one pod unboundedly), and
  a per-window rotation that abandons stale live entries. bench.py
  measures the total as an interleaved on/off A/B on the arrival
  headline (podtrace_overhead_pct in the BENCH artifact).

Completion feeds three consumers:

- the CRITICAL-PATH aggregate: consecutive event deltas telescope into
  named phases (queue_wait / requeue_wait / dispatch / device /
  bind_flush / fence / gang_wait / classic_round / wire / other) whose
  per-pod sum equals the pod's first-event->BOUND span EXACTLY (by
  construction — the phases are a partition of the timeline), summed
  per window and served through the TelemetryRegistry;
- the TAIL-EXEMPLAR reservoir: the slowest ``exemplars`` completed
  timelines per window keep their FULL event lists (the forensics
  payload of /debug/pods and the Perfetto pod lanes);
- the SLO engine (slo.py) observes every bound pod's span separately —
  SLO math runs over ALL pods, not the sampled subset.

Trace context ACROSS transports: a fleet scheduleOne's filter->bind
hops join one timeline keyed by the trace id (the pod key). The HTTP
sidecar reads the ``X-Pod-Trace`` header, the binary wire carries
FLAG_TRACE + a trace-id field on FILTER/BIND (framing.wrap_trace), and
the embedded API passes ``trace_ctx=`` natively — presence of a context
forces the sample (the CLIENT made the head decision; servers honor
it), so a sampled pod's timeline is identical in shape whichever wire
carried it (transport parity is test-pinned).

Host-pure like the recorder: every stamp is a monotonic timestamp plus
host ints already in hand — fetching a device value to "trace" it would
be exactly the GL002 hidden-sync hazard, and the graftlint fixture pins
that the shipped seams stay silent while a fetching variant fires.
"""

from __future__ import annotations

import os
import threading
from kubernetes_tpu.analysis import lockcheck
import time
import zlib
from typing import Dict, List, Optional, Sequence

# ---------------------------------------------------------- event kinds

CREATED = 0
ENQUEUED = 1
POPPED = 2
WAVE_DISPATCHED = 3
HARVESTED = 4
FENCE_REQUEUED = 5
GANG_GATED = 6
PREEMPT_VICTIM = 7
EVICTED = 8
BOUND = 9
WIRE_HOP = 10
# fast-lane eval (ISSUE 17): the pod's sampled power-of-k scoring ran —
# a carries the eval path (0=device [1,k] dispatch, 1=host twin), b the
# attempt number (0 first try, >0 a fence-loss resample)
FAST_DISPATCHED = 11

KIND_NAMES = ("created", "enqueued", "popped", "wave_dispatched",
              "harvested", "fence_requeued", "gang_gated",
              "preempt_victim", "evicted", "bound", "wire_hop",
              "fast_dispatched")

# typed fence-requeue reasons (ISSUE 15 satellite): the one folded
# "fence_requeued" count becomes attributable — capacity races vs
# topology vs dying nodes vs gang rollbacks vs stale encodings are
# different production stories with different fixes
REASON_CAPACITY = 0
REASON_AFFINITY = 1
REASON_LIVENESS = 2
REASON_GANG = 3
REASON_STALE = 4
# double_claim (ISSUE 16): the pod itself is already claimed — another
# scheduler process committed it through the shared cell's fence. Only
# the WIRE fence can attribute this reason (the wave engine owns its
# pods exclusively); it shares this vocabulary so the wire's typed
# bind_conflict_reason_* counters partition with the same names as the
# engine's fence_reason_* requeues.
REASON_DOUBLE_CLAIM = 5
# host_check / policy (ISSUE 18): the last two serializing chunk shapes
# now ride the wave blind — host-check classes against a precomputed
# static host column (or an exact harvest-tail oracle), Policy classes
# against frozen policy fit/score columns. Their fence losers are their
# own production story: a label or workload-set change raced the wave
# in flight, the conservative fence caught it, and the pod requeued
# instead of binding on stale truth.
REASON_HOSTCHECK = 6
REASON_POLICY = 7

REASON_NAMES = ("capacity", "affinity", "liveness", "gang",
                "stale_encoding", "double_claim", "host_check",
                "policy")

# wire-hop codes
WIRE_HTTP = 0
WIRE_BINARY = 1
WIRE_EMBEDDED = 2
WIRE_NAMES = ("http", "binary", "embedded")
HOP_FILTER = 0
HOP_BIND = 1
HOP_NAMES = ("filter", "bind")

# phase vocabulary of the critical-path decomposition (decompose()).
# fast_eval / fast_bind (ISSUE 17) decompose a fast-lane pod's span:
# pop -> sampled eval, then eval -> bind-complete — the two halves of
# the sub-10 ms budget, attributable separately
PHASE_NAMES = ("queue_wait", "requeue_wait", "dispatch", "device",
               "bind_flush", "classic_round", "fence", "gang_wait",
               "wire", "fast_eval", "fast_bind", "other")


def phase_of(prev_k: int, k: int, requeued: bool) -> str:
    """Phase label for ONE consecutive-event transition — shared by the
    window aggregate (decompose) and the Perfetto pod lanes, so the
    picture and the numbers can never disagree."""
    if prev_k == GANG_GATED:
        return "gang_wait"
    if k == POPPED or k == ENQUEUED:
        return "requeue_wait" if requeued else "queue_wait"
    if k == WAVE_DISPATCHED:
        return "dispatch"
    if k == FAST_DISPATCHED:
        return "fast_eval"  # pop -> sampled [1,k] eval (ISSUE 17)
    if k == HARVESTED:
        return "device"
    if k == BOUND:
        if prev_k == HARVESTED:
            return "bind_flush"
        if prev_k == FAST_DISPATCHED:
            return "fast_bind"  # eval -> fence + bind-complete
        if prev_k == POPPED:
            return "classic_round"
        if prev_k == WIRE_HOP:
            return "wire"  # wire-path bind verdict landing
        return "other"
    if k == FENCE_REQUEUED:
        return "fence"
    if k == WIRE_HOP:
        return "wire"
    return "other"


def decompose(events: Sequence[tuple]) -> Dict[str, float]:
    """Telescoping critical-path decomposition of one timeline: each
    consecutive event delta is attributed to ONE phase, so the phase
    sums partition the span exactly —
    ``sum(decompose(ev).values()) == ev[-1].t - ev[0].t`` to float
    resolution. Events are (kind, t, a, b) tuples, time-ordered."""
    out: Dict[str, float] = {}
    if len(events) < 2:
        return out
    requeued = False
    prev_k = events[0][0]
    prev_t = events[0][1]
    for k, t, _a, _b in events[1:]:
        ph = phase_of(prev_k, k, requeued)
        if k == FENCE_REQUEUED:
            requeued = True
        out[ph] = out.get(ph, 0.0) + (t - prev_t)
        prev_k, prev_t = k, t
    return out


class PodTracer:
    """Bounded, head-sampled per-pod lifecycle tracer (module docstring).

    One lock guards the live map, the done-set, the window aggregates
    and the exemplar heap; batch emit sites take it once per BATCH, not
    per pod. Everything here is host ints, floats and small lists —
    never a device value."""

    def __init__(self, sample: int = 0, max_live: int = 0,
                 exemplars: int = 0, window_s: float = 0.0,
                 max_events: int = 64, now=time.monotonic):
        if sample <= 0:
            sample = int(os.environ.get("GRAFT_PODTRACE_SAMPLE", 64))
        if max_live <= 0:
            max_live = int(os.environ.get("GRAFT_PODTRACE_MAX_LIVE", 4096))
        if exemplars <= 0:
            exemplars = int(os.environ.get("GRAFT_PODTRACE_EXEMPLARS", 32))
        if window_s <= 0:
            window_s = float(os.environ.get("GRAFT_PODTRACE_WINDOW_S", 60))
        # sample normalizes to a power of two so the admit check is one
        # AND (1-in-(mask+1)); sample=1 traces everything (tests/audits)
        self.sample = 1 << max(int(sample) - 1, 0).bit_length()
        self._mask = self.sample - 1
        self.max_live = max(int(max_live), 8)
        self.exemplar_k = max(int(exemplars), 1)
        self.window_s = float(window_s)
        self.max_events = max(int(max_events), 8)
        self.enabled = False
        self._now = now
        self._lock = lockcheck.make_lock("PodTracer._lock")
        self._live: Dict[str, List[tuple]] = {}
        self._done: set = set()         # completed this window (dup audit)
        self._seq = 0
        self._window_start = now()
        # slowest-K min-heap of (span, seq, key, events)
        self._heap: List[tuple] = []
        self._prev_exemplars: List[Dict] = []
        self._phases: Dict[str, List] = {}       # name -> [count, seconds]
        self._prev_phases: Dict[str, List] = {}
        # monotonic totals (never reset by rotation)
        self._sampled_total = 0
        self._completed_total = 0
        self._dropped_live = 0
        self._dropped_events = 0
        self._duplicate_bound = 0
        self._abandoned = 0

    # ------------------------------------------------------------ control

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._done.clear()
            self._heap = []
            self._prev_exemplars = []
            self._phases = {}
            self._prev_phases = {}
            self._window_start = self._now()
            self._sampled_total = self._completed_total = 0
            self._dropped_live = self._dropped_events = 0
            self._duplicate_bound = self._abandoned = 0

    # ----------------------------------------------------------- sampling

    def sampled(self, key: str) -> bool:
        """The head decision, deterministic across processes: crc32 of
        the pod key against the power-of-two sample mask."""
        return (zlib.crc32(key.encode()) & self._mask) == 0

    def _admit_locked(self, key: str, t: float, kind: int,
                      a: int = 0) -> Optional[List[tuple]]:
        if len(self._live) >= self.max_live:
            self._dropped_live += 1
            return None
        ev = [(kind, t, a, 0)]
        self._live[key] = ev
        self._sampled_total += 1
        return ev

    # ------------------------------------------------------------- stamps

    def begin_batch(self, keys: Sequence[str], backoff: bool = False,
                    t0: float = 0.0) -> None:
        """Queue-admission seam (queue.add/add_many/add_backoff): apply
        the head decision per key, open timelines for the winners, or
        CONTINUE an existing timeline (a backoff requeue, a victim
        re-entering after EVICTED)."""
        t = t0 or self._now()
        a = 1 if backoff else 0
        crc = zlib.crc32
        mask = self._mask
        with self._lock:
            live = self._live
            for key in keys:
                ev = live.get(key)
                if ev is not None:
                    if len(ev) < self.max_events:
                        ev.append((ENQUEUED, t, a, 0))
                    else:
                        self._dropped_events += 1
                elif (crc(key.encode()) & mask) == 0 \
                        and key not in self._done:
                    self._admit_locked(key, t, ENQUEUED, a)

    def begin_forced(self, key: str, kind: int = CREATED,
                     t0: float = 0.0) -> None:
        """Wire ingress / trace-context honor: the caller already made
        (or received) the head decision — open unconditionally."""
        t = t0 or self._now()
        with self._lock:
            if key not in self._live and key not in self._done:
                self._admit_locked(key, t, kind)

    def batch_event(self, kind: int, keys: Sequence[str], a: int = 0,
                    b: int = 0, t0: float = 0.0) -> None:
        """One typed event for every SAMPLED key in a batch (one lock,
        one dict probe per key — the non-sampled common case costs
        exactly the probe)."""
        t = t0 or self._now()
        with self._lock:
            live = self._live
            max_ev = self.max_events
            for key in keys:
                ev = live.get(key)
                if ev is None:
                    continue
                if len(ev) >= max_ev:
                    self._dropped_events += 1
                    continue
                ev.append((kind, t, a, b))

    def pop_batch(self, keys: Sequence[str], t0: float = 0.0) -> None:
        """POPPED for a whole admission batch: a = the realized quantum
        (batch size), b = this pod's pop round (how many times it has
        left the queue — requeue loops made visible)."""
        t = t0 or self._now()
        n = len(keys)
        with self._lock:
            live = self._live
            max_ev = self.max_events
            for key in keys:
                ev = live.get(key)
                if ev is None:
                    continue
                if len(ev) >= max_ev:
                    self._dropped_events += 1
                    continue
                rounds = sum(1 for e in ev if e[0] == POPPED) + 1
                ev.append((POPPED, t, n, rounds))

    def event(self, key: str, kind: int, a: int = 0, b: int = 0,
              t0: float = 0.0) -> None:
        """Single-pod stamp (gang gating, preempt victims, wire hops)."""
        t = t0 or self._now()
        with self._lock:
            ev = self._live.get(key)
            if ev is None:
                return
            if len(ev) >= self.max_events:
                self._dropped_events += 1
                return
            ev.append((kind, t, a, b))

    def wire_hop(self, trace_id: str, transport: int, verb: int,
                 t0: float = 0.0) -> None:
        """One transport hop joins the trace: presence of a context IS
        the sample decision (begin_forced), so filter->bind hops of a
        fleet scheduleOne land on one timeline whichever wire carried
        them."""
        t = t0 or self._now()
        with self._lock:
            ev = self._live.get(trace_id)
            if ev is None:
                if trace_id in self._done:
                    return
                ev = self._admit_locked(trace_id, t, CREATED)
                if ev is None:
                    return
            if len(ev) >= self.max_events:
                self._dropped_events += 1
                return
            ev.append((WIRE_HOP, t, transport, verb))

    def evicted_batch(self, keys: Sequence[str], t0: float = 0.0) -> None:
        """A committed preemption unbound these pods: stamp EVICTED on
        any live timeline (rare — a victim usually completed long ago)
        and clear the done-mark, so the victim's RE-placement opens a
        fresh timeline whose eventual BOUND is a legitimate second bind,
        not a duplicate witness."""
        t = t0 or self._now()
        with self._lock:
            for key in keys:
                self._done.discard(key)
                ev = self._live.get(key)
                if ev is not None and len(ev) < self.max_events:
                    ev.append((EVICTED, t, 0, 0))

    # --------------------------------------------------------- completion

    def bound_batch(self, keys: Sequence[str], t0: float = 0.0) -> None:
        """Terminal BOUND for every sampled key: the timeline completes,
        its phase decomposition folds into the window aggregate, and it
        competes for the slowest-K exemplar reservoir. A key completing
        TWICE inside one window is a duplicate-bind witness — counted,
        never silently merged (the exactly-once trace audit reads
        this)."""
        import heapq
        t = t0 or self._now()
        with self._lock:
            self._rotate_locked(t)
            live = self._live
            done = self._done
            phases = self._phases
            for key in keys:
                ev = live.pop(key, None)
                if ev is None:
                    if key in done:
                        self._duplicate_bound += 1
                    continue
                ev.append((BOUND, t, 0, 0))
                if len(done) < 4 * self.max_live:
                    done.add(key)
                self._completed_total += 1
                span = t - ev[0][1]
                for ph, secs in decompose(ev).items():
                    slot = phases.get(ph)
                    if slot is None:
                        phases[ph] = [1, secs]
                    else:
                        slot[0] += 1
                        slot[1] += secs
                self._seq += 1
                heapq.heappush(self._heap, (span, self._seq, key, ev))
                if len(self._heap) > self.exemplar_k:
                    heapq.heappop(self._heap)

    def _rotate_locked(self, now: float) -> None:
        if now - self._window_start < self.window_s:
            return
        self._prev_exemplars = self._exemplars_locked()
        self._prev_phases = {k: list(v) for k, v in self._phases.items()}
        self._heap = []
        self._phases = {}
        self._done.clear()
        self._window_start = now
        # abandon stale live timelines (unschedulable forever, lost to a
        # relist): a live entry whose last stamp predates the PREVIOUS
        # window can never complete meaningfully — reclaim its slot
        cutoff = now - 2 * self.window_s
        stale = [k for k, ev in self._live.items() if ev[-1][1] < cutoff]
        for k in stale:
            del self._live[k]
        self._abandoned += len(stale)

    # ------------------------------------------------------------ reading

    @staticmethod
    def _timeline_dict(key: str, events: List[tuple]) -> Dict:
        span = events[-1][1] - events[0][1]
        phases = decompose(events)
        return {
            "key": key,
            # absolute (monotonic) first-event instant: the Perfetto pod
            # lanes align against the ring's time base with this — the
            # per-event t_ms below are pod-relative
            "t0": round(events[0][1], 6),
            "span_ms": round(span * 1e3, 6),
            "phases_ms": {ph: round(s * 1e3, 6)
                          for ph, s in sorted(phases.items())},
            "events": [{"kind": KIND_NAMES[k],
                        "t_ms": round((t - events[0][1]) * 1e3, 6),
                        "a": a, "b": b}
                       for k, t, a, b in events],
        }

    def _exemplars_locked(self) -> List[Dict]:
        out = [self._timeline_dict(key, ev)
               for _span, _seq, key, ev in
               sorted(self._heap, reverse=True)]
        return out

    def timeline(self, key: str) -> Optional[List[tuple]]:
        """The raw live timeline of one pod (tests/audits)."""
        with self._lock:
            ev = self._live.get(key)
            return list(ev) if ev is not None else None

    def snapshot(self) -> Dict:
        """The /debug/pods payload (identical on every transport):
        window phase aggregate + slowest-K exemplars, current and
        previous window, plus the bound/drop accounting."""
        with self._lock:
            self._rotate_locked(self._now())
            return {
                "sample_rate": self.sample,
                "window_s": self.window_s,
                "phases": {ph: {"count": c,
                                "seconds": round(s, 6)}
                           for ph, (c, s) in sorted(self._phases.items())},
                "exemplars": self._exemplars_locked(),
                "prev_phases": {ph: {"count": c, "seconds": round(s, 6)}
                                for ph, (c, s) in
                                sorted(self._prev_phases.items())},
                "prev_exemplars": self._prev_exemplars,
                "live": len(self._live),
                "stats": self._stats_locked(),
            }

    def _stats_locked(self) -> Dict[str, float]:
        return {"enabled": int(self.enabled),
                "sample_rate": self.sample,
                "live": len(self._live),
                "sampled_total": self._sampled_total,
                "completed_total": self._completed_total,
                "dropped_live": self._dropped_live,
                "dropped_events": self._dropped_events,
                "duplicate_bound": self._duplicate_bound,
                "abandoned": self._abandoned}

    def stats(self) -> Dict[str, float]:
        """Flat registry fold: bound accounting plus the per-window
        phase aggregate (podtrace.phase.<name>.count/seconds in the
        unified namespace — gauges, not counters: they reset with the
        window). Rotates like snapshot() so a scrape after binds stop
        never serves an arbitrarily stale window as current."""
        with self._lock:
            self._rotate_locked(self._now())
            out = self._stats_locked()
            for ph, (c, s) in self._phases.items():
                out[f"phase.{ph}.count"] = c
                out[f"phase.{ph}.seconds"] = round(s, 6)
            return out


# process-wide tracer, disabled unless armed — the emit sites all guard
# on TRACER.enabled (exact no-op off). GRAFT_PODTRACE=1 arms at import;
# bench.py flips it programmatically for the on/off A/B.
TRACER = PodTracer()
if os.environ.get("GRAFT_PODTRACE", "0") == "1":
    TRACER.enable()


__all__ = ["BOUND", "CREATED", "ENQUEUED", "EVICTED", "FAST_DISPATCHED",
           "FENCE_REQUEUED",
           "GANG_GATED", "HARVESTED", "HOP_BIND", "HOP_FILTER",
           "HOP_NAMES", "KIND_NAMES", "PHASE_NAMES", "POPPED",
           "PREEMPT_VICTIM", "PodTracer", "REASON_AFFINITY",
           "REASON_CAPACITY", "REASON_DOUBLE_CLAIM", "REASON_GANG",
           "REASON_HOSTCHECK", "REASON_LIVENESS",
           "REASON_NAMES", "REASON_POLICY", "REASON_STALE", "TRACER",
           "WAVE_DISPATCHED",
           "WIRE_BINARY", "WIRE_EMBEDDED", "WIRE_HOP", "WIRE_HTTP",
           "WIRE_NAMES", "decompose", "phase_of"]
