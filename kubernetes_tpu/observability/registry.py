"""Unified telemetry registry: one labeled namespace over every metric
source the tree grew ad-hoc (ISSUE 13).

Before this module there were four disjoint telemetry systems:
``utils/trace.py`` COUNTERS (span counts + accumulated wall time),
``utils/metrics.py`` SchedulerMetrics (histograms + counters, one
instance per Scheduler/backend), the extender's ``_counters`` dict
(service counters under their own torn-read-audited lock), and loose
gauges (commit/snapshot generations, the streaming loop's quantum /
backlog / degraded state) that only existed as attributes. Each had its
own render, and only one (the extender's) was scrapeable.

``TelemetryRegistry`` folds them:

- ``snapshot()`` returns ONE flat dict under a labeled namespace —
  ``span.<name>.count`` / ``span.<name>.seconds``,
  ``hist.<prefix>.<name>.count`` / ``.sum``, ``counter.<prefix>.<k>``,
  ``gauge.<name>``, ``recorder.*`` — the exact payload every
  introspection transport serves (HTTP ``/debug/vars``, the binary
  STATS verb, ``VerdictService.debug_snapshot``), so transport parity
  is a dict equality, test-pinned.
- ``render_prometheus()`` is the single Prometheus text render: the
  SchedulerMetrics families verbatim (existing scrape consumers keep
  their names), the service counters as ``<prom_prefix>_<k>_total``,
  gauges by their registered names, plus the span and recorder
  families the old render never exposed.

Torn-read discipline (the r12 audit, inherited): every source snapshots
under ITS OWN lock, sources are read in sequence (never nested), and
the registry itself holds no lock while calling into one — a scrape can
contend with the eval path only for the microseconds one source's
snapshot takes.

Registration is keyed (kind, name): re-registering replaces, so a
replacement ScheduleLoop's gauges supersede the dead loop's instead of
accumulating.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.observability.podtrace import TRACER
from kubernetes_tpu.observability.recorder import RECORDER
from kubernetes_tpu.observability.slo import SLO, SLO_FAST
from kubernetes_tpu.utils.trace import COUNTERS


class TelemetryRegistry:
    """One process-local fold over span counters, SchedulerMetrics,
    counter dicts, and gauge providers."""

    def __init__(self, spans=COUNTERS, recorder=RECORDER, tracer=TRACER,
                 slo=SLO, slo_fast=SLO_FAST):
        self._spans = spans
        self._recorder = recorder
        # pod-level black box (ISSUE 15): the tracer's bound accounting
        # + per-window phase aggregate and the SLO gauges fold in beside
        # the recorder, so "why is p99 moving" is one scrape on any
        # transport
        self._tracer = tracer
        self._slo = slo
        # per-tier objective (ISSUE 17): the fast lane's 10 ms SLO folds
        # as slo.fast.* beside the bulk slo.* on every transport
        self._slo_fast = slo_fast
        # keyed sources; insertion-ordered so renders are stable. The
        # registration lock guards the MAPS only (a ScheduleLoop swap
        # races a scrape's iteration — dict-changed-size mid-snapshot);
        # provider fns are called OUTSIDE it, so a slow source can never
        # block registration and the per-source lock discipline holds.
        self._reg_lock = lockcheck.make_lock("TelemetryRegistry._reg_lock")
        self._metrics: Dict[str, object] = {}
        self._counters: Dict[str, Tuple[Callable[[], Dict[str, int]],
                                        Optional[str]]] = {}
        self._gauges: Dict[str, Callable[[], Dict[str, float]]] = {}

    # ------------------------------------------------------- registration

    def register_metrics(self, prefix: str, metrics) -> None:
        """A utils.metrics.SchedulerMetrics (or any object exposing
        iterable ``histograms()``/``counters()`` — see below) under a
        namespace prefix."""
        with self._reg_lock:
            self._metrics[prefix] = metrics

    def register_counters(self, prefix: str,
                          fn: Callable[[], Dict[str, int]],
                          prom_prefix: Optional[str] = None) -> None:
        """A counter-dict provider. ``fn`` must snapshot under the
        owner's own lock and return a plain dict. ``prom_prefix`` names
        the Prometheus family stem (``<prom_prefix>_<k>_total``)."""
        with self._reg_lock:
            self._counters[prefix] = (fn, prom_prefix)

    def register_gauges(self, name: str,
                        fn: Callable[[], Dict[str, float]]) -> None:
        """A gauge provider returning {prom_name: value}. Values must be
        cheap host reads (ints/floats already in hand)."""
        with self._reg_lock:
            self._gauges[name] = fn

    def unregister_gauges(self, name: str, only_if=None) -> None:
        """Drop a gauge provider. ``only_if`` guards the handover race:
        a dying owner removes its registration only while it is still
        the one registered (a replacement that re-registered under the
        same key is left in place). Equality, not identity: bound
        methods are re-created per attribute access — ``==`` compares
        (__self__, __func__)."""
        with self._reg_lock:
            if only_if is not None \
                    and self._gauges.get(name) != only_if:
                return
            self._gauges.pop(name, None)

    def _sources(self):
        """Stable copies of the registration maps — iteration happens
        over these, never the live dicts a register/unregister could
        resize mid-scrape."""
        with self._reg_lock:
            return (list(self._metrics.items()),
                    list(self._counters.items()),
                    list(self._gauges.items()))

    # ----------------------------------------------------------- snapshot

    @staticmethod
    def _metric_parts(metrics):
        """(histograms, counters) of a SchedulerMetrics-shaped object —
        duck-typed off utils.metrics so the registry never imports a
        specific metric set."""
        from kubernetes_tpu.utils.metrics import Counter, Histogram
        hists: List = []
        ctrs: List = []
        for v in vars(metrics).values():
            if isinstance(v, Histogram):
                hists.append(v)
            elif isinstance(v, Counter):
                ctrs.append(v)
        return hists, ctrs

    def snapshot(self) -> Dict[str, float]:
        metrics_src, counters_src, gauges_src = self._sources()
        out: Dict[str, float] = {}
        for name, (count, secs) in sorted(self._spans.snapshot().items()):
            out[f"span.{name}.count"] = count
            out[f"span.{name}.seconds"] = round(secs, 6)
        for prefix, metrics in metrics_src:
            hists, ctrs = self._metric_parts(metrics)
            for h in hists:
                count, total = h.totals()
                out[f"hist.{prefix}.{h.name}.count"] = count
                out[f"hist.{prefix}.{h.name}.sum"] = round(total, 6)
            for c in ctrs:
                out[f"counter.{prefix}.{c.name}"] = c.value
        for prefix, (fn, _prom) in counters_src:
            for k, v in sorted(fn().items()):
                out[f"counter.{prefix}.{k}"] = v
        for _name, fn in gauges_src:
            for k, v in sorted(fn().items()):
                out[f"gauge.{k}"] = v
        for k, v in self._recorder.stats().items():
            out[f"recorder.{k}"] = v
        for k, v in self._tracer.stats().items():
            out[f"podtrace.{k}"] = v
        for k, v in self._slo.snapshot().items():
            out[f"slo.{k}"] = v
        for k, v in self._slo_fast.snapshot().items():
            out[f"slo.fast.{k}"] = v
        return out

    # --------------------------------------------------------- Prometheus

    def render_prometheus(self) -> str:
        metrics_src, counters_src, gauges_src = self._sources()
        lines: List[str] = []
        for _prefix, metrics in metrics_src:
            lines.append(metrics.render())
        for _prefix, (fn, prom) in counters_src:
            stem = prom or "tpu"
            snap = fn()
            for k in sorted(snap):
                name = f"{stem}_{k}_total"
                lines.append(f"# TYPE {name} counter\n{name} {snap[k]}")
        for _name, fn in gauges_src:
            for k, v in sorted(fn().items()):
                lines.append(f"# TYPE {k} gauge\n{k} {v}")
        # span family: one labeled pair of counters instead of a family
        # per span name (the span vocabulary is open-ended)
        spans = sorted(self._spans.snapshot().items())
        if spans:
            lines.append("# TYPE tpu_span_count_total counter")
            for name, (count, _secs) in spans:
                lines.append(f'tpu_span_count_total{{span="{name}"}} '
                             f'{count}')
            lines.append("# TYPE tpu_span_seconds_total counter")
            for name, (_count, secs) in spans:
                lines.append(f'tpu_span_seconds_total{{span="{name}"}} '
                             f'{secs:.6f}')
        rec = self._recorder.stats()
        for k in sorted(rec):
            name = f"tpu_flight_recorder_{k}"
            kind = "counter" if k in ("events", "dropped") else "gauge"
            lines.append(f"# TYPE {name} {kind}\n{name} {rec[k]}")
        # pod tracer + SLO families (ISSUE 15): dots in the phase keys
        # become underscores (Prometheus name grammar)
        trc = self._tracer.stats()
        for k in sorted(trc):
            name = "tpu_podtrace_" + k.replace(".", "_")
            # phase.* values reset per window — gauges, not counters
            # (a counter TYPE would make rate()/increase() misread every
            # rotation as a reset)
            kind = "counter" if (("total" in k or "dropped" in k
                                  or "duplicate" in k or "abandoned" in k)
                                 and not k.startswith("phase.")) \
                else "gauge"
            lines.append(f"# TYPE {name} {kind}\n{name} {trc[k]}")
        slo = self._slo.snapshot()
        for k in sorted(slo):
            name = f"tpu_slo_{k}"
            kind = "counter" if k == "alerts_total" else "gauge"
            lines.append(f"# TYPE {name} {kind}\n{name} {slo[k]}")
        slo_fast = self._slo_fast.snapshot()
        for k in sorted(slo_fast):
            name = f"tpu_slo_fast_{k}"
            kind = "counter" if k == "alerts_total" else "gauge"
            lines.append(f"# TYPE {name} {kind}\n{name} {slo_fast[k]}")
        return "\n".join(lines)


__all__ = ["TelemetryRegistry"]
