"""Compact length-prefixed binary framing for the fleet verbs (ISSUE 11).

PROFILE_r12 attributed the fleet wall to the transport, not the payload:
a NO-OP ThreadingHTTPServer measures ~196 req/s with 100 clients on the
2-core box while the service answers a warm scheduleOne step in
~0.2-6 ms. This module is the wire half of killing that wall — a
hand-rolled struct encoding (pure stdlib, no msgpack dependency) for the
verbs the fleet actually speaks, served by the single-threaded async
event loop in server/asyncwire.py and driven by the blocking fleet
client in client/binarywire.py.

Frame layout (network byte order)::

    u32  length    # bytes AFTER this field: 6-byte header rest + payload
    u8   verb      # request 0x01-0x06, response 0x81-0x89
    u8   flags     # FLAG_COMPACT on FILTER: elide the all-passed echo
    u32  request_id  # client correlation id, echoed verbatim in the
                     # response (a pipelining frontend matches on it)
    ...  payload   # verb-specific, primitives below

Primitives: u8/u16/u32, i64, str (u32 length + utf-8), blob (u32 length
+ raw bytes). Every read is bounds-checked: a truncated or corrupt
payload raises the typed ``FrameError`` instead of an IndexError deep in
struct — the async server answers it with an ERROR frame (payload decode)
or drops the connection (unrecoverable stream desync on a corrupt length
prefix), and the frame fuzzer in tests/test_framing.py pins both.

Verbs — requests:

    FILTER      fused filter+topk on ONE ticket (the binary twin of the
                HTTP ``/filter {"Compact", "TopK"}`` extension): u16
                top_k, u32 deadline_ms (0 = none), pod blob. The
                response is VERDICT.
    BIND        spec-carrying commit: pod_name, namespace, uid, node,
                i64 snapshot_gen (-1 = none), idempotency key (the
                BindLedger key rides the frame, "" = none), u32
                deadline_ms, optional pod blob (exact fence math).
                Response: BIND_RESULT.
    SYNC_NODES / SYNC_PODS
                bulk cache sync. Payload: u8 codec tag + blob — tag 1 is
                the existing api/protowire protobuf codec when available,
                tag 0 the JSON item list (the negotiable fallback, same
                as the HTTP Content-Type switch). Response: SYNCED.
    METRICS     -> METRICS_TEXT (the Prometheus text the HTTP /metrics
                serves).
    PING        -> PONG, no service touch — the no-op round trip
                bench.measure_wire_floor times against the threaded-HTTP
                no-op floor.
    STATS       live introspection (ISSUE 13): u32 last_n ->
                STATS_RESULT carrying the unified telemetry-registry
                snapshot plus the flight recorder's last_n events as a
                JSON blob — identical content to HTTP /debug/vars +
                /debug/trace and the embedded debug_snapshot.
    RELIST      cell-truth pull (ISSUE 16): no payload ->
                RELIST_RESULT carrying two codec-tagged item blobs —
                live nodes, then every pod the shared cache charges to a
                node — so a scheduler PROCESS refreshes its own
                bounded-stale snapshot without the server pushing state
                (the level-triggered re-list of the watch/relist
                discipline, over the wire).
    CELL_AGG    federation aggregate pull (ISSUE 20): u8 verb flags
                (drain spill / evacuate pending) -> CELL_AGG_RESULT
                carrying the cell's incrementally-maintained aggregate
                (JSON blob) + the spilled/evacuated pods it hands back
                for re-routing (codec-tagged items blob).
    ADMIT       federation admission (ISSUE 20): idempotency key + pod
                batch -> ADMIT_RESULT (accepted, replayed counts). A
                pod that already exists in the cell's store is a REPLAY,
                never a second admission.

Verbs — responses:

    VERDICT     i64 snapshot_gen, u8 all_passed, u32 passed_count,
                passed names (empty under FLAG_COMPACT+all_passed — the
                5k-name echo is the single biggest JSON-wire cost),
                failed names, top scores [(host, i64 score)].
    BIND_RESULT u8 kind (0 ok, 1 conflict, 2 pending, 3 shed, 4 error),
                u32 retry_after_ms, error string — the typed
                conflict/backoff contract of bind_verdict, verbatim.
    OVERLOADED  u32 retry_after_ms, jittered server-side: the typed
                backpressure frame (the HTTP 429 + Retry-After twin).
    DEADLINE    the request outlived its own deadline while queued
                (the HTTP 504 twin); nothing was evaluated.
    ERROR       str message — typed in-band failure, connection stays
                usable (payload-level errors only; stream-level
                corruption closes the connection instead).

All correctness semantics live BELOW this codec (fence, ledger,
staleness, coalescing — server/extender.py, server/embedded.py);
swapping the wire moves no semantics, which tests/test_asyncwire.py
pins by re-running the ISSUE 9 fault storms over this framing.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Tuple

# ------------------------------------------------------------------ verbs

FILTER = 0x01
BIND = 0x02
SYNC_NODES = 0x03
SYNC_PODS = 0x04
METRICS = 0x05
PING = 0x06
# live introspection (ISSUE 13): u32 last_n (0 = vars only) -> the
# unified telemetry-registry snapshot + the flight recorder's event
# tail, identical content to HTTP /debug/vars + /debug/trace and the
# embedded debug_snapshot — the wire twin of Borg's per-task
# introspection endpoints
STATS = 0x07
# cell-truth pull (ISSUE 16): the inverse of the SYNC push — a worker
# process relists (nodes, bound pods) from the shared cell to refresh
# its own scheduler's bounded-stale snapshot
RELIST = 0x08
# federation verbs (ISSUE 20): the front-door router's two touches of a
# member cell. CELL_AGG pulls the cell's incrementally-maintained
# aggregate (capacity headroom, band pressure, affinity domains — the
# [C, M] routing tensor's one column) plus any spilled pods the cell
# wants re-routed; flags in the payload ask for spill drain and/or a
# full pending evacuation (brownout). ADMIT hands a batch of pods to
# exactly one cell under an idempotency key — replays are counted, not
# re-created, so a lost ADMIT_RESULT re-send cannot double-admit.
CELL_AGG = 0x09
ADMIT = 0x0A

VERDICT = 0x81
BIND_RESULT = 0x82
OVERLOADED = 0x84
DEADLINE = 0x85
ERROR = 0x86
SYNCED = 0x87
METRICS_TEXT = 0x88
PONG = 0x89
STATS_RESULT = 0x8A
RELIST_RESULT = 0x8B
CELL_AGG_RESULT = 0x8C
ADMIT_RESULT = 0x8D

FLAG_COMPACT = 0x01
# trace context on FILTER/BIND (ISSUE 15): when set, the payload is
# PREFIXED with one str field — the pod-trace id — so a fleet
# scheduleOne's filter->bind hops join one podtrace timeline across the
# wire. Presence IS the sample decision (the client made the head call);
# a server without the tracer armed skips the id in O(1).
FLAG_TRACE = 0x02

BIND_KINDS = ("ok", "conflict", "pending", "shed", "error")
_BIND_KIND_CODE = {k: i for i, k in enumerate(BIND_KINDS)}

# codec tags for object blobs (pods / node lists): the existing protobuf
# path when its bindings exist, JSON otherwise — the binary FRAMING is
# independent of the payload codec, exactly like the HTTP Content-Type
# negotiation it replaces
CODEC_JSON = 0
CODEC_PROTO = 1

# header: length(u32) covers verb+flags+request_id+payload
_HDR = struct.Struct("!IBBI")
HEADER_SIZE = _HDR.size  # 10
_LEN_REST = HEADER_SIZE - 4  # verb+flags+request_id = 6

# a 5k-node JSON node list is a few MB; 64 MiB bounds any legitimate
# sync while making a corrupt length prefix (e.g. ASCII read as u32)
# detectable immediately instead of a multi-GB allocation
MAX_FRAME = 64 << 20


class FrameError(Exception):
    """Typed framing failure: corrupt length, truncated payload, unknown
    structure. Payload-scoped errors keep the connection; a corrupt
    length prefix is a stream desync and closes it."""


# ------------------------------------------------------------- primitives


class Writer:
    """Append-only payload builder over one bytearray."""

    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def u8(self, v: int) -> "Writer":
        self.buf.append(v & 0xFF)
        return self

    def u16(self, v: int) -> "Writer":
        self.buf += struct.pack("!H", v)
        return self

    def u32(self, v: int) -> "Writer":
        self.buf += struct.pack("!I", v)
        return self

    def i64(self, v: int) -> "Writer":
        self.buf += struct.pack("!q", v)
        return self

    def str_(self, s: str) -> "Writer":
        b = s.encode("utf-8")
        self.u32(len(b))
        self.buf += b
        return self

    def blob(self, b: bytes) -> "Writer":
        self.u32(len(b))
        self.buf += b
        return self

    def strs(self, items) -> "Writer":
        self.u32(len(items))
        for s in items:
            self.str_(s)
        return self


class Reader:
    """Bounds-checked cursor over one frame payload — every underrun is
    the typed FrameError, never a silent short read."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise FrameError(
                f"truncated payload: need {n} bytes at offset {self.pos}, "
                f"have {len(self.buf) - self.pos}")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("!H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("!I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("!q", self._take(8))[0]

    def str_(self) -> str:
        n = self.u32()
        if n > len(self.buf) - self.pos:
            raise FrameError(f"truncated string: declared {n} bytes, "
                             f"have {len(self.buf) - self.pos}")
        return bytes(self._take(n)).decode("utf-8", errors="replace")

    def blob(self) -> bytes:
        n = self.u32()
        if n > len(self.buf) - self.pos:
            raise FrameError(f"truncated blob: declared {n} bytes, "
                             f"have {len(self.buf) - self.pos}")
        return bytes(self._take(n))

    def strs(self) -> List[str]:
        n = self.u32()
        # each entry needs >= 4 length bytes: reject absurd counts before
        # looping (a corrupt count must not spin building a giant list)
        if n > (len(self.buf) - self.pos) // 4 + 1:
            raise FrameError(f"corrupt list count {n}")
        return [self.str_() for _ in range(n)]


# ---------------------------------------------------------- trace context


def wrap_trace(payload: bytes, trace_id: str) -> bytes:
    """Prefix a FILTER/BIND payload with the pod-trace id (the sender
    also sets FLAG_TRACE on the frame)."""
    return bytes(Writer().str_(trace_id).buf) + payload


def unwrap_trace(payload: bytes, flags: int):
    """(trace_id | None, payload rest): strips the FLAG_TRACE prefix
    when present, returns the payload untouched otherwise."""
    if not (flags & FLAG_TRACE):
        return None, payload
    r = Reader(payload)
    tid = r.str_()
    return tid, payload[r.pos:]


# ----------------------------------------------------------------- frames


def encode_frame(verb: int, request_id: int, payload: bytes = b"",
                 flags: int = 0) -> bytes:
    return _HDR.pack(_LEN_REST + len(payload), verb, flags,
                     request_id) + payload


class FrameDecoder:
    """Incremental stream decoder: feed() arbitrary chunks (interleaved
    partial writes included), get complete frames back. A corrupt length
    prefix raises FrameError — the stream cannot be resynced past it."""

    __slots__ = ("_buf", "max_frame")

    def __init__(self, max_frame: int = MAX_FRAME) -> None:
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed(self, data: bytes) -> List[Tuple[int, int, int, bytes]]:
        """Returns complete frames as (verb, flags, request_id, payload)."""
        self._buf += data
        out = []
        while len(self._buf) >= HEADER_SIZE:
            length, verb, flags, req_id = _HDR.unpack_from(self._buf, 0)
            if length < _LEN_REST or length > self.max_frame:
                raise FrameError(f"corrupt frame length {length} "
                                 f"(bounds {_LEN_REST}..{self.max_frame})")
            total = 4 + length
            if len(self._buf) < total:
                break  # partial frame: wait for more bytes
            payload = bytes(self._buf[HEADER_SIZE:total])
            del self._buf[:total]
            out.append((verb, flags, req_id, payload))
        return out

    @property
    def buffered(self) -> int:
        return len(self._buf)


# -------------------------------------------------------------- pod blobs


def _proto_available() -> bool:
    try:
        from kubernetes_tpu.api import protowire
        return protowire.available()
    except Exception:
        return False


def encode_pod_blob(pod) -> bytes:
    """One pod, protobuf when the bindings exist, JSON serde otherwise."""
    if _proto_available():
        from kubernetes_tpu.api import protowire
        return bytes([CODEC_PROTO]) + protowire.encode_pods([pod])
    from kubernetes_tpu.api import serde
    return bytes([CODEC_JSON]) + json.dumps(
        serde.encode_pod(pod), separators=(",", ":")).encode()


def decode_pod_blob(blob: bytes):
    if not blob:
        raise FrameError("empty pod blob")
    tag, body = blob[0], blob[1:]
    if tag == CODEC_PROTO:
        from kubernetes_tpu.api import protowire
        if not protowire.available():
            raise FrameError("protobuf pod blob but bindings unavailable")
        pods = protowire.decode_pods(body)
        if len(pods) != 1:
            raise FrameError(f"pod blob holds {len(pods)} pods, want 1")
        return pods[0]
    if tag == CODEC_JSON:
        from kubernetes_tpu.api import serde
        try:
            return serde.decode_pod(json.loads(body))
        except (ValueError, KeyError, TypeError) as e:
            raise FrameError(f"bad JSON pod blob: {e}") from e
    raise FrameError(f"unknown pod codec tag {tag}")


def encode_items_blob(items, kind: str) -> bytes:
    """Bulk node/pod list for the SYNC verbs, codec-negotiated like the
    HTTP bulk endpoints (protowire Content-Type vs JSON)."""
    if _proto_available():
        from kubernetes_tpu.api import protowire
        enc = (protowire.encode_nodes if kind == "nodes"
               else protowire.encode_pods)
        return bytes([CODEC_PROTO]) + enc(items)
    from kubernetes_tpu.api import serde
    enc1 = serde.encode_node if kind == "nodes" else serde.encode_pod
    return bytes([CODEC_JSON]) + json.dumps(
        [enc1(i) for i in items], separators=(",", ":")).encode()


def decode_items_blob(blob: bytes, kind: str):
    if not blob:
        raise FrameError("empty items blob")
    tag, body = blob[0], blob[1:]
    if tag == CODEC_PROTO:
        from kubernetes_tpu.api import protowire
        if not protowire.available():
            raise FrameError("protobuf items blob but bindings unavailable")
        return (protowire.decode_nodes(body) if kind == "nodes"
                else protowire.decode_pods(body))
    if tag == CODEC_JSON:
        from kubernetes_tpu.api import serde
        dec1 = serde.decode_node if kind == "nodes" else serde.decode_pod
        try:
            return [dec1(o) for o in json.loads(body)]
        except (ValueError, KeyError, TypeError) as e:
            raise FrameError(f"bad JSON items blob: {e}") from e
    raise FrameError(f"unknown items codec tag {tag}")


# --------------------------------------------------------------- requests


def encode_filter_request(pod, top_k: int = 0, deadline_ms: int = 0,
                          pod_blob: Optional[bytes] = None) -> bytes:
    """``pod_blob`` lets a retrying client amortize the spec encoding
    across attempts (the blob is deterministic per spec — exactly the
    candidate-list-serialized-once discipline of the HTTP drivers)."""
    return bytes(Writer().u16(top_k).u32(deadline_ms)
                 .blob(pod_blob if pod_blob is not None
                       else encode_pod_blob(pod)).buf)


def decode_filter_request(payload: bytes):
    blob, top_k, deadline_ms = decode_filter_request_lazy(payload)
    return decode_pod_blob(blob), top_k, deadline_ms


def decode_filter_request_lazy(payload: bytes):
    """Header fields now, pod blob LATER: the async server parses frames
    on the event loop but defers the (comparatively expensive) pod
    decode to the worker — and caches it, since the same spec blob
    arrives once per verb and once per retry."""
    r = Reader(payload)
    top_k = r.u16()
    deadline_ms = r.u32()
    return r.blob(), top_k, deadline_ms


def encode_bind_request(pod_name: str, namespace: str, uid: str, node: str,
                        snapshot_gen: Optional[int] = None,
                        idem_key: str = "", deadline_ms: int = 0,
                        pod=None, pod_blob: Optional[bytes] = None) -> bytes:
    w = (Writer().str_(pod_name).str_(namespace).str_(uid).str_(node)
         .i64(-1 if snapshot_gen is None else snapshot_gen)
         .str_(idem_key).u32(deadline_ms))
    if pod_blob is not None:
        w.blob(pod_blob)
    else:
        w.blob(encode_pod_blob(pod) if pod is not None else b"")
    return bytes(w.buf)


def decode_bind_request(payload: bytes):
    out = decode_bind_request_lazy(payload)
    blob = out[-1]
    return out[:-1] + (decode_pod_blob(blob) if blob else None,)


def decode_bind_request_lazy(payload: bytes):
    """Like decode_filter_request_lazy: everything but the pod decode."""
    r = Reader(payload)
    name, ns, uid, node = r.str_(), r.str_(), r.str_(), r.str_()
    gen = r.i64()
    idem_key = r.str_()
    deadline_ms = r.u32()
    blob = r.blob()
    return (name, ns, uid, node, None if gen < 0 else gen,
            idem_key or None, deadline_ms, blob)


def encode_sync_request(items, kind: str) -> bytes:
    return encode_items_blob(items, kind)


# -------------------------------------------------------------- responses


def encode_verdict(gen: Optional[int], all_passed: bool, passed_count: int,
                   passed: Optional[List[str]], failed: List[str],
                   top: List[Tuple[str, int]]) -> bytes:
    w = (Writer().i64(-1 if gen is None else gen)
         .u8(1 if all_passed else 0).u32(passed_count)
         .strs(passed or []).strs(failed))
    w.u32(len(top))
    for host, score in top:
        w.str_(host).i64(int(score))
    return bytes(w.buf)


def decode_verdict(payload: bytes):
    r = Reader(payload)
    gen = r.i64()
    all_passed = bool(r.u8())
    passed_count = r.u32()
    passed = r.strs()
    failed = r.strs()
    top = [(r.str_(), r.i64()) for _ in range(r.u32())]
    return {"gen": None if gen < 0 else gen, "all_passed": all_passed,
            "passed_count": passed_count, "passed": passed,
            "failed": failed, "top": top}


def encode_bind_result(kind: str, retry_after_ms: int, error: str) -> bytes:
    return bytes(Writer().u8(_BIND_KIND_CODE[kind]).u32(retry_after_ms)
                 .str_(error).buf)


def decode_bind_result(payload: bytes):
    r = Reader(payload)
    code = r.u8()
    if code >= len(BIND_KINDS):
        raise FrameError(f"unknown bind-result kind {code}")
    return {"kind": BIND_KINDS[code], "retry_after_ms": r.u32(),
            "error": r.str_()}


def encode_overloaded(retry_after_ms: int) -> bytes:
    return bytes(Writer().u32(retry_after_ms).buf)


def decode_overloaded(payload: bytes) -> int:
    return Reader(payload).u32()


def encode_error(message: str) -> bytes:
    return bytes(Writer().str_(message).buf)


def decode_error(payload: bytes) -> str:
    return Reader(payload).str_()


def encode_synced(count: int) -> bytes:
    return bytes(Writer().u32(count).buf)


def decode_synced(payload: bytes) -> int:
    return Reader(payload).u32()


def encode_metrics_text(text: str) -> bytes:
    return bytes(Writer().str_(text).buf)


def decode_metrics_text(payload: bytes) -> str:
    return Reader(payload).str_()


def encode_stats_request(last: int = 0) -> bytes:
    """STATS request: how many trailing recorder events to include
    (0 = registry vars only)."""
    return bytes(Writer().u32(last).buf)


def decode_stats_request(payload: bytes) -> int:
    return Reader(payload).u32()


def encode_stats_result(obj: Dict) -> bytes:
    """STATS_RESULT: {"vars": <registry snapshot>, "trace": [events]}
    as one JSON blob — introspection is a debug verb; the payload's
    open-ended key set does not justify a bespoke struct layout."""
    return bytes(Writer().blob(json.dumps(
        obj, separators=(",", ":")).encode()).buf)


def decode_stats_result(payload: bytes) -> Dict:
    try:
        return json.loads(Reader(payload).blob())
    except ValueError as e:
        raise FrameError(f"bad STATS payload: {e}") from e


def encode_relist_result(nodes, pods) -> bytes:
    """RELIST_RESULT: two codec-tagged item blobs — live nodes, then the
    bound pods the shared cache charges to them (ISSUE 16). Each rides
    its own length prefix so the reader never guesses a boundary."""
    return bytes(Writer().blob(encode_items_blob(nodes, "nodes"))
                 .blob(encode_items_blob(pods, "pods")).buf)


def decode_relist_result(payload: bytes):
    r = Reader(payload)
    return (decode_items_blob(r.blob(), "nodes"),
            decode_items_blob(r.blob(), "pods"))


# ------------------------------------------------------- federation verbs

# CELL_AGG request flag bits (payload u8, not frame flags: frame flags
# are transport-scoped, these are verb semantics)
CELL_DRAIN_SPILL = 0x01   # include + consume the cell's spill buffer
CELL_EVACUATE = 0x02      # brownout: ALSO uproot every pending pod


def encode_cell_agg_request(drain_spill: bool = False,
                            evacuate: bool = False) -> bytes:
    f = (CELL_DRAIN_SPILL if drain_spill else 0) \
        | (CELL_EVACUATE if evacuate else 0)
    return bytes(Writer().u8(f).buf)


def decode_cell_agg_request(payload: bytes) -> Tuple[bool, bool]:
    f = Reader(payload).u8()
    return bool(f & CELL_DRAIN_SPILL), bool(f & CELL_EVACUATE)


def encode_cell_agg_result(agg: Dict, spilled) -> bytes:
    """CELL_AGG_RESULT: the aggregate as one JSON blob (an open-ended,
    evolving key set — the STATS rationale) + a codec-tagged items blob
    of pods the cell hands back for re-routing (spill drain/evacuation;
    empty when the request asked for neither)."""
    return bytes(Writer()
                 .blob(json.dumps(agg, separators=(",", ":")).encode())
                 .blob(encode_items_blob(list(spilled), "pods")
                       if spilled else b"").buf)


def decode_cell_agg_result(payload: bytes):
    r = Reader(payload)
    try:
        agg = json.loads(r.blob())
    except ValueError as e:
        raise FrameError(f"bad CELL_AGG payload: {e}") from e
    blob = r.blob()
    return agg, (decode_items_blob(blob, "pods") if blob else [])


def encode_admit_request(idem_key: str, pods) -> bytes:
    return bytes(Writer().str_(idem_key)
                 .blob(encode_items_blob(list(pods), "pods")).buf)


def decode_admit_request(payload: bytes):
    r = Reader(payload)
    idem_key = r.str_()
    return idem_key, decode_items_blob(r.blob(), "pods")


def encode_admit_result(accepted: int, replayed: int) -> bytes:
    return bytes(Writer().u32(accepted).u32(replayed).buf)


def decode_admit_result(payload: bytes) -> Tuple[int, int]:
    r = Reader(payload)
    return r.u32(), r.u32()


__all__ = [
    "ADMIT", "ADMIT_RESULT",
    "BIND", "BIND_KINDS", "BIND_RESULT",
    "CELL_AGG", "CELL_AGG_RESULT", "CELL_DRAIN_SPILL", "CELL_EVACUATE",
    "CODEC_JSON", "CODEC_PROTO",
    "DEADLINE", "ERROR", "FILTER", "FLAG_COMPACT", "FLAG_TRACE",
    "FrameDecoder",
    "FrameError", "HEADER_SIZE", "MAX_FRAME", "METRICS", "METRICS_TEXT",
    "OVERLOADED", "PING", "PONG", "RELIST", "RELIST_RESULT", "Reader",
    "STATS", "STATS_RESULT",
    "SYNCED", "SYNC_NODES", "SYNC_PODS", "VERDICT", "Writer",
    "decode_admit_request", "decode_admit_result",
    "decode_bind_request", "decode_bind_request_lazy",
    "decode_bind_result",
    "decode_cell_agg_request", "decode_cell_agg_result",
    "decode_error", "decode_filter_request",
    "decode_filter_request_lazy", "decode_items_blob",
    "decode_metrics_text", "decode_overloaded", "decode_pod_blob",
    "decode_relist_result",
    "decode_stats_request", "decode_stats_result", "decode_synced",
    "decode_verdict",
    "encode_admit_request", "encode_admit_result",
    "encode_bind_request", "encode_bind_result",
    "encode_cell_agg_request", "encode_cell_agg_result",
    "encode_error", "encode_filter_request", "encode_frame",
    "encode_items_blob", "encode_metrics_text", "encode_overloaded",
    "encode_pod_blob", "encode_relist_result", "encode_stats_request",
    "encode_stats_result",
    "encode_sync_request", "encode_synced", "encode_verdict",
    "unwrap_trace", "wrap_trace",
]
