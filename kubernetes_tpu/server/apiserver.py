"""The full apiserver: handler chain around the apiserver-lite store.

Mirror of DefaultBuildHandlerChain
(staging/src/k8s.io/apiserver/pkg/server/config.go:469) — the filters a
request traverses before the registry:

    panic-recovery -> request-info -> [timeout] -> authentication -> audit ->
    [impersonation] -> max-in-flight -> authorization -> admission ->
    registry strategy -> storage

plus the subresources the control plane depends on: pods/binding
(pkg/registry/core/pod/storage/storage.go:128 BindingREST), pods/status,
pods/eviction with PDB enforcement (pkg/registry/core/pod/storage/
eviction.go), scale for replicated workloads, and namespace two-phase
delete. Audit entries (apiserver/pkg/audit) land in a bounded ring.

Transport note (SURVEY.md §5.8): in-process calls are the fast path, the
HTTP facade (server/rest_http.py) exposes the same handler over REST for
out-of-process clients — the control-plane fabric stays request/response
exactly like the reference; the TPU fabric is the engine's device arrays.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from kubernetes_tpu.admission import (
    AdmissionChain,
    AdmissionRequest,
    Rejected,
    default_plugins,
)
from kubernetes_tpu.api.cluster import Eviction
from kubernetes_tpu.api.rbac import (
    UserInfo,
    bootstrap_cluster_role_bindings,
    bootstrap_cluster_roles,
)
from kubernetes_tpu.api.types import Binding, Pod
from kubernetes_tpu.api.workloads import pods_matching
from kubernetes_tpu.auth.authn import Credential, Unauthenticated, UnionAuthenticator
from kubernetes_tpu.auth.authz import (
    ALLOW,
    Attributes,
    DENY,
    Forbidden,
    NO_OPINION,
    NodeAuthorizer,
    RBACAuthorizer,
    UnionAuthorizer,
)
from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    Conflict,
    NotFound,
)
from kubernetes_tpu.server.extensions import (
    crd_delete_cascade,
    crd_on_create,
    discovery_doc,
    resolve_crd,
    validate_custom_create,
)

# kind -> (resource plural, cluster-scoped)
KIND_INFO: Dict[str, Tuple[str, bool]] = {
    "Pod": ("pods", False),
    "Node": ("nodes", True),
    "Service": ("services", False),
    "Endpoints": ("endpoints", False),
    "Namespace": ("namespaces", True),
    "ReplicaSet": ("replicasets", False),
    "ReplicationController": ("replicationcontrollers", False),
    "Deployment": ("deployments", False),
    "StatefulSet": ("statefulsets", False),
    "DaemonSet": ("daemonsets", False),
    "Job": ("jobs", False),
    "CronJob": ("cronjobs", False),
    "PersistentVolume": ("persistentvolumes", True),
    "PersistentVolumeClaim": ("persistentvolumeclaims", False),
    "Secret": ("secrets", False),
    "ConfigMap": ("configmaps", False),
    "ServiceAccount": ("serviceaccounts", False),
    "ResourceQuota": ("resourcequotas", False),
    "LimitRange": ("limitranges", False),
    "PodDisruptionBudget": ("poddisruptionbudgets", False),
    "PriorityClass": ("priorityclasses", True),
    "StorageClass": ("storageclasses", True),
    "Role": ("roles", False),
    "ClusterRole": ("clusterroles", True),
    "RoleBinding": ("rolebindings", False),
    "ClusterRoleBinding": ("clusterrolebindings", True),
    "Event": ("events", False),
    "HorizontalPodAutoscaler": ("horizontalpodautoscalers", False),
    "CertificateSigningRequest": ("certificatesigningrequests", True),
    "CustomResourceDefinition": ("customresourcedefinitions", True),
    "APIService": ("apiservices", True),
    "PodSecurityPolicy": ("podsecuritypolicies", True),
}


class TooManyRequests(Exception):
    """429 — eviction blocked by a PodDisruptionBudget, or max-in-flight."""


class Invalid(Exception):
    """422 — registry strategy validation failure."""


@dataclass
class AuditEvent:
    """apiserver/pkg/audit event (one per request, ResponseComplete stage)."""

    user: str
    verb: str
    resource: str
    namespace: str
    name: str
    code: int
    ts: float = 0.0
    level: str = "Metadata"


@dataclass
class AuditRule:
    """One policy rule (apiserver/pkg/apis/audit Policy.Rules): first
    match wins; empty selector lists match everything."""

    level: str  # "None" | "Metadata" | "Request"
    users: List[str] = field(default_factory=list)
    verbs: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    namespaces: List[str] = field(default_factory=list)

    def matches(self, user: str, verb: str, resource: str,
                namespace: str) -> bool:
        return ((not self.users or user in self.users)
                and (not self.verbs or verb in self.verbs)
                and (not self.resources or resource in self.resources)
                and (not self.namespaces or namespace in self.namespaces))


@dataclass
class AuditPolicy:
    """Policy-driven auditing (apiserver/pkg/audit/policy): the level for
    a request is the FIRST matching rule's; no match falls through to
    `default_level`. Level None suppresses the entry entirely."""

    rules: List[AuditRule] = field(default_factory=list)
    default_level: str = "Metadata"

    def level_for(self, user: str, verb: str, resource: str,
                  namespace: str) -> str:
        for rule in self.rules:
            if rule.matches(user, verb, resource, namespace):
                return rule.level
        return self.default_level


class ApiServer:
    """Authenticated/authorized/admitted facade over ApiServerLite.

    auth=False (default) keeps the open in-process behavior benches and
    controllers use (the reference's --insecure-port localhost path);
    auth=True enforces the full chain, like the secure port.
    """

    def __init__(self, store: Optional[ApiServerLite] = None,
                 authenticator: Optional[UnionAuthenticator] = None,
                 auth: bool = False,
                 admission: Optional[AdmissionChain] = None,
                 max_audit: int = 10_000,
                 audit_policy: Optional[AuditPolicy] = None,
                 now=time.time):
        self.store = store if store is not None else ApiServerLite()
        self.auth_enabled = auth
        self.authenticator = authenticator
        self.admission = admission if admission is not None else \
            AdmissionChain(default_plugins(), store=self.store)
        self.authorizer = UnionAuthorizer(
            [NodeAuthorizer(self.store), RBACAuthorizer(self.store)])
        self.audit_log: List[AuditEvent] = []
        self._max_audit = max_audit
        self.audit_policy = audit_policy if audit_policy is not None \
            else AuditPolicy()
        self._now = now
        self._audit_lock = lockcheck.make_lock("ApiServer._audit_lock")
        self._inflight = threading.Semaphore(400)  # --max-requests-inflight

    # ---------------------------------------------------------------- setup

    def bootstrap_rbac(self) -> None:
        """Install the bootstrap policy (rbac/bootstrappolicy) if absent —
        the post-start hook of the rbac rest storage provider."""
        existing = {r.name for r in self.store.list("ClusterRole")[0]}
        for role in bootstrap_cluster_roles():
            if role.name not in existing:
                self.store.create("ClusterRole", role)
        existing_b = {b.name for b in self.store.list("ClusterRoleBinding")[0]}
        for b in bootstrap_cluster_role_bindings():
            if b.name not in existing_b:
                self.store.create("ClusterRoleBinding", b)

    # ------------------------------------------------------------- the chain

    def _authn(self, cred: Optional[Credential]) -> UserInfo:
        return self._impersonate(self._authn_base(cred), cred)

    def _authn_base(self, cred: Optional[Credential]) -> UserInfo:
        if not self.auth_enabled:
            return UserInfo("system:admin", groups=["system:masters"])
        if cred is None or self.authenticator is None:
            raise Unauthenticated("no credentials provided")
        return self.authenticator.authenticate(cred)

    def _impersonate(self, user: UserInfo,
                     cred: Optional[Credential]) -> UserInfo:
        """The impersonation filter (endpoints/filters/impersonation.go):
        the AUTHENTICATED user needs the "impersonate" verb on users (and
        on groups for each requested group); the rest of the chain then
        sees the impersonated identity, with the real one recorded for
        audit attribution."""
        if cred is None or not cred.impersonate_user:
            return user
        checks = [("users", cred.impersonate_user)] + \
            [("groups", g) for g in cred.impersonate_groups]
        for resource, name in checks:
            attrs = Attributes(user=user, verb="impersonate",
                               resource=resource, namespace="", name=name)
            if self.authorizer.authorize(attrs) != ALLOW:
                raise Forbidden(
                    f'User "{user.name}" cannot impersonate '
                    f'{resource[:-1]} "{name}"')
        groups = list(cred.impersonate_groups)
        if "system:authenticated" not in groups:
            # every non-anonymous identity carries system:authenticated
            # (UnionAuthenticator appends it to real logins; the
            # impersonation filter must preserve the invariant or --as
            # stops previewing the impersonated user's real permissions)
            groups.append("system:authenticated")
        return UserInfo(cred.impersonate_user, groups=groups,
                        extra={"impersonated-by": user.name})

    def _serving_info(self, kind: str, for_write: bool = False):
        """Dynamic discovery: (plural, cluster_scoped, crd-or-None) for a
        served kind — built-in or backed by an Established CRD; anything
        else 404s like an unregistered resource on the real server
        (apiextensions customresource_handler.go)."""
        if kind in KIND_INFO:
            plural, cluster_scoped = KIND_INFO[kind]
            return plural, cluster_scoped, None
        crd = resolve_crd(self.store, kind, for_write=for_write)
        if crd is None:
            raise NotFound(
                f"the server could not find the requested resource "
                f"(kind {kind!r})")
        return crd.names.plural, crd.scope == "Cluster", crd

    def _authz(self, user: UserInfo, verb: str, kind: str, namespace: str,
               name: str, subresource: str = "") -> None:
        if not self.auth_enabled:
            return
        resource, cluster_scoped = KIND_INFO.get(kind, (kind.lower() + "s",
                                                        False))
        crd = None if kind in KIND_INFO else resolve_crd(self.store, kind)
        if crd is not None:
            resource, cluster_scoped = (crd.names.plural,
                                        crd.scope == "Cluster")
        if subresource:
            resource = resource + "/" + subresource
        attrs = Attributes(user=user, verb=verb, resource=resource,
                           namespace="" if cluster_scoped else namespace,
                           name=name)
        if self.authorizer.authorize(attrs) != ALLOW:
            raise Forbidden(
                f'User "{user.name}" cannot {verb} {resource} '
                f'in namespace "{namespace}"')

    def _audit(self, user: UserInfo, verb: str, kind: str, namespace: str,
               name: str, code: int) -> None:
        resource, _ = KIND_INFO.get(kind, (kind.lower() + "s", False))
        # policy decides the level per request; None drops the entry
        # (audit/policy checker.go LevelForRequest)
        level = self.audit_policy.level_for(user.name, verb, resource,
                                            namespace)
        if level == "None":
            return
        with self._audit_lock:
            self.audit_log.append(AuditEvent(
                user.name, verb, resource, namespace, name, code,
                ts=self._now(), level=level))
            if len(self.audit_log) > self._max_audit:
                del self.audit_log[: len(self.audit_log) - self._max_audit]

    def _run(self, cred, verb, kind, namespace, name, fn, subresource=""):
        """panic-recovery + authn + authz + audit around fn()."""
        with self._inflight:
            user = self._authn_base(cred)
            code = 200
            try:
                # impersonation INSIDE the audited span: a denied
                # escalation attempt must land in the audit log,
                # attributed to the REAL user with code 403
                user = self._impersonate(user, cred)
                self._authz(user, verb, kind, namespace, name, subresource)
                return fn(user)
            except Unauthenticated:
                code = 401
                raise
            except Forbidden:
                code = 403
                raise
            except Rejected:
                code = 403
                raise
            except NotFound:
                code = 404
                raise
            except Conflict:
                code = 409
                raise
            except TooManyRequests:
                code = 429
                raise
            except Invalid:
                code = 422
                raise
            finally:
                self._audit(user, verb, kind, namespace, name, code)

    # ---------------------------------------------------------------- verbs

    def create(self, kind: str, obj: Any,
               cred: Optional[Credential] = None) -> int:
        ns = getattr(obj, "namespace", "")

        def do(user: UserInfo) -> int:
            _, _, crd = self._serving_info(kind, for_write=True)
            if kind == "CustomResourceDefinition":
                # naming + establishing controller work, done atomically
                # at admission time (server/extensions.py)
                crd_on_create(self.store, obj, KIND_INFO)
            elif crd is not None:
                validate_custom_create(crd, obj)
            if self.auth_enabled and kind == "CertificateSigningRequest":
                # registry strategy PrepareForCreate: requestor identity is
                # stamped from the authenticated user, never client-supplied
                # (pkg/registry/certificates/certificates/strategy.go) —
                # else any CSR creator could claim system:bootstrappers and
                # mint auto-approved node certs
                obj.requestor = user.name
                obj.groups = list(user.groups)
            # admission (mutating) precedes registry strategy validation,
            # matching the chain order in the module doc — so defaults
            # applied by plugins are themselves validated
            req = AdmissionRequest(
                "CREATE", kind, ns, obj.name, obj=obj, user=user)
            self.admission.admit(req)
            try:
                self._validate(kind, obj, None)
                return self.store.create(kind, obj)
            except Exception:
                # undo admission side effects (quota usage CAS) so a failed
                # create doesn't leak usage until the controller resync
                self.admission.rollback(req)
                raise

        return self._run(cred, "create", kind, ns, obj.name, do)

    def get(self, kind: str, namespace: str, name: str,
            cred: Optional[Credential] = None) -> Any:
        def do(user: UserInfo) -> Any:
            self._serving_info(kind)
            return self.store.get(kind, namespace, name)

        return self._run(cred, "get", kind, namespace, name, do)

    def list(self, kind: str, cred: Optional[Credential] = None,
             namespace: str = "", field_selector: str = "",
             include_uninitialized: bool = False):
        """namespace="" = cluster-wide list (needs cluster-wide authority);
        a namespace scopes both the RBAC check and the result set, like the
        namespaced list endpoints. field_selector is the apimachinery
        fields axis ("spec.nodeName=n1,status.phase!=Failed") applied
        through the per-kind GetAttrs (api/fields.py).
        include_uninitialized=False hides objects with pending initializers
        (the ?includeUninitialized=true list knob of the 1.7 alpha
        initializers feature)."""

        def do(user: UserInfo):
            self._serving_info(kind)
            objs, rv = self.store.list(kind)
            if not include_uninitialized:
                from kubernetes_tpu.admission.webhook import is_uninitialized
                objs = [o for o in objs if not is_uninitialized(o)]
            if namespace:
                objs = [o for o in objs
                        if getattr(o, "namespace", "") == namespace]
            if field_selector:
                from kubernetes_tpu.api.fields import (
                    FieldSelectorError,
                    filter_objects,
                    parse_field_selector,
                )
                try:
                    objs = filter_objects(
                        kind, objs, parse_field_selector(field_selector))
                except FieldSelectorError as e:
                    raise Invalid(str(e)) from None
            return objs, rv

        return self._run(cred, "list", kind, namespace, "", do)

    def update(self, kind: str, obj: Any, expect_rv: Optional[int] = None,
               cred: Optional[Credential] = None) -> int:
        ns = getattr(obj, "namespace", "")

        def do(user: UserInfo) -> int:
            _, _, crd = self._serving_info(kind, for_write=True)
            if crd is not None:
                validate_custom_create(crd, obj)
            if kind == "CustomResourceDefinition":
                # updates re-run the naming/structure checks create
                # enforces — else a PUT could rename plural/kind/group
                # into a collision or break the plural.group invariant
                crd_on_create(self.store, obj, KIND_INFO)
            old = self._try_get(kind, ns, obj.name)
            if kind == "CertificateSigningRequest" and old is not None:
                # ValidateUpdate (certificates/strategy.go): the request
                # identity and spec are immutable after create — else an
                # updater could restore groups=[system:bootstrappers] and
                # re-open the escalation the create-time stamp closed
                if obj.requestor != old.requestor \
                        or list(obj.groups) != list(old.groups) \
                        or obj.cn != old.cn or list(obj.orgs) != list(old.orgs):
                    raise Invalid(
                        "CertificateSigningRequest spec and requestor "
                        "identity are immutable after creation")
                if self.auth_enabled and (obj.approved != old.approved
                                          or obj.denied != old.denied):
                    # approval flips require the approval subresource
                    # permission (certificates/approval gating)
                    self._authz(user, "update", kind, ns, obj.name,
                                subresource="approval")
            self.admission.admit(AdmissionRequest(
                "UPDATE", kind, ns, obj.name, obj=obj, old_obj=old,
                user=user))
            self._validate(kind, obj, old)
            return self.store.update(kind, obj, expect_rv=expect_rv)

        return self._run(cred, "update", kind, ns, obj.name, do)

    def delete(self, kind: str, namespace: str, name: str,
               cred: Optional[Credential] = None) -> None:
        def do(user: UserInfo) -> None:
            self._serving_info(kind)
            old = self._try_get(kind, namespace, name)
            self.admission.admit(AdmissionRequest(
                "DELETE", kind, namespace, name, old_obj=old, user=user))
            if kind == "CustomResourceDefinition":
                if old is None:
                    raise NotFound(
                        f"customresourcedefinitions {name!r} not found")
                # customresourcecleanup finalizer: purge instances
                # before the definition row goes away
                crd_delete_cascade(self.store, old)
                return
            if kind == "Namespace":
                # two-phase delete: mark Terminating; the namespace
                # controller empties it then finalizes (pkg/controller/
                # namespace + registry/core/namespace strategy)
                ns_obj = self.store.get("Namespace", "", name)
                if ns_obj.phase != "Terminating":
                    ns_obj.phase = "Terminating"
                    self.store.update("Namespace", ns_obj)
                    return
            self.store.delete(kind, namespace, name)

        return self._run(cred, "delete", kind, namespace, name, do)

    def watch_since(self, kinds, from_rv, timeout=None,
                    cred: Optional[Credential] = None):
        user = self._audited_authn(cred, "watch",
                                   kinds[0] if kinds else "")
        if self.auth_enabled:
            for k in kinds:
                try:
                    self._authz(user, "watch", k, "", "")
                except Forbidden:
                    # a denied watch is audited like every other denial
                    self._audit(user, "watch", k, "", "", 403)
                    raise
        if self.auth_enabled:
            # allowed watches audit too (secure port only: the in-process
            # insecure path is the scheduler/informer sync loop, whose
            # sub-second polls would flood the 10k ring and evict the 403
            # entries that matter; per-rule suppression via AuditPolicy
            # remains available for noisy authenticated watchers)
            for k in kinds:
                self._audit(user, "watch", k, "", "", 200)
        return self.store.watch_since(kinds, from_rv, timeout=timeout)

    def _audited_authn(self, cred, verb: str, kind: str) -> UserInfo:
        """authn + impersonation for the paths that bypass _run: a DENIED
        impersonation must land in the audit log attributed to the real
        user (code 403) on every entry point, not just the CRUD verbs."""
        user = self._authn_base(cred)
        try:
            return self._impersonate(user, cred)
        except Forbidden:
            self._audit(user, verb, kind, "", "", 403)
            raise

    # ----------------------------------------------------------- subresources

    def bind(self, binding: Binding, cred: Optional[Credential] = None) -> int:
        def do(user: UserInfo) -> int:
            return self.store.bind(binding)

        return self._run(cred, "create", "Pod", binding.pod_namespace,
                         binding.pod_name, do, subresource="binding")

    def bind_many(self, bindings, cred: Optional[Credential] = None):
        """Batched bindings with per-binding authorization (one RBAC check
        per distinct namespace — bindings in a namespace the caller cannot
        create pods/binding in are rejected without touching the store) and
        a single aggregated audit entry for the batch."""
        if not bindings:
            return []
        user = self._audited_authn(cred, "create", "Pod")
        if self.auth_enabled:
            try:
                for ns in {b.pod_namespace for b in bindings}:
                    self._authz(user, "create", "Pod", ns, "",
                                subresource="binding")
            except Forbidden:
                self._audit(user, "create", "Pod",
                            bindings[0].pod_namespace,
                            f"<batch of {len(bindings)} bindings>", 403)
                raise
        self._audit(user, "create", "Pod", bindings[0].pod_namespace,
                    f"<batch of {len(bindings)} bindings>", 200)
        return self.store.bind_many(bindings)

    def update_status(self, kind: str, obj: Any,
                      cred: Optional[Credential] = None) -> int:
        ns = getattr(obj, "namespace", "")

        def do(user: UserInfo) -> int:
            # status writes run the admission chain too (the reference's
            # subresource REST goes through the same handler chain) — this
            # is what lets NodeRestriction block cross-node pod status writes
            old = self._try_get(kind, ns, obj.name)
            self.admission.admit(AdmissionRequest(
                "UPDATE", kind, ns, obj.name, obj=obj, old_obj=old,
                user=user, subresource="status"))
            return self.store.update(kind, obj)

        return self._run(cred, "update", kind, ns, obj.name, do,
                         subresource="status")

    def evict(self, ev: Eviction, cred: Optional[Credential] = None) -> None:
        """pods/eviction (eviction.go): honor PodDisruptionBudgets — refuse
        with 429 when disruptions_allowed is exhausted."""

        def do(user: UserInfo) -> None:
            import copy as _copy
            for _ in range(10):  # CAS retry (eviction.go retries on Conflict)
                pod = self.store.get("Pod", ev.namespace, ev.pod_name)
                matching = [
                    pdb for pdb in self.store.list("PodDisruptionBudget")[0]
                    if pdb.namespace == ev.namespace
                    and pdb.selector is not None
                    and pods_matching(pdb, [pod])]
                if len(matching) > 1:
                    # eviction.go: "only one PodDisruptionBudget is allowed"
                    raise Invalid(
                        "This pod has more than one PodDisruptionBudget, "
                        "which the Eviction subresource does not support")
                if matching:
                    pdb = matching[0]
                    if pdb.disruptions_allowed <= 0:
                        raise TooManyRequests(
                            f"Cannot evict pod as it would violate the pod's "
                            f"disruption budget {pdb.name}")
                    npdb = _copy.deepcopy(pdb)
                    npdb.disruptions_allowed -= 1
                    try:
                        # guarded status update so concurrent evictions
                        # cannot overspend the budget (eviction.go
                        # checkAndDecrement via UpdateStatus + rv)
                        self.store.update("PodDisruptionBudget", npdb,
                                          expect_rv=pdb.resource_version)
                    except Conflict:
                        continue
                self.store.delete("Pod", ev.namespace, ev.pod_name)
                return
            raise Conflict("eviction: PodDisruptionBudget update conflicts")

        return self._run(cred, "create", "Pod", ev.namespace, ev.pod_name,
                         do, subresource="eviction")

    def scale(self, kind: str, namespace: str, name: str,
              replicas: Optional[int] = None,
              cred: Optional[Credential] = None) -> int:
        """The scale subresource (registry/.../scale): get or set replicas
        on RS/RC/Deployment/StatefulSet."""

        def do(user: UserInfo) -> int:
            obj = self.store.get(kind, namespace, name)
            if replicas is None:
                return obj.replicas
            if replicas < 0:
                raise Invalid("replicas must be >= 0")
            obj.replicas = replicas
            self.store.update(kind, obj)
            return replicas

        verb = "get" if replicas is None else "update"
        return self._run(cred, verb, kind, namespace, name, do,
                         subresource="scale")

    def finalize_namespace(self, name: str,
                           cred: Optional[Credential] = None) -> None:
        """namespaces/finalize: the namespace controller calls this once the
        namespace is empty; the store row is removed."""

        def do(user: UserInfo) -> None:
            self.store.delete("Namespace", "", name)

        return self._run(cred, "update", "Namespace", "", name, do,
                         subresource="finalize")

    # -------------------------------------------------------------- helpers

    def healthz(self) -> Dict[str, str]:
        return {"status": "ok"}

    def discovery(self) -> Dict[str, Any]:
        """/apis discovery document (group/version/resource triples for
        built-ins + Established CRDs + aggregated groups) — what the
        discovery client and `ktctl api-resources` consume."""
        try:
            apiservices = self.store.list("APIService")[0]
        except NotFound:
            apiservices = []
        return discovery_doc(self.store, KIND_INFO, apiservices)

    def configz(self) -> Dict[str, Any]:
        return {"admission": [type(p).__name__ for p in
                              self.admission.plugins],
                "authorization": ["Node", "RBAC"] if self.auth_enabled
                else ["AlwaysAllow"]}

    def _try_get(self, kind, ns, name):
        try:
            return self.store.get(kind, ns, name)
        except NotFound:
            return None

    def _validate(self, kind: str, obj: Any, old: Any) -> None:
        """Registry strategy validation (pkg/registry/core/*/strategy.go),
        the load-bearing subset."""
        name = getattr(obj, "name", "")
        if not name:
            raise Invalid(f"{kind}: metadata.name is required")
        if kind == "Pod":
            if old is not None and old.node_name and \
                    obj.node_name != old.node_name:
                raise Invalid("pod spec.nodeName is immutable after binding")
            for c in obj.containers:
                for res, v in list(c.requests.items()):
                    if v < 0:
                        raise Invalid(f"negative request {res}={v}")
                for res, v in c.limits.items():
                    if res in c.requests and c.requests[res] > v:
                        raise Invalid(
                            f"request {res} must be <= limit")
        elif kind in ("ReplicaSet", "ReplicationController", "Deployment",
                      "StatefulSet"):
            if getattr(obj, "replicas", 0) < 0:
                raise Invalid("spec.replicas must be >= 0")
