"""Coalesced dispatch for the multi-frontend extender (ISSUE 9).

One kube-scheduler at 19 pods/s never queues two evaluations; a fleet of
100 does nothing else. This module turns concurrent /filter + /prioritize
requests into micro-batches against the backend's shared device-resident
snapshot: the first thread to arrive becomes the LEADER, drains whatever
is queued (plus an optional accumulation window when a storm is clearly
forming), and evaluates the whole batch through the engine's fused [C, N]
dispatch (scheduler_engine.evaluate_pods_batch) while followers park on
their ticket. Requests that arrive while the leader is on the device pile
up and ride the NEXT batch — natural group-commit batching, so a lone
client pays zero added latency and a storm pays ~1 dispatch per window
instead of one per request.

Robustness envelope (the rest of the ISSUE 9 contract):

  - ADMISSION CONTROL: the queue is bounded; past ``max_depth`` a submit
    raises Overloaded and the HTTP layer answers 429 + Retry-After —
    offered load beyond the dispatch budget sheds instead of queueing
    unboundedly (PAPERS.md §Sparrow: honest overload is visible overload).
  - DEADLINES: a request whose client already gave up (its DeadlineMs
    elapsed while queued) is SHED at batch formation, not evaluated into
    a response nobody is waiting for.
  - DEGRADED FALLBACK: when the batched evaluation itself faults, the
    leader falls back to per-request evaluation for the same tickets, so
    a coalescer bug degrades to PR 6 behavior (one eval per request)
    instead of an outage; the fault is counted and surfaced in /metrics.
"""

from __future__ import annotations

import random
import threading
from kubernetes_tpu.analysis import lockcheck
import time
from collections import deque
from typing import Optional


class Overloaded(Exception):
    """Queue depth exceeded the admission bound — retry after a backoff."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"coalescer queue full; retry after "
                         f"{retry_after_s * 1e3:.0f}ms")
        self.retry_after_s = retry_after_s


class DeadlineExceeded(Exception):
    """The request's client-supplied deadline elapsed before evaluation."""


class _Ticket:
    __slots__ = ("pod", "arrival", "deadline_s", "done", "result", "error")

    def __init__(self, pod, deadline_s: Optional[float]):
        self.pod = pod
        self.arrival = time.monotonic()
        self.deadline_s = deadline_s
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class EvalCoalescer:
    """Leader/follower micro-batch window over a TPUExtenderBackend.

    ``submit(pod, deadline_s)`` returns the backend's eval verdict for the
    pod (whatever ``backend._eval_many`` yields per pod), raising
    Overloaded / DeadlineExceeded per the envelope above. The backend's
    own lock serializes leaders against binds and syncs, so coalescing
    changes WHEN evaluations run, never what one means."""

    # follower safety net: a ticket with no deadline still must not park
    # forever if its leader dies uncleanly mid-serve
    MAX_WAIT_S = 30.0

    def __init__(self, backend, window_s: float = 0.0, max_batch: int = 64,
                 max_depth: int = 512):
        self._backend = backend
        self.window_s = window_s
        self.max_batch = max(int(max_batch), 1)
        self.max_depth = max(int(max_depth), 1)
        self._cv = lockcheck.make_condition("EvalCoalescer._cv")
        self._queue: deque = deque()
        self._leader_active = False
        self._rng = random.Random(0xC0A1)

    # ------------------------------------------------------------- submit

    def submit(self, pod, deadline_s: Optional[float] = None):
        t = _Ticket(pod, deadline_s)
        lead = False
        with self._cv:
            if len(self._queue) >= self.max_depth:
                self._backend._count("admission_shed")
                # jittered so 100 shed clients don't re-arrive in lockstep
                raise Overloaded(0.01 + self._rng.random() * 0.04)
            self._queue.append(t)
            # waiters park on the CV (not a private event) so leadership
            # can MIGRATE: a stepping-down leader wakes the room and the
            # first unserved waiter with work pending takes over — no
            # permanent dispatcher whose own caller is starved, and no
            # stranded queue when a leader exits between batches
            while not t.done.is_set():
                if not self._leader_active and self._queue:
                    self._leader_active = True
                    lead = True
                    break
                waited = time.monotonic() - t.arrival
                limit = self.MAX_WAIT_S if t.deadline_s is None \
                    else min(t.deadline_s, self.MAX_WAIT_S)
                if waited >= limit:
                    # withdraw the ticket: a ghost left queued would count
                    # against max_depth (spurious 429s) and be evaluated
                    # into a result nobody reads. Already popped into an
                    # in-flight batch -> the leader resolves it; dropping
                    # our reference is enough.
                    try:
                        self._queue.remove(t)
                    except ValueError:
                        pass
                    self._backend._count("deadline_shed")
                    raise DeadlineExceeded(
                        "queued past the request deadline")
                self._cv.wait(timeout=min(limit - waited, 0.05))
        if lead:
            self._lead(t)
        if t.error is not None:
            raise t.error
        if not t.done.is_set():  # led, stepped down with own ticket unserved
            raise DeadlineExceeded("leadership ended before service")
        return t.result

    # ------------------------------------------------------------- leader

    def _lead(self, own: _Ticket) -> None:
        try:
            while True:
                with self._cv:
                    if not self._queue or own.done.is_set():
                        # step down once our own caller is answered (or
                        # nothing is queued): the wakeup lets a parked
                        # waiter claim the role for what remains
                        self._leader_active = False
                        self._cv.notify_all()
                        return
                    if self.window_s > 0 \
                            and 1 < len(self._queue) < self.max_batch:
                        # a storm is forming (more than one waiter):
                        # optionally hold the window open for a fuller
                        # batch. A lone request never waits here.
                        self._cv.wait(timeout=self.window_s)
                    batch = []
                    while self._queue and len(batch) < self.max_batch:
                        batch.append(self._queue.popleft())
                self._serve(batch)
        except BaseException:
            # never strand the leader role on an unexpected escape —
            # _serve resolves its own tickets, so nothing else is pending
            with self._cv:
                self._leader_active = False
                self._cv.notify_all()
            raise

    def _serve(self, batch) -> None:
        backend = self._backend
        now = time.monotonic()
        live = []
        shed = 0
        for t in batch:
            if t.deadline_s is not None and now - t.arrival > t.deadline_s:
                t.error = DeadlineExceeded("deadline elapsed in queue")
                t.done.set()
                shed += 1
            else:
                live.append(t)
        if shed:
            backend._count("deadline_shed", shed)
        if not live:
            with self._cv:
                self._cv.notify_all()
            return
        backend._count("coalesce_batches")
        backend._count("coalesce_requests", len(live))
        try:
            outs = backend._eval_many([t.pod for t in live])
        except Exception:
            # DEGRADED FALLBACK: per-request evaluation, failures isolated
            # per ticket — a coalescer fault must not take the verb down
            backend._count("coalesce_faults")
            for t in live:
                try:
                    t.result = backend._eval_one(t.pod)
                except BaseException as e:  # noqa: BLE001 — ticket owns it
                    t.error = e
                t.done.set()
        else:
            for t, out in zip(live, outs):
                t.result = out
                t.done.set()
        with self._cv:
            self._cv.notify_all()  # served waiters are parked on the CV


__all__ = ["DeadlineExceeded", "EvalCoalescer", "Overloaded"]
