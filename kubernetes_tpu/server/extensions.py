"""Server-side API extension machinery: CRD lifecycle + the aggregator.

Two reference components re-built for the in-process control plane:

- **CRD serving** (apiextensions-apiserver): `crd_on_create` is the
  naming+establishing controller pair collapsed into admission-time work
  (pkg/controller/{naming,establish} in the staging repo run async; with
  an in-process store the check-and-flip is atomic here instead).
  `resolve_kind` is the dynamic discovery the customresource_handler
  does per-request: a kind is served iff built-in or backed by an
  Established CRD. `crd_delete_cascade` is the
  customresourcecleanup finalizer: purge instances, then the definition.
- **Aggregation** (kube-aggregator): `Aggregator` proxies per
  group/version to registered extension apiservers, with an
  availability probe gating traffic like available_controller.go; local
  APIServices fall through to the primary server.
"""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.extensions import (
    APIService,
    CustomResource,
    CustomResourceDefinition,
    SchemaError,
    validate_custom,
)


class Unavailable(Exception):
    """503 — aggregated backend is not available."""


# ---------------------------------------------------------------------- CRDs


def crd_on_create(store, crd: CustomResourceDefinition,
                  builtin_kinds: Dict[str, Tuple[str, bool]]) -> None:
    """Validate structure, accept/reject names, establish.

    Mirrors apiextensions validation (name == "<plural>.<group>") and the
    NamesAccepted check against every other served resource; a CRD whose
    kind or plural collides is stored with NamesAccepted=False and never
    established (so its kind is NOT served), exactly the reference's
    behavior rather than a hard create-failure.
    """
    expect = f"{crd.names.plural}.{crd.group}"
    if crd.name != expect:
        from kubernetes_tpu.server.apiserver import Invalid
        raise Invalid(
            f"CustomResourceDefinition name must be {expect!r} "
            f"(plural.group), got {crd.name!r}")
    if not crd.group or "." not in crd.group:
        from kubernetes_tpu.server.apiserver import Invalid
        raise Invalid("CRD group must be a DNS-style name with a dot")

    taken_kinds = set(builtin_kinds)
    taken_plurals = {plural for plural, _ in builtin_kinds.values()}
    for other in store.list("CustomResourceDefinition")[0]:
        if other.name == crd.name:
            continue
        taken_kinds.add(other.names.kind)
        taken_plurals.add(other.names.plural)

    if crd.names.kind in taken_kinds or crd.names.plural in taken_plurals:
        crd.set_condition(
            "NamesAccepted", "False", reason="Conflict",
            message=f"kind {crd.names.kind!r} or plural "
                    f"{crd.names.plural!r} is already in use")
        crd.set_condition("Established", "False", reason="NotAccepted")
    else:
        crd.set_condition("NamesAccepted", "True", reason="NoConflicts")
        crd.set_condition("Established", "True", reason="InitialNamesAccepted")


def resolve_crd(store, kind: str,
                for_write: bool = False) -> Optional[CustomResourceDefinition]:
    """Return the Established CRD serving `kind`, if any. A Terminating
    CRD still serves reads (instances drain through the finalizer) but
    refuses writes — the reference's terminating-CRD behavior."""
    for crd in store.list("CustomResourceDefinition")[0]:
        if crd.names.kind == kind and crd.established:
            if for_write and crd.terminating:
                return None
            return crd
    return None


def validate_custom_create(crd: CustomResourceDefinition,
                           obj: Any) -> None:
    """Scope + schema checks for a custom object write (the dynamic
    registry strategy)."""
    from kubernetes_tpu.server.apiserver import Invalid
    ns = getattr(obj, "namespace", "")
    if crd.scope == "Namespaced" and not ns:
        raise Invalid(
            f"{crd.names.kind} is namespaced: metadata.namespace required")
    if crd.scope == "Cluster" and ns:
        raise Invalid(
            f"{crd.names.kind} is cluster-scoped: metadata.namespace "
            f"must be empty")
    if isinstance(obj, CustomResource) or hasattr(obj, "spec"):
        try:
            validate_custom(crd, obj)
        except SchemaError as e:
            raise Invalid(str(e)) from e


def crd_delete_cascade(store, crd: CustomResourceDefinition) -> None:
    """The customresourcecleanup finalizer: mark Terminating (new writes
    of the kind are refused via resolve_crd), purge every instance, then
    drop the definition row."""
    crd.terminating = True
    crd.set_condition("Terminating", "True", reason="InstanceDeletionInProgress")
    store.update("CustomResourceDefinition", crd)
    objs, _ = store.list(crd.names.kind)
    for o in objs:
        store.delete(crd.names.kind, getattr(o, "namespace", ""), o.name)
    store.delete("CustomResourceDefinition", "", crd.name)


# ----------------------------------------------------------------- discovery


def discovery_doc(store, builtin_kinds: Dict[str, Tuple[str, bool]],
                  apiservices: Optional[List[APIService]] = None
                  ) -> Dict[str, Any]:
    """The /apis discovery document: group/version/resource triples for
    built-ins, established CRDs, and aggregated groups — what client-go's
    discovery client consumes to map kinds to endpoints."""
    resources = [
        {"kind": kind, "name": plural, "namespaced": not cluster_scoped,
         "group": "", "version": "v1"}
        for kind, (plural, cluster_scoped) in sorted(builtin_kinds.items())
    ]
    for crd in store.list("CustomResourceDefinition")[0]:
        if not crd.established:
            continue
        resources.append({
            "kind": crd.names.kind, "name": crd.names.plural,
            "namespaced": crd.scope == "Namespaced",
            "group": crd.group, "version": crd.version,
            "shortNames": list(crd.names.short_names)})
    groups: List[Dict[str, Any]] = []
    for svc in (apiservices or []):
        groups.append({"group": svc.group, "version": svc.version,
                       "available": svc.available,
                       "local": svc.local})
    return {"resources": resources, "aggregatedGroups": groups}


# ---------------------------------------------------------------- aggregator


class Aggregator:
    """kube-aggregator: one front door over the primary apiserver plus any
    registered extension apiservers, routed by APIService group/version.

    `register_backend` pairs an APIService object with an in-process
    backend (anything exposing create/get/list/update/delete + healthz —
    i.e. another ApiServer, the sample-apiserver shape). The availability
    probe (`check_availability`) flips APIService.available off a failed
    healthz, and requests to an unavailable backend fail with 503 the way
    the real proxy does after available_controller marks it down.
    """

    def __init__(self, primary, probe_interval: float = 30.0):
        self.primary = primary
        self._backends: Dict[Tuple[str, str], Any] = {}
        self._lock = lockcheck.make_lock("Aggregator._lock")
        self.probe_interval = probe_interval
        self._last_probe = 0.0

    # -- registration ------------------------------------------------------

    def register_backend(self, apiservice: APIService, backend=None) -> None:
        """Create/refresh the APIService row; backend=None means a Local
        APIService (served by the primary)."""
        if backend is not None and apiservice.service is None:
            raise ValueError("remote APIService needs a ServiceReference")
        with self._lock:
            key = (apiservice.group, apiservice.version)
            if backend is not None:
                self._backends[key] = backend
        store = self.primary.store
        existing = [s for s in store.list("APIService")[0]
                    if s.name == apiservice.name]
        if existing:
            apiservice.resource_version = existing[0].resource_version
            store.update("APIService", apiservice)
        else:
            store.create("APIService", apiservice)
        self.check_availability(force=True)

    def remove_backend(self, name: str) -> None:
        store = self.primary.store
        for s in store.list("APIService")[0]:
            if s.name == name:
                with self._lock:
                    self._backends.pop((s.group, s.version), None)
                store.delete("APIService", "", name)
                return

    # -- availability ------------------------------------------------------

    def check_availability(self, force: bool = False) -> None:
        """The available_controller pass: probe each remote backend's
        healthz and persist the condition on its APIService row."""
        now = time.time()
        if not force and now - self._last_probe < self.probe_interval:
            return
        self._last_probe = now
        store = self.primary.store
        for svc in store.list("APIService")[0]:
            if svc.local:
                ok, msg = True, "Local APIServices are always available"
            else:
                with self._lock:
                    backend = self._backends.get((svc.group, svc.version))
                if backend is None:
                    ok, msg = False, "no backend registered"
                else:
                    try:
                        ok = backend.healthz().get("status") == "ok"
                        msg = "all checks passed" if ok \
                            else "healthz reported failure"
                    except Exception as e:  # probe must never throw
                        ok, msg = False, f"healthz probe failed: {e}"
            if svc.available != ok or svc.available_message != msg:
                svc.available = ok
                svc.available_message = msg
                store.update("APIService", svc)

    # -- routing -----------------------------------------------------------

    def _route(self, group: str, version: str):
        """Pick the serving backend for a group/version, honoring
        availability. Unknown group/versions 404 via the primary path."""
        if not group:  # core group is always local
            return self.primary
        store = self.primary.store
        match: Optional[APIService] = None
        for svc in store.list("APIService")[0]:
            if svc.group == group and svc.version == version:
                match = svc
                break
        if match is None or match.local:
            return self.primary
        self.check_availability()
        # re-read: check_availability may have flipped the row
        cur = next((s for s in store.list("APIService")[0]
                    if s.name == match.name), match)
        if not cur.available:
            raise Unavailable(
                f"the server is currently unable to handle the request "
                f"(APIService {cur.name}: {cur.available_message})")
        with self._lock:
            backend = self._backends.get((group, version))
        if backend is None:
            raise Unavailable(f"no backend for APIService {match.name}")
        return backend

    def handle(self, group: str, version: str, verb: str, *args, **kwargs):
        """Generic dispatch: handle("metrics.example.io", "v1", "list",
        "NodeMetrics") → backend.list("NodeMetrics")."""
        backend = self._route(group, version)
        return getattr(backend, verb)(*args, **kwargs)

    def discovery(self) -> Dict[str, Any]:
        self.check_availability()
        apiservices = self.primary.store.list("APIService")[0]
        from kubernetes_tpu.server.apiserver import KIND_INFO
        return discovery_doc(self.primary.store, KIND_INFO, apiservices)
