"""Store replication: WAL shipping + standby promotion.

The reference's store survives node loss because etcd replicates its WAL
through raft before acknowledging writes (etcd behind
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:85; disaster
recovery discipline in cluster/restore-from-backup.sh). This module gives
the durable store (server/durable.py) the availability half of that story
without writing raft: an ASYNCHRONOUS log-shipping follower —

- the primary keeps writing its own snapshot.db + wal.log untouched;
- a WalShippingStandby periodically pulls: the snapshot when it changed,
  then any new WAL bytes since its last offset (detecting primary
  compaction by the WAL shrinking below the shipped offset);
- on primary death, promote() restores an ApiServerLite from the standby
  directory and serves.

Honest semantics, stated plainly: shipping is async, so writes committed
on the primary AFTER the last ship() are lost at failover (raft would not
lose them; this is warm-standby / etcd-backup semantics, the
restore-from-backup.sh path automated). What IS guaranteed: the standby
restores to a consistent prefix of the primary's history — torn shipped
tails are dropped by the WAL's CRC framing, a half-shipped compaction
falls back to snapshot+reset, and every object present after promotion has
exactly the state some prefix of primary history gave it, so binds remain
exactly-once against the promoted truth.
"""

from __future__ import annotations

import os
import zlib
from typing import Optional, Tuple

from kubernetes_tpu.server.durable import _HDR, DurableStore


def _complete_frame_prefix(data: bytes) -> int:
    """Length of the longest prefix of `data` consisting of whole,
    CRC-VALID WAL frames. Shipping must be frame-aligned (a half-record
    shipped and then dropped by the standby's torn-tail repair would
    desynchronize every later frame), and CRC-checked (bytes read at a
    stale offset after a primary compaction can be length-plausible
    garbage — the checksum is what proves they are frames)."""
    off = 0
    while off + _HDR.size <= len(data):
        ln, crc = _HDR.unpack_from(data, off)
        end = off + _HDR.size + ln
        if end > len(data):
            break
        if zlib.crc32(data[off + _HDR.size:end]) != crc:
            break
        off = end
    return off


class WalShippingStandby:
    """Pull-based follower over a primary's durable data dir."""

    def __init__(self, primary_dir: str, standby_dir: str):
        self.primary_dir = primary_dir
        self.standby_dir = standby_dir
        os.makedirs(standby_dir, exist_ok=True)
        self._p_snap = os.path.join(primary_dir, DurableStore.SNAPSHOT)
        self._p_wal = os.path.join(primary_dir, DurableStore.WAL)
        self._s_snap = os.path.join(standby_dir, DurableStore.SNAPSHOT)
        self._s_wal = os.path.join(standby_dir, DurableStore.WAL)
        self._wal_offset = 0  # bytes of primary WAL shipped so far
        self._snap_sig: Optional[Tuple[float, int]] = None  # (mtime, size)
        self.ships = 0  # diagnostics
        self.bytes_shipped = 0

    # ------------------------------------------------------------ shipping

    def _snapshot_signature(self) -> Optional[Tuple[float, int]]:
        try:
            st = os.stat(self._p_snap)
        except FileNotFoundError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def _copy_snapshot(self) -> None:
        """Atomic copy (tmp + rename, like the primary's own compaction
        discipline) so a crash mid-ship never leaves a torn snapshot."""
        with open(self._p_snap, "rb") as src:
            data = src.read()
        tmp = self._s_snap + ".tmp"
        with open(tmp, "wb") as dst:
            dst.write(data)
            dst.flush()
            os.fsync(dst.fileno())
        os.replace(tmp, self._s_snap)

    def ship(self) -> int:
        """One shipping pass; returns bytes shipped. Handles the two
        primary-side events that invalidate simple byte-append:

        - new snapshot (compaction): re-copy it, restart the WAL from 0
          (the primary truncated its WAL at that instant)
        - WAL shrunk below our offset without a visible new snapshot
          (raced mid-compaction): same reset, next pass catches up

        A compaction can also land BETWEEN reading the snapshot signature
        and reading the WAL (the primary is another process): the
        signature is re-checked after the WAL read, and a changed one
        discards this pass's bytes and retries — appending them would
        stack post-compaction frames on the pre-compaction standby
        snapshot, silently skipping the records in between."""
        shipped = 0
        for _attempt in range(4):
            shipped = 0  # a retried attempt's copies don't count twice
            sig = self._snapshot_signature()
            try:
                wal_size = os.path.getsize(self._p_wal)
            except FileNotFoundError:
                wal_size = 0
            if sig != self._snap_sig or wal_size < self._wal_offset:
                if sig is not None:
                    self._copy_snapshot()
                    shipped += sig[1]
                self._snap_sig = sig
                self._wal_offset = 0
                # the primary's WAL restarted at its snapshot point; ours
                # must restart with it or we'd replay pre-snapshot records
                open(self._s_wal, "wb").close()
            data = b""
            if wal_size > self._wal_offset:
                with open(self._p_wal, "rb") as src:
                    src.seek(self._wal_offset)
                    data = src.read(wal_size - self._wal_offset)
            if self._snapshot_signature() != sig:
                continue  # compaction raced this pass; retry clean
            n = _complete_frame_prefix(data)
            if n:
                with open(self._s_wal, "ab") as dst:
                    dst.write(data[:n])
                    dst.flush()
                    os.fsync(dst.fileno())
                self._wal_offset += n
                shipped += n
            break
        self.ships += 1
        self.bytes_shipped += shipped
        return shipped

    # ----------------------------------------------------------- promotion

    def promote(self, **apiserver_kwargs):
        """Primary is dead: become the store. Restores snapshot+WAL from
        the standby dir (torn shipped tail repaired by the CRC scan) and
        returns a serving ApiServerLite. The returned server OWNS the
        standby dir from here on (its writes append there)."""
        from kubernetes_tpu.server.apiserver_lite import ApiServerLite
        return ApiServerLite(data_dir=self.standby_dir, **apiserver_kwargs)

    def standby_rv(self) -> int:
        """Highest resourceVersion the standby would restore to (test +
        monitoring probe; the replication-lag gauge)."""
        store = DurableStore(self.standby_dir)
        _objects, rv = store.restore()
        store.close()
        return rv
