"""The standalone scheduler daemon: leader election + healthz + metrics +
policy flags around the scheduling loop.

Mirror of the reference's binary composition
(plugin/cmd/kube-scheduler/app/server.go:67 Run: client -> informers ->
CreateScheduler -> healthz/pprof HTTP -> leaderelection.RunOrDie :127-146)
with the option surface of app/options/options.go:70-92:

  --scheduler-name             SchedulerOptions.scheduler_name
  --algorithm-provider         .algorithm_provider (api/policy.PROVIDERS)
  --policy-config-file         .policy_config_file (JSON Policy)
  --leader-elect               .leader_elect
  --lock-object-{namespace,name}  .lock_object_namespace/.lock_object_name
  --address/--port (healthz)   .healthz_host/.healthz_port

Two drive modes, like every other component here: `step()` for
deterministic fake-clock tests (one elector tick + one scheduling round
when leading), and `run()`/`stop()` for threaded operation. Failover is
exercised end-to-end by tests/test_chaos.py: kill the leading daemon
mid-storm, the standby acquires the lease and finishes the drain.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

import time

from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


@dataclass
class SchedulerOptions:
    """app/options/options.go:70-92, reduced to the implemented knobs."""

    scheduler_name: str = "default-scheduler"
    algorithm_provider: str = "DefaultProvider"
    policy_config_file: Optional[str] = None
    leader_elect: bool = True
    lock_object_namespace: str = "kube-system"
    lock_object_name: str = "kube-scheduler"
    healthz_host: str = "127.0.0.1"
    healthz_port: int = 0  # 0 = ephemeral; None disables the server
    batch_mode: str = "wave"

    @classmethod
    def from_component_config(cls, cfg) -> "SchedulerOptions":
        """Options from a decoded componentconfig
        KubeSchedulerConfiguration (api/scheme.py) — the
        --config/--policy-configmap path of the reference server
        (KubeSchedulerConfiguration, componentconfig types.go:158)."""
        host, _, port = cfg.healthz_bind_address.rpartition(":")
        return cls(
            scheduler_name=cfg.scheduler_name,
            algorithm_provider=cfg.algorithm_provider,
            policy_config_file=cfg.policy_config_file or None,
            leader_elect=cfg.leader_election.leader_elect,
            lock_object_namespace=cfg.leader_election.lock_object_namespace,
            lock_object_name=cfg.leader_election.lock_object_name,
            healthz_host=host or "127.0.0.1",
            healthz_port=int(port) if port else 0)


class SchedulerDaemon:
    def __init__(self, api: ApiServerLite, identity: str,
                 options: Optional[SchedulerOptions] = None,
                 now: Callable[[], float] = time.monotonic):
        self.api = api
        self.identity = identity
        self.options = options or SchedulerOptions()
        self._now = now
        self.scheduler: Optional[Scheduler] = None
        self._policy = None
        if self.options.policy_config_file:
            from kubernetes_tpu.api.policy import parse_policy
            with open(self.options.policy_config_file) as f:
                self._policy = parse_policy(f.read())
        self._priorities = None
        if self._policy is None \
                and self.options.algorithm_provider != "DefaultProvider":
            from kubernetes_tpu.api.policy import provider_priorities
            self._priorities = provider_priorities(
                self.options.algorithm_provider)
        self.elector: Optional[LeaderElector] = None
        if self.options.leader_elect:
            lock = LeaseLock(api, self.options.lock_object_name,
                             self.options.lock_object_namespace)
            self.elector = LeaderElector(
                lock, identity, now=now,
                on_started_leading=self._on_started_leading,
                on_stopped_leading=self._on_stopped_leading)
        self._healthz: Optional[ThreadingHTTPServer] = None
        self._healthz_thread: Optional[threading.Thread] = None
        self._run_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        if self.options.healthz_port is not None:
            self._start_healthz()

    # --------------------------------------------------------------- leading

    def _make_scheduler(self) -> Scheduler:
        kwargs = dict(scheduler_name=self.options.scheduler_name,
                      batch_mode=self.options.batch_mode,
                      record_events=False, policy=self._policy,
                      now=self._now)  # one clock for LE, TTLs, and backoff
        if self._priorities is not None:
            kwargs["priorities"] = self._priorities
        sched = Scheduler(self.api, **kwargs)
        sched.start()
        return sched

    def _on_started_leading(self) -> None:
        # fresh scheduler = fresh relist; the previous leader's assumed
        # state is irrelevant (level-triggered recovery, SURVEY §5.4)
        self.scheduler = self._make_scheduler()

    def _on_stopped_leading(self) -> None:
        self.scheduler = None

    def is_leader(self) -> bool:
        if self.elector is None:
            return True
        return self.elector.is_leader()

    # ----------------------------------------------------------------- drive

    def step(self) -> dict:
        """One daemon iteration (fake-clock testable): elector tick, then a
        scheduling round when leading."""
        if self.elector is not None:
            self.elector.step()
        if self.is_leader():
            if self.scheduler is None:  # leader_elect=False path
                self.scheduler = self._make_scheduler()
            return self.scheduler.schedule_round()
        return {"popped": 0, "bound": 0, "unschedulable": 0,
                "bind_errors": 0}

    def run(self, poll: float = 0.01) -> None:
        def loop():
            while not self._stopping.is_set():
                self.step()
                self._stopping.wait(poll)
        self._run_thread = threading.Thread(target=loop, daemon=True)
        self._run_thread.start()

    def stop(self, release: bool = True) -> None:
        """Graceful stop: releases the lease so a standby acquires
        immediately. release=False simulates a crash — the lease stays
        held, so a standby must wait out lease_duration (the failover path
        tests/test_chaos.py kills)."""
        self._stopping.set()
        if self._run_thread is not None:
            self._run_thread.join(timeout=5)
            self._run_thread = None
        if self.elector is not None:
            self.elector.stop()
            if release:
                self.elector.release()
        if self._healthz is not None:
            self._healthz.shutdown()
            self._healthz.server_close()  # free the listening socket
            if self._healthz_thread is not None:
                self._healthz_thread.join(timeout=5)
            self._healthz = None

    # --------------------------------------------------------------- healthz

    @property
    def healthz_port(self) -> Optional[int]:
        return self._healthz.server_address[1] if self._healthz else None

    def _start_healthz(self) -> None:
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _write(self, body: bytes, ctype: str = "text/plain"):
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._write(b"ok")
                elif self.path == "/metrics":
                    sched = daemon.scheduler
                    body = sched.metrics.render() if sched else ""
                    self._write(body.encode())
                elif self.path == "/leader":
                    self._write(str(daemon.is_leader()).lower().encode())
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

        self._healthz = ThreadingHTTPServer(
            (self.options.healthz_host, self.options.healthz_port), Handler)
        self._healthz_thread = threading.Thread(
            target=self._healthz.serve_forever, daemon=True)
        self._healthz_thread.start()


def main(argv=None) -> None:
    """Self-contained demo entrypoint: in-process apiserver, a small hollow
    cluster, two competing daemons — shows election, scheduling, failover."""
    import argparse

    from kubernetes_tpu.api.types import make_node, make_pod

    ap = argparse.ArgumentParser(prog="kube-scheduler-lite")
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--pods", type=int, default=500)
    ap.add_argument("--policy-config-file", default=None)
    ap.add_argument("--config", default=None,
                    help="componentconfig KubeSchedulerConfiguration file "
                         "(versioned; decoded through api/scheme.py)")
    args = ap.parse_args(argv)

    api = ApiServerLite()
    for i in range(args.nodes):
        api.create("Node", make_node(f"node-{i:03d}"))
    for i in range(args.pods):
        api.create("Pod", make_pod(f"pod-{i:04d}", cpu=100))
    if args.config:
        import json as _json

        from kubernetes_tpu.api.scheme import DEFAULT_SCHEME
        from kubernetes_tpu.utils import features
        with open(args.config) as f:
            cfg = DEFAULT_SCHEME.decode(_json.load(f))
        for gate, val in cfg.feature_gates.items():
            features.DEFAULT_FEATURE_GATE.set(gate, val)
        opts = SchedulerOptions.from_component_config(cfg)
        if args.policy_config_file:
            opts.policy_config_file = args.policy_config_file
        # the demo runs TWO daemons in one process: a fixed healthz port
        # from the config (default 10251) would EADDRINUSE on the second
        # — ephemeral ports for both, like the no-config path
        opts.healthz_port = 0
    else:
        opts = SchedulerOptions(policy_config_file=args.policy_config_file)
    a = SchedulerDaemon(api, "daemon-a", opts)
    b = SchedulerDaemon(api, "daemon-b", opts)
    for _ in range(50):
        a.step()
        b.step()
        pods, _ = api.list("Pod")
        if all(p.node_name for p in pods):
            break
    bound = sum(1 for p in api.list("Pod")[0] if p.node_name)
    leader = "daemon-a" if a.is_leader() else "daemon-b"
    print(f"leader={leader} bound={bound}/{args.pods} "
          f"healthz(a)=:{a.healthz_port} healthz(b)=:{b.healthz_port}")
    a.stop()
    b.stop()


if __name__ == "__main__":
    main()
