"""apiserver-lite: in-process object store with resourceVersion CAS + watch.

The benchmark-grade stand-in for kube-apiserver+etcd, mirroring what the
reference's integration tier does with its in-process master
(test/integration/scheduler_perf/util.go:47 mustSetupScheduler). Semantics
kept from the real storage stack:

- monotonically increasing resourceVersion assigned on every write
  (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go).
- optimistic concurrency: update with expect_rv mismatching -> Conflict,
  like GuaranteedUpdate's CAS loop (etcd3/store.go:257).
- watch: every write appends to an event log; watchers consume from a cursor.
  A bounded log means a too-slow watcher gets TooOldResourceVersion and must
  relist — the etcd compaction / watch-cache-eviction behavior
  (storage/cacher.go; apimachinery watch semantics).
- the pods/<name>/binding subresource sets spec.nodeName atomically and
  refuses double-binding (pkg/registry/core/pod/storage/storage.go:128
  BindingREST -> pod strategy's "pod X is already assigned to node Y").

Thread-safe; watchers may block with a timeout (condition variable).
"""

from __future__ import annotations

import dataclasses
import threading
from kubernetes_tpu.analysis import lockcheck
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

from kubernetes_tpu.api.types import Binding, Node, Pod


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


class TooOldResourceVersion(Exception):
    """Watcher fell behind the bounded event log; relist and re-watch."""


@dataclass(slots=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any
    rv: int


_KEY = Tuple[str, str, str]  # kind, namespace, name


def _meta(obj: Any) -> Tuple[str, str]:
    ns = getattr(obj, "namespace", "")
    return ns, obj.name


class ApiServerLite:
    def __init__(self, max_log: int = 200_000, data_dir: Optional[str] = None,
                 fsync: str = "batch", compact_every: int = 200_000):
        """data_dir=None (default) is the pure in-memory benchmark store;
        a data_dir makes every write durable through a WAL + snapshots
        (server/durable.py — the etcd role, etcd3/store.go:85) and restores
        state on construction. Watchers resuming with a pre-restart rv get
        TooOldResourceVersion and must relist, like an etcd compaction."""
        self._lock = lockcheck.make_condition("ApiServerLite._lock")
        self._objects: Dict[_KEY, Any] = {}
        self._rv = 0
        self._log: List[WatchEvent] = []
        self._log_start_rv = 0  # rv of the first retained event
        self._max_log = max_log
        self._durable = None
        if data_dir is not None:
            from kubernetes_tpu.server.durable import DurableStore
            self._durable = DurableStore(data_dir, fsync=fsync,
                                         compact_every=compact_every)
            self._objects, self._rv = self._durable.restore()
            # the event log did not survive: anything before the restored rv
            # is unreachable, so resuming watchers must relist
            self._log_start_rv = self._rv + 1

    # ------------------------------------------------------------------ CRUD

    def create(self, kind: str, obj: Any) -> int:
        with self._lock:
            key = (kind, *_meta(obj))
            if key in self._objects:
                raise Conflict(f"{key} already exists")
            self._rv += 1
            obj.resource_version = self._rv
            self._objects[key] = obj
            self._append_locked(WatchEvent("ADDED", kind, obj, self._rv))
            self._persist_put_locked(key, obj)
            return self._rv

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            try:
                return self._objects[(kind, namespace, name)]
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None

    def list(self, kind: str) -> Tuple[List[Any], int]:
        """Returns (objects, resourceVersion-at-list-time) — the reflector's
        List+Watch handshake (client-go/tools/cache/reflector.go)."""
        with self._lock:
            objs = [o for (k, _, _), o in self._objects.items() if k == kind]
            return objs, self._rv

    def update(self, kind: str, obj: Any, expect_rv: Optional[int] = None) -> int:
        with self._lock:
            key = (kind, *_meta(obj))
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(str(key))
            if expect_rv is not None and cur.resource_version != expect_rv:
                raise Conflict(
                    f"{key}: rv {expect_rv} != current {cur.resource_version}")
            self._rv += 1
            obj.resource_version = self._rv
            self._objects[key] = obj
            self._append_locked(WatchEvent("MODIFIED", kind, obj, self._rv))
            self._persist_put_locked(key, obj)
            return self._rv

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = (kind, namespace, name)
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFound(str(key))
            self._rv += 1
            self._append_locked(WatchEvent("DELETED", kind, obj, self._rv))
            if self._durable is not None:
                self._durable.delete(key, self._rv)
                self._durable.flush()
                self._maybe_compact_locked()

    # ------------------------------------------------------------- binding

    def bind(self, binding: Binding) -> int:
        """The /binding subresource (BindingREST, storage.go:128)."""
        with self._lock:
            return self._bind_locked(binding)

    def bind_many(self, bindings: List[Binding]) -> List[Optional[str]]:
        """Batch of /binding POSTs under one lock acquisition (the scheduler
        issues one per placement; semantics per binding are identical to
        bind()). Returns one entry per binding: None on success, else the
        error string ('conflict: ...' / 'not found: ...')."""
        return self._bind_batch((b.pod_namespace, b.pod_name, b.node_name)
                                for b in bindings)

    def bind_pods_bulk(self, pods: List[Pod]) -> List[Optional[str]]:
        """bind_many over already-placed Pod objects (pod.node_name = the
        chosen node): the columnar drain path reads the identifiers straight
        off the pods instead of minting one Binding per placement. Error
        strings and per-binding semantics identical to bind_many."""
        return self._bind_batch((p.namespace, p.name, p.node_name)
                                for p in pods)

    def _bind_batch(self, triples) -> List[Optional[str]]:
        """Shared body of bind_many/bind_pods_bulk over (namespace, name,
        node_name) triples. The happy path is inlined (no per-binding call/
        exception machinery, one notify + one log trim for the whole batch)
        — this is the 30k-pod storm's write burst, the analog of etcd3 txn
        batching."""
        out: List[Optional[str]] = []
        append = out.append
        with self._lock:
            objects = self._objects
            objects_get = objects.get
            log = self._log
            log_append = log.append
            durable = self._durable
            mk = object.__new__
            ev = WatchEvent
            rv = self._rv
            try:
                for ns, name, node_name in triples:
                    key = ("Pod", ns, name)
                    pod = objects_get(key)
                    if pod is None:
                        append(f"not found: pod {ns}/{name}")
                        continue
                    if pod.node_name:
                        append(f"conflict: pod {pod.key()} is already "
                               f"assigned to node {pod.node_name}")
                        continue
                    new = mk(Pod)
                    new.__dict__.update(pod.__dict__)
                    new.node_name = node_name
                    rv += 1
                    new.resource_version = rv
                    objects[key] = new
                    log_append(ev("MODIFIED", "Pod", new, rv))
                    if durable is not None:
                        durable.put(key, new, rv)
                    append(None)
            finally:
                # even if a durable append raises mid-batch, rv must cover
                # every binding already applied to objects/log — reissuing
                # an rv would break the log's bisect-by-rv invariant
                self._rv = rv
            if durable is not None:
                durable.flush()
                self._maybe_compact_locked()
            if len(log) > self._max_log:
                drop = len(log) - self._max_log
                self._log = log[drop:]
                self._log_start_rv = self._log[0].rv
            self._lock.notify_all()
        return out

    def preempt_pods_bulk(self, victims: List[Pod],
                          binding: Binding) -> Optional[str]:
        """Atomic preemption commit (ISSUE 14): evict every victim
        (spec.nodeName cleared — the pod re-enters the pending pool, it
        is NOT deleted) AND bind the preemptor, all-or-nothing under one
        lock. Validation runs first; any refusal aborts the whole op
        with NOTHING applied — zero partial preemptions by construction,
        which is the property the scheduler's fault handling (and the
        churn harness's injected eviction faults) leans on.

        Replay convergence (the at-most-once ambiguity): a victim
        already unbound with the same uid counts as already-evicted (the
        prior attempt's write landed; skipped, no second event), and a
        preemptor already bound to the SAME node heals to success — a
        retry of a landed-but-timed-out commit converges instead of
        erroring. A victim bound to a DIFFERENT node, or a preemptor
        bound elsewhere, aborts: the cluster moved and the plan is
        stale. Returns None on success, else the error string."""
        with self._lock:
            evict: List[Tuple[_KEY, Pod]] = []
            for vic in victims:
                key = ("Pod", vic.namespace, vic.name)
                cur = self._objects.get(key)
                if cur is None:
                    return f"preempt: victim not found: {vic.key()}"
                if vic.uid and cur.uid and cur.uid != vic.uid:
                    return f"preempt: victim uid moved: {vic.key()}"
                if not cur.node_name:
                    continue  # already evicted (landed replay): skip
                if vic.node_name and cur.node_name != vic.node_name:
                    return (f"preempt: victim {vic.key()} moved to node "
                            f"{cur.node_name}")
                evict.append((key, cur))
            bkey = ("Pod", binding.pod_namespace, binding.pod_name)
            target = self._objects.get(bkey)
            if target is None:
                return (f"preempt: preemptor not found: "
                        f"{binding.pod_namespace}/{binding.pod_name}")
            bind_needed = True
            if target.node_name:
                if target.node_name == binding.node_name:
                    bind_needed = False  # landed replay: heal to success
                else:
                    return (f"preempt: pod {target.key()} is already "
                            f"assigned to node {target.node_name}")
            # validated — apply all (no fallible step below this line)
            mk = object.__new__
            for key, cur in evict:
                new = mk(Pod)
                new.__dict__.update(cur.__dict__)
                new.node_name = ""
                self._rv += 1
                new.resource_version = self._rv
                self._objects[key] = new
                self._append_locked(WatchEvent("MODIFIED", "Pod", new, self._rv))
                self._persist_put_locked(key, new)
            if bind_needed:
                new = mk(Pod)
                new.__dict__.update(target.__dict__)
                new.node_name = binding.node_name
                self._rv += 1
                new.resource_version = self._rv
                self._objects[bkey] = new
                self._append_locked(WatchEvent("MODIFIED", "Pod", new, self._rv))
                self._persist_put_locked(bkey, new)
            self._lock.notify_all()
            return None

    def _bind_locked(self, binding: Binding) -> int:
        key = ("Pod", binding.pod_namespace, binding.pod_name)
        pod: Optional[Pod] = self._objects.get(key)
        if pod is None:
            raise NotFound(f"pod {binding.pod_namespace}/{binding.pod_name}")
        if pod.node_name:
            raise Conflict(
                f"pod {pod.key()} is already assigned to node {pod.node_name}")
        # shallow clone (same effect as dataclasses.replace, ~4x faster on
        # the 30k-binding storm path; watchers keep seeing the old object)
        new = object.__new__(Pod)
        new.__dict__.update(pod.__dict__)
        new.node_name = binding.node_name
        self._rv += 1
        new.resource_version = self._rv
        self._objects[key] = new
        self._append_locked(WatchEvent("MODIFIED", "Pod", new, self._rv))
        self._persist_put_locked(key, new)
        return self._rv

    # --------------------------------------------------------------- watch

    def watch_since(self, kinds: Tuple[str, ...], from_rv: int,
                    timeout: Optional[float] = None) -> List[WatchEvent]:
        """All events with rv > from_rv for the given kinds; blocks up to
        `timeout` when none are available (0/None = non-blocking)."""
        with self._lock:
            if from_rv < self._log_start_rv - 1 and from_rv < self._rv:
                # events the watcher needs were compacted away — either
                # trimmed from the bounded log, or lost in a restart (the
                # durable store recovers objects, not the event log)
                if not self._log or self._log[0].rv > from_rv + 1:
                    raise TooOldResourceVersion(
                        f"requested rv {from_rv}, log starts at rv "
                        f"{self._log[0].rv if self._log else self._log_start_rv}")
            evs = self._collect_locked(kinds, from_rv)
            if not evs and timeout:
                self._lock.wait(timeout)
                evs = self._collect_locked(kinds, from_rv)
            return evs

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    # --------------------------------------------------------- durability

    def _persist_put_locked(self, key: _KEY, obj: Any) -> None:
        """Called under the lock after a state mutation + event append."""
        lockcheck.assert_held(self._lock, "_persist_put_locked")
        if self._durable is not None:
            self._durable.put(key, obj, self._rv)
            self._durable.flush()
            self._maybe_compact_locked()

    def _maybe_compact_locked(self) -> None:
        lockcheck.assert_held(self._lock, "_maybe_compact_locked")
        if self._durable.should_compact():
            self._durable.compact(self._objects, self._rv)

    def compact(self) -> None:
        """Force a snapshot + WAL truncation (restore-from-backup.sh's
        backup step; etcd's periodic snapshotting)."""
        with self._lock:
            if self._durable is not None:
                self._durable.compact(self._objects, self._rv)

    def close(self) -> None:
        with self._lock:
            if self._durable is not None:
                self._durable.close()

    # ------------------------------------------------------------ internals

    def _collect_locked(self, kinds: Tuple[str, ...], from_rv: int) -> List[WatchEvent]:
        # events are appended in rv order — binary-search the start
        lockcheck.assert_held(self._lock, "_collect_locked")
        import bisect
        lo = bisect.bisect_right(self._log, from_rv, key=lambda e: e.rv)
        return [e for e in self._log[lo:] if e.kind in kinds]

    def _append_locked(self, ev: WatchEvent) -> None:
        lockcheck.assert_held(self._lock, "_append_locked")
        self._log.append(ev)
        if len(self._log) > self._max_log:
            drop = len(self._log) - self._max_log
            self._log = self._log[drop:]
            self._log_start_rv = self._log[0].rv
        self._lock.notify_all()
