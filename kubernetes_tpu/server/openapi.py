"""OpenAPI (swagger) spec serving.

The reference apiserver serves a generated OpenAPI v2 document at
/swagger.json and /openapi/v2
(staging/src/k8s.io/apiserver/pkg/server/routes/openapi.go, spec built by
the openapi-gen toolchain from type comments). Here the spec is derived
REFLECTIVELY from the same registries the serving path uses — KIND_INFO
(kind -> plural/scope) and the wire dataclass registry — so the document
can never drift from what the server actually serves: every definition's
properties come from the live dataclass fields, every path from the live
routing table, and Established CRDs appear the moment they serve.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict

VERSION_INFO = {"title": "kubernetes-tpu", "version": "v1.7-tpu"}


def _schema_for_type(tp: Any, depth: int = 0) -> Dict[str, Any]:
    origin = typing.get_origin(tp)
    if origin in (list, typing.List, tuple, typing.Tuple):
        args = typing.get_args(tp)
        item = _schema_for_type(args[0], depth) if args \
            else {"type": "object"}
        return {"type": "array", "items": item}
    if origin in (dict, typing.Dict):
        return {"type": "object", "additionalProperties": True}
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _schema_for_type(args[0], depth) if args \
            else {"type": "object"}
    if tp is int:
        return {"type": "integer", "format": "int64"}
    if tp is float:
        return {"type": "number", "format": "double"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is str:
        return {"type": "string"}
    if isinstance(tp, type) and dataclasses.is_dataclass(tp):
        # nested dataclasses inline their fields (no $ref plumbing at
        # this scale; the reference $refs via gen) — depth-capped so a
        # future recursive type cannot blow the document up
        if depth >= 4:
            return {"type": "object"}
        return _definition_for(tp, depth + 1)
    if isinstance(tp, type) and issubclass(tp, str):  # str enums
        return {"type": "string"}
    return {"type": "object"}


def _definition_for(cls: type, depth: int = 0) -> Dict[str, Any]:
    props: Dict[str, Any] = {}
    try:
        hints = typing.get_type_hints(cls)
    except Exception:
        hints = {f.name: f.type for f in dataclasses.fields(cls)}
    for f in dataclasses.fields(cls):
        props[f.name] = _schema_for_type(hints.get(f.name, str), depth)
    return {"type": "object", "properties": props}


def _paths_for(kind: str, plural: str, cluster_scoped: bool,
               definition_ref: str) -> Dict[str, Any]:
    base = f"/api/v1/{plural}" if cluster_scoped \
        else f"/api/v1/namespaces/{{namespace}}/{plural}"
    ref = {"$ref": definition_ref}
    ok = {"200": {"description": "OK", "schema": ref}}
    list_ok = {"200": {"description": "OK",
                       "schema": {"type": "array", "items": ref}}}
    return {
        base: {
            "get": {"operationId": f"list{kind}", "responses": list_ok},
            "post": {"operationId": f"create{kind}", "responses": ok},
        },
        base + "/{name}": {
            "get": {"operationId": f"read{kind}", "responses": ok},
            "put": {"operationId": f"replace{kind}", "responses": ok},
            "delete": {"operationId": f"delete{kind}",
                       "responses": {"200": {"description": "OK"}}},
        },
    }


def build_spec(store=None) -> Dict[str, Any]:
    """The OpenAPI v2 document for everything currently served: built-in
    kinds from KIND_INFO/wire registry, plus Established CRDs when a
    store is given (the apiextensions openapi contribution)."""
    from kubernetes_tpu.api.wire import KIND_REGISTRY
    from kubernetes_tpu.server.apiserver import KIND_INFO

    definitions: Dict[str, Any] = {}
    paths: Dict[str, Any] = {}
    for kind, (plural, cluster_scoped) in sorted(KIND_INFO.items()):
        cls = KIND_REGISTRY.get(kind)
        definitions[kind] = _definition_for(cls) if cls is not None \
            and dataclasses.is_dataclass(cls) else {"type": "object"}
        paths.update(_paths_for(kind, plural, cluster_scoped,
                                f"#/definitions/{kind}"))
    if store is not None:
        try:
            crds, _ = store.list("CustomResourceDefinition")
        except Exception:
            crds = []
        for crd in crds:
            kind = crd.names.kind
            if not kind or kind in definitions:
                continue
            definitions[kind] = {"type": "object", "properties": {
                "spec": {"type": "object",
                         "properties": dict(crd.validation or {})}}}
            plural = crd.names.plural
            group, version = crd.group, crd.version
            base = (f"/apis/{group}/{version}/namespaces/{{namespace}}/"
                    f"{plural}") if crd.scope == "Namespaced" \
                else f"/apis/{group}/{version}/{plural}"
            ref = {"$ref": f"#/definitions/{kind}"}
            ok = {"200": {"description": "OK", "schema": ref}}
            paths[base] = {
                "get": {"operationId": f"list{kind}", "responses": ok},
                "post": {"operationId": f"create{kind}", "responses": ok}}
            paths[base + "/{name}"] = {
                "get": {"operationId": f"read{kind}", "responses": ok},
                "put": {"operationId": f"replace{kind}", "responses": ok},
                "delete": {"operationId": f"delete{kind}",
                           "responses": {"200": {"description": "OK"}}}}
    return {
        "swagger": "2.0",
        "info": dict(VERSION_INFO),
        "paths": paths,
        "definitions": definitions,
    }
