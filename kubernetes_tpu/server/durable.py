"""Durable persistence for apiserver-lite: write-ahead log + snapshots.

The reference's single durable truth is etcd: every write goes through a
raft-replicated WAL and periodic snapshots, and recovery is "replay the WAL
on top of the last snapshot" (reference: etcd behind
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:85 New / :257
GuaranteedUpdate; disaster path cluster/restore-from-backup.sh; the WAL
record framing itself is the forked etcd proto under third_party/).

This module gives ApiServerLite the same durability story, single-node:

- WriteAheadLog: append-only file of length+CRC32-framed records. A torn
  tail (crash mid-write) is detected by the CRC/length check and replay
  stops at the last complete record — the etcd WAL's torn-entry semantics.
- DurableStore: data-dir layout `snapshot.db` (full object map + rv,
  written atomically via tmp+rename) and `wal.log` (records since that
  snapshot). restore() = load snapshot, replay WAL.
- Records are ("put", key, obj, rv) / ("del", key, rv) — create, update,
  and the /binding subresource all reduce to `put`, exactly like etcd txns.
- fsync policy: "batch" (default) flushes OS buffers once per API call —
  surviving process crashes (kill -9) but not power loss; "always" fsyncs
  every flush; "off" leaves buffering to Python (fastest, test-only).

Resume semantics for watchers mirror etcd compaction: the in-memory event
log does not survive a restart, so a watcher resuming with a pre-crash
resourceVersion gets TooOldResourceVersion and must relist — which is the
reference's documented recovery path (level-triggered re-list; SURVEY §5.4).
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Dict, Iterator, Optional, Tuple

_HDR = struct.Struct("<II")  # payload length, crc32(payload)


class WriteAheadLog:
    """Append-only framed log; tolerant of a torn final record on replay."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")

    def append(self, payload: bytes) -> None:
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)) + payload)

    def flush(self, sync: bool = False) -> None:
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()

    @staticmethod
    def replay(path: str) -> Iterator[bytes]:
        """Yield complete, checksum-valid records; stop at the first torn or
        corrupt frame (crash mid-append leaves at most one)."""
        for payload, _end in WriteAheadLog.scan(path):
            yield payload

    @staticmethod
    def scan(path: str) -> Iterator[Tuple[bytes, int]]:
        """(payload, end-offset-after-this-record) for each valid record —
        the end offset lets restore truncate a torn tail before appending
        (etcd WAL repair semantics: reopening in append mode after a torn
        record would bury every later write behind the tear)."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            pos = 0
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return
                length, crc = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    return
                pos += _HDR.size + length
                yield payload, pos


class DurableStore:
    """snapshot.db + wal.log management for one ApiServerLite instance."""

    SNAPSHOT = "snapshot.db"
    WAL = "wal.log"

    def __init__(self, data_dir: str, fsync: str = "batch",
                 compact_every: int = 200_000):
        assert fsync in ("always", "batch", "off")
        self.data_dir = data_dir
        self.fsync = fsync
        self.compact_every = compact_every
        os.makedirs(data_dir, exist_ok=True)
        self._snap_path = os.path.join(data_dir, self.SNAPSHOT)
        self._wal_path = os.path.join(data_dir, self.WAL)
        self._wal: Optional[WriteAheadLog] = None
        self._records_since_snapshot = 0

    # ------------------------------------------------------------ recovery

    def restore(self) -> Tuple[Dict[Any, Any], int]:
        """(objects, rv) = last snapshot + WAL replay. Also counts replayed
        records toward the next compaction threshold."""
        objects: Dict[Any, Any] = {}
        rv = 0
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                objects, rv = pickle.load(f)
        n = 0
        valid_end = 0
        for payload, end in WriteAheadLog.scan(self._wal_path):
            rec = pickle.loads(payload)
            op = rec[0]
            if op == "put":
                _, key, obj, rec_rv = rec
                objects[key] = obj
                rv = max(rv, rec_rv)
            elif op == "del":
                _, key, rec_rv = rec
                objects.pop(key, None)
                rv = max(rv, rec_rv)
            n += 1
            valid_end = end
        # repair a torn tail NOW: appending after it would bury every
        # subsequent flushed record behind an unreadable frame
        if os.path.exists(self._wal_path) \
                and os.path.getsize(self._wal_path) > valid_end:
            with open(self._wal_path, "r+b") as f:
                f.truncate(valid_end)
        self._records_since_snapshot = n
        return objects, rv

    # ------------------------------------------------------------- logging

    def _ensure_wal(self) -> WriteAheadLog:
        if self._wal is None:
            self._wal = WriteAheadLog(self._wal_path)
        return self._wal

    def put(self, key, obj, rv: int) -> None:
        self._ensure_wal().append(
            pickle.dumps(("put", key, obj, rv), pickle.HIGHEST_PROTOCOL))
        self._records_since_snapshot += 1

    def delete(self, key, rv: int) -> None:
        self._ensure_wal().append(
            pickle.dumps(("del", key, rv), pickle.HIGHEST_PROTOCOL))
        self._records_since_snapshot += 1

    def flush(self) -> None:
        """Once per API write call (batch boundary)."""
        if self._wal is None:
            return
        if self.fsync == "always":
            self._wal.flush(sync=True)
        elif self.fsync == "batch":
            self._wal.flush(sync=False)

    def should_compact(self) -> bool:
        return self._records_since_snapshot >= self.compact_every

    # ---------------------------------------------------------- compaction

    def compact(self, objects: Dict[Any, Any], rv: int) -> None:
        """Write a full snapshot atomically (tmp + fsync + rename — the
        restore-from-backup.sh discipline), then truncate the WAL."""
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump((objects, rv), f, pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        # make the rename itself durable BEFORE truncating the WAL: a power
        # loss that kept the truncate but lost the directory entry would
        # otherwise recover old-snapshot + empty-WAL = silent data loss
        dir_fd = os.open(self.data_dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        open(self._wal_path, "wb").close()  # truncate
        self._records_since_snapshot = 0

    def close(self) -> None:
        if self._wal is not None:
            self._wal.flush(sync=self.fsync != "off")
            self._wal.close()
            self._wal = None
