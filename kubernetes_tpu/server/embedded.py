"""The verdict API as a LIBRARY (ISSUE 11): the transport-agnostic
service core every wire shares, plus the in-process embedding mode for
co-located frontends.

PROFILE_r12's attribution made the split obvious: every correctness
semantic of the multi-frontend service — coalesced dispatch, bounded
staleness + the Omega bind fence, the BindLedger's exactly-once, typed
backpressure and deadline shedding — already lives BELOW the transport,
in TPUExtenderBackend. What the transports were missing was a shared,
typed seam:

  - ``VerdictService`` wraps a backend and answers the fleet verbs as
    plain typed objects (FilterVerdict / BindResult), raising the
    coalescer's typed Overloaded / DeadlineExceeded. The JSON HTTP
    server (server/extender.py), the async binary wire
    (server/asyncwire.py) and the embedding below are all thin adapters
    over THIS class — swapping the wire cannot move a semantic because
    no wire owns one.
  - ``EmbeddedVerdictAPI`` is the zero-wire deployment: the frontend
    links the verdict API directly (the sidecar AS a library), keeping
    the coalescer, stale window, fence and ledger intact — concurrent
    embedded frontends still micro-batch into one fused [C, N] dispatch
    and still commit through the fence. ``schedule_one`` packages the
    proven fleet scheduleOne loop (fused verdict -> top-score pick ->
    fenced bind, conflict/overload retries with jittered backoff,
    idempotency-key replay of ambiguous attempts) as one call.

The 100-frontend in-process fleet in bench.py measures this mode: on the
2-core CI box it sustains 416-687 scheduleOnes/s — the number the binary
wire is measured AGAINST (acceptance: within 2x over the wire).
"""

from __future__ import annotations

import random
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.server.extender import TPUExtenderBackend


@dataclass
class FilterVerdict:
    """One fused filter(+topk) evaluation against the shared snapshot."""

    snapshot_gen: Optional[int]
    all_passed: bool
    passed_count: int
    # None when compact elision applied (all passed, nothing to echo)
    passed: Optional[List[str]]
    failed: Dict[str, str] = field(default_factory=dict)
    # None when top_k was not requested; [] when requested and nothing fits
    top_scores: Optional[List[Tuple[str, int]]] = None


@dataclass
class BindResult:
    """Typed bind_verdict outcome — kind in ok|conflict|pending|shed|error
    (server/extender.py bind_verdict docstring has the retry contract)."""

    kind: str
    error: str = ""
    retry_after_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.kind == "ok"

    @property
    def retryable(self) -> bool:
        return self.kind in ("conflict", "pending")


class ScheduleFailed(Exception):
    """schedule_one exhausted its attempt budget without a bind."""


class VerdictService:
    """The transport-agnostic service core over one TPUExtenderBackend.

    filter()/bind() ride the backend's own coalescer and fence (what the
    HTTP handlers and the embedded mode use); eval_batch()/finish_filter()
    are the batch seam for a transport that does its OWN group-commit
    batching (the async wire's event loop collects concurrent FILTER
    frames and dispatches them as one fused batch — transport-level
    coalescing, same engine seam, same degraded fallback)."""

    def __init__(self, backend: TPUExtenderBackend):
        self.backend = backend

    # ------------------------------------------------------------ verbs

    def filter(self, pod, node_names: Optional[List[str]] = None,
               top_k: int = 0, deadline_s: Optional[float] = None,
               compact: bool = False,
               trace_ctx: Optional[str] = None) -> FilterVerdict:
        """Fused filter(+topk) through the coalescing window. Raises the
        coalescer's Overloaded / DeadlineExceeded. ``node_names``
        restricts the candidate set (the HTTP args shape); compact
        elision only applies to the whole-cluster form — a restricted
        verdict always echoes its survivors. ``trace_ctx`` stamps one
        embedded WIRE_HOP on the pod-trace timeline (ISSUE 15) — the
        in-process twin of the HTTP header / binary flag."""
        if trace_ctx:
            self._trace_hop(trace_ctx, 0)
        b = self.backend
        if top_k:
            passed, failed, top, gen = b.fused_verdict(
                pod, node_names, deadline_s=deadline_s, top_k=top_k)
        else:
            passed, failed, gen = b.filter_verdict(
                pod, node_names, deadline_s=deadline_s)
            top = None
        return self._as_filter_verdict(passed, failed, top, gen,
                                       compact and node_names is None)

    @staticmethod
    def _as_filter_verdict(passed, failed, top, gen,
                           compact: bool) -> FilterVerdict:
        all_passed = not failed
        return FilterVerdict(
            snapshot_gen=gen, all_passed=all_passed,
            passed_count=len(passed),
            passed=None if (compact and all_passed) else list(passed),
            failed=dict(failed), top_scores=top)

    @staticmethod
    def _trace_hop(trace_id: str, hop_verb: int) -> None:
        from kubernetes_tpu.observability import podtrace
        if podtrace.TRACER.enabled:
            podtrace.TRACER.wire_hop(trace_id, podtrace.WIRE_EMBEDDED,
                                     hop_verb)

    @staticmethod
    def trace_bound(trace_id: str) -> None:
        """Terminal BOUND for a wire-path trace: the sidecar deployment
        has no scheduler bind path to complete the timeline, so each
        transport stamps completion when ITS bind verdict lands ok —
        without this, wire timelines would pin live slots until the
        window-abandonment sweep and /debug/pods would never show a
        completed wire exemplar."""
        from kubernetes_tpu.observability import podtrace
        if podtrace.TRACER.enabled:
            podtrace.TRACER.bound_batch([trace_id])

    def bind(self, pod_name: str, namespace: str, uid: str, node: str,
             snapshot_gen: Optional[int] = None,
             idem_key: Optional[str] = None,
             deadline_s: Optional[float] = None, pod=None,
             trace_ctx: Optional[str] = None) -> BindResult:
        if trace_ctx:
            self._trace_hop(trace_ctx, 1)
        err, kind, retry_s = self.backend.bind_verdict(
            pod_name, namespace, uid, node, snapshot_gen=snapshot_gen,
            idem_key=idem_key, deadline_s=deadline_s, pod_spec=pod)
        if trace_ctx and kind == "ok":
            self.trace_bound(trace_ctx)
        return BindResult(kind=kind, error=err, retry_after_s=retry_s)

    def sync_nodes(self, nodes) -> int:
        self.backend.sync_nodes(nodes)
        return len(nodes)

    def sync_pods(self, pods) -> int:
        self.backend.sync_pods(pods)
        return len(pods)

    def relist(self):
        """``(nodes, bound_pods)`` — the cell-truth snapshot a scheduler
        process pulls to refresh ITS OWN bounded-stale cache (ISSUE 16;
        extender.list_state docstring). Served identically over the
        binary RELIST verb; the level-triggered re-list half of the
        reference's watch/relist discipline."""
        return self.backend.list_state()

    def metrics_text(self) -> str:
        return self.backend.metrics_text()

    def debug_snapshot(self, last: int = 0) -> Dict:
        """Live introspection (ISSUE 13 + 15): the unified telemetry-
        registry snapshot, the flight recorder's last ``last`` events,
        the pod tracer's black box and the SLO engine's burn-rate view —
        IDENTICAL content to HTTP ``/debug/vars`` + ``/debug/trace`` +
        ``/debug/pods`` + ``/debug/slo`` and the binary wire's STATS
        verb (transport parity is test-pinned; every source snapshots
        under its own lock, so a mid-storm read never tears)."""
        dv = getattr(self.backend, "debug_vars", None)
        dt = getattr(self.backend, "debug_trace", None)
        dp = getattr(self.backend, "debug_pods", None)
        ds = getattr(self.backend, "debug_slo", None)
        return {"vars": dv() if dv is not None else {},
                "trace": dt(last) if (last and dt is not None) else [],
                "pods": dp() if dp is not None else {},
                "slo": ds() if ds is not None else {}}

    # ----------------------------------------------- batch seam (asyncwire)

    def eval_batch(self, pods) -> List:
        """Leader-side batch evaluation for a transport-level coalescer:
        one fused [C, N] dispatch for the batch, with the same degraded
        per-request fallback the thread coalescer carries (a faulting
        batch eval must not take the verb down). Returns one _Verdict OR
        one Exception per pod, in order — the caller answers exceptions
        with typed ERROR frames instead of dropping tickets."""
        b = self.backend
        b._count("coalesce_batches")
        b._count("coalesce_requests", len(pods))
        try:
            return list(b._eval_many(pods))
        except Exception:
            b._count("coalesce_faults")
            outs: List = []
            for p in pods:
                try:
                    outs.append(b._eval_one(p))
                except Exception as e:  # noqa: BLE001 — per-ticket fault
                    outs.append(e)
            return outs

    def finish_filter(self, verdict, top_k: int = 0,
                      compact: bool = False) -> FilterVerdict:
        """Build the FilterVerdict for one eval_batch() verdict — the
        split/top-k marshalling outside the backend lock. Compact fast
        path: an all-passed verdict under elision never materializes the
        N-name passed list at all (at 5k nodes and fleet request rates
        that list build is pure overhead for a response that elides it)."""
        import numpy as np
        b = self.backend
        if compact:
            n = len(verdict.names)
            if bool(np.asarray(verdict.m[:n]).all()):
                top = b._top_scores(verdict, top_k) if top_k else None
                return FilterVerdict(
                    snapshot_gen=verdict.gen, all_passed=True,
                    passed_count=n, passed=None, failed={},
                    top_scores=top)
        passed, failed = b._split_passed(verdict.m, verdict.names,
                                         verdict.idx, None)
        top = b._top_scores(verdict, top_k) if top_k else None
        return self._as_filter_verdict(passed, failed, top, verdict.gen,
                                       compact)


class EmbeddedVerdictAPI(VerdictService):
    """The in-process embedding mode: the verdict API constructed AS a
    library by a co-located frontend — no socket, no serialization, the
    full multi-frontend service semantics (the backend underneath is the
    same object the wires serve).

    Thread-safe: N frontend threads call filter/bind/schedule_one
    concurrently; evaluations micro-batch through the coalescer, commits
    serialize through the fence."""

    def __init__(self, binder=None, stale_window_s: float = 0.025,
                 coalesce_window_s: float = 0.0005,
                 coalesce_max_batch: int = 64,
                 coalesce_max_depth: int = 512):
        super().__init__(TPUExtenderBackend(
            binder=binder, stale_window_s=stale_window_s,
            coalesce_window_s=coalesce_window_s,
            coalesce_max_batch=coalesce_max_batch,
            coalesce_max_depth=coalesce_max_depth))

    def schedule_one(self, pod, top_k: int = 32, max_attempts: int = 80,
                     deadline_s: Optional[float] = None,
                     rng: Optional[random.Random] = None) -> Tuple[str, int]:
        """One frontend scheduleOne through the embedded API: fused
        verdict, pick among the max-score hosts, fenced bind with an
        idempotency key per attempt. CONFLICTs retry against a fresh
        verdict with the server-suggested jittered backoff; Overloaded
        waits out the typed retry-after; an ambiguous bind error replays
        the SAME key so the ledger converges it to exactly-once; the
        store's "already assigned" refusal heals to success (store is
        truth). Returns (node, attempts). Raises ScheduleFailed past the
        attempt budget — the caller's scheduleOne loop owns what happens
        then, exactly like a wire client."""
        from kubernetes_tpu.server.coalescer import (
            DeadlineExceeded,
            Overloaded,
        )
        rng = rng or random.Random()
        # pod-trace context (ISSUE 15): a sampled pod's filter/bind hops
        # join one timeline — the embedded twin of the wire contexts
        from kubernetes_tpu.observability.podtrace import TRACER
        trace_ctx = None
        if TRACER.enabled:
            key = f"{pod.namespace}/{pod.name}"
            if TRACER.sampled(key):
                TRACER.begin_forced(key)
                trace_ctx = key
        for attempt in range(max_attempts):
            try:
                v = self.filter(pod, top_k=top_k, deadline_s=deadline_s,
                                compact=True, trace_ctx=trace_ctx)
            except Overloaded as e:
                time.sleep(e.retry_after_s * rng.uniform(0.5, 1.5))
                continue
            except DeadlineExceeded:
                time.sleep(0.005 * rng.uniform(0.5, 1.5))
                continue
            scores = v.top_scores or []
            if not scores:
                # transiently full per the (possibly stale) verdict:
                # expiries/forgets free slots — retry, don't abort
                time.sleep(0.01 * rng.uniform(0.5, 1.5))
                continue
            best = scores[0][1]
            cands = [nm for nm, s in scores if s == best]
            node = cands[rng.randrange(len(cands))]
            res = self.bind(pod.name, pod.namespace, pod.uid, node,
                            snapshot_gen=v.snapshot_gen,
                            idem_key=f"{pod.namespace}/{pod.name}:{attempt}",
                            deadline_s=deadline_s, pod=pod,
                            trace_ctx=trace_ctx)
            if res.ok:
                return node, attempt + 1
            if res.kind == "conflict" and "double-claim" in res.error:
                # another scheduler process owns this pod (ISSUE 16):
                # converge on ITS placement instead of retrying into the
                # same typed refusal forever — store is truth, the same
                # discipline as the "already assigned" heal below
                m = re.search(r"already claimed on (\S+)", res.error)
                return (m.group(1) if m else node), attempt + 1
            if res.retryable:
                time.sleep(res.retry_after_s * rng.uniform(0.5, 1.5))
                continue
            if "already assigned" in res.error:
                return node, attempt + 1  # landed earlier; store is truth
            if res.kind == "error":
                # ambiguous downstream write: same key converges via the
                # ledger (replays to the recorded node)
                res2 = self.bind(
                    pod.name, pod.namespace, pod.uid, node,
                    idem_key=f"{pod.namespace}/{pod.name}:{attempt}",
                    pod=pod)
                if res2.ok or "already assigned" in res2.error:
                    return node, attempt + 1
            # shed or unresolved: fresh attempt, fresh key
        raise ScheduleFailed(
            f"{pod.namespace}/{pod.name}: no bind in {max_attempts} attempts")


__all__ = ["BindResult", "EmbeddedVerdictAPI", "FilterVerdict",
           "ScheduleFailed", "VerdictService"]
