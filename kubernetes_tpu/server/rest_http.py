"""REST facade: the apiserver handler chain over HTTP.

Maps the reference's REST layout (staging/src/k8s.io/apiserver/pkg/endpoints
installer) onto the in-process ApiServer:

  GET    /healthz /configz /metrics /api /apis /version
  GET    /api/v1/{resource}                       (cluster list)
  GET    /api/v1/namespaces/{ns}/{resource}       (namespaced list)
  GET    /api/v1/namespaces/{ns}/{resource}/{name}
  POST   /api/v1/namespaces/{ns}/{resource}       (create; body = JSON obj)
  PUT    /api/v1/namespaces/{ns}/{resource}/{name}
  DELETE /api/v1/namespaces/{ns}/{resource}/{name}
  POST   .../pods/{name}/binding | /eviction
  PUT    .../pods/{name}/status
  GET/PUT .../{resource}/{name}/scale
  GET    /api/v1/watch?resourceVersion=N[&timeout=s]   (JSON-lines batch)

Bearer tokens ride the Authorization header; the native wire codec
(api/wire.py) carries objects, and `kind` is inferred from the resource
path. Long-running watch streams use chunked JSON lines like the reference's
watch framing (apimachinery/pkg/watch + streaming serializer)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.api import wire
from kubernetes_tpu.api.cluster import Eviction
from kubernetes_tpu.api.types import Binding
from kubernetes_tpu.auth.authn import Credential, Unauthenticated
from kubernetes_tpu.auth.authz import Forbidden
from kubernetes_tpu.admission import Rejected
from kubernetes_tpu.server.apiserver import (
    ApiServer,
    Invalid,
    KIND_INFO,
    TooManyRequests,
)
from kubernetes_tpu.server.apiserver_lite import Conflict, NotFound

RESOURCE_TO_KIND = {res: kind for kind, (res, _) in KIND_INFO.items()}
VERSION = {"major": "1", "minor": "7+tpu", "gitVersion": "v1.7.0-tpu.0"}


class RestServer:
    def __init__(self, api: ApiServer, host: str = "127.0.0.1",
                 port: int = 0, metrics_text=None):
        self.api = api
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _cred(self) -> Optional[Credential]:
                auth = self.headers.get("Authorization", "")
                if auth.startswith("Bearer "):
                    return Credential(token=auth[len("Bearer "):])
                return None

            def _send(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def _dispatch(self, method: str) -> None:
                try:
                    self._route(method)
                except NotFound as e:
                    self._send(404, {"kind": "Status", "code": 404,
                                     "message": str(e)})
                except Conflict as e:
                    self._send(409, {"kind": "Status", "code": 409,
                                     "message": str(e)})
                except (Forbidden, Rejected) as e:
                    self._send(403, {"kind": "Status", "code": 403,
                                     "message": str(e)})
                except Unauthenticated as e:
                    self._send(401, {"kind": "Status", "code": 401,
                                     "message": str(e)})
                except TooManyRequests as e:
                    self._send(429, {"kind": "Status", "code": 429,
                                     "message": str(e)})
                except Invalid as e:
                    self._send(422, {"kind": "Status", "code": 422,
                                     "message": str(e)})
                except ValueError as e:
                    self._send(400, {"kind": "Status", "code": 400,
                                     "message": str(e)})
                except Exception as e:  # panic recovery filter
                    self._send(500, {"kind": "Status", "code": 500,
                                     "message": f"{type(e).__name__}: {e}"})

            # --------------------------------------------------- routing

            def _route(self, method: str) -> None:
                url = urlparse(self.path)
                q = parse_qs(url.query)
                parts = [p for p in url.path.split("/") if p]
                cred = self._cred()
                api = outer.api
                if url.path == "/healthz":
                    return self._send(200, api.healthz())
                if url.path == "/configz":
                    return self._send(200, api.configz())
                if url.path == "/version":
                    return self._send(200, VERSION)
                if url.path in ("/openapi/v2", "/swagger.json"):
                    # routes/openapi.go: the generated spec, served at
                    # both the modern and the 1.7 swagger paths
                    from kubernetes_tpu.server.openapi import build_spec
                    return self._send(200, build_spec(api.store))
                if url.path == "/metrics":
                    text = outer.metrics_text() if outer.metrics_text else ""
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if url.path == "/api":
                    return self._send(200, {"versions": ["v1"]})
                if url.path == "/apis":
                    # discovery document: built-ins + Established CRDs +
                    # aggregated groups (kube-aggregator /apis root)
                    return self._send(200, api.discovery())
                if url.path == "/api/v1" and method == "GET":
                    return self._send(200, {
                        "resources": sorted(RESOURCE_TO_KIND)})
                if parts[:2] == ["api", "v1"] and len(parts) >= 3 \
                        and parts[2] == "watch":
                    from_rv = int(q.get("resourceVersion", ["0"])[0])
                    timeout = float(q.get("timeout", ["0"])[0])
                    def _watch_kind(res):
                        if res in RESOURCE_TO_KIND:
                            return RESOURCE_TO_KIND[res]
                        for crd in api.store.list(
                                "CustomResourceDefinition")[0]:
                            if crd.names.plural == res and crd.established:
                                return crd.names.kind
                        return None
                    kinds = tuple(
                        k for k in (_watch_kind(r)
                                    for r in q.get("resource", []))
                        if k is not None) \
                        or tuple(RESOURCE_TO_KIND.values())
                    evs = api.watch_since(kinds, from_rv, timeout=timeout,
                                          cred=cred)
                    return self._send(200, [
                        {"type": e.type, "kind": e.kind, "rv": e.rv,
                         "object": wire.encode(e.obj, kind=e.kind)}
                        for e in evs])
                if parts[:2] == ["api", "v1"]:
                    rest = parts[2:]
                    resolve = RESOURCE_TO_KIND.get
                elif parts[0] == "apis" and len(parts) >= 4:
                    # /apis/{group}/{version}/[namespaces/{ns}/]{plural}/...
                    # — the CRD serving path (apiextensions
                    # customresource_handler.go route shape)
                    group, version = parts[1], parts[2]
                    rest = parts[3:]

                    def resolve(res, _g=group, _v=version):
                        for crd in api.store.list(
                                "CustomResourceDefinition")[0]:
                            if crd.names.plural == res and crd.group == _g \
                                    and crd.version == _v \
                                    and crd.established:
                                return crd.names.kind
                        return None
                else:
                    raise NotFound(self.path)
                ns = ""
                if rest and rest[0] == "namespaces" and len(rest) >= 3:
                    # /namespaces/{ns}/{resource}/...; a bare
                    # /namespaces/{name} falls through and addresses the
                    # Namespace object itself
                    ns, rest = rest[1], rest[2:]
                if not rest:
                    raise NotFound(self.path)
                resource = rest[0]
                kind = resolve(resource)
                if kind is None:
                    raise NotFound(f"unknown resource {resource!r}")
                name = rest[1] if len(rest) > 1 else ""
                sub = rest[2] if len(rest) > 2 else ""

                if sub == "binding" and method == "POST":
                    b = self._body()
                    rv = api.bind(Binding(
                        b.get("pod_name", name), ns or "default",
                        b.get("pod_uid", ""), b["node_name"]), cred=cred)
                    return self._send(201, {"resourceVersion": rv})
                if sub == "eviction" and method == "POST":
                    api.evict(Eviction(name, ns or "default"), cred=cred)
                    return self._send(201, {"status": "evicted"})
                if sub == "status" and method == "PUT":
                    obj = wire.decode_any(self._body(), kind=kind)
                    rv = api.update_status(kind, obj, cred=cred)
                    return self._send(200, {"resourceVersion": rv})
                if sub == "scale":
                    if method == "GET":
                        return self._send(200, {
                            "replicas": api.scale(kind, ns, name, cred=cred)})
                    if method == "PUT":
                        reps = int(self._body().get("replicas", 0))
                        api.scale(kind, ns, name, replicas=reps, cred=cred)
                        return self._send(200, {"replicas": reps})
                if sub:
                    # unknown subresource, or a known one with the wrong
                    # method — never fall through to the plain-object verbs
                    # (DELETE .../eviction must not bypass PDB enforcement)
                    raise NotFound(f"{method} {self.path}")
                if method == "GET" and name:
                    obj = api.get(kind, ns, name, cred=cred)
                    return self._send(200, wire.encode(obj, kind=kind))
                if method == "GET":
                    objs, rv = api.list(
                        kind, cred=cred, namespace=ns,
                        field_selector=q.get("fieldSelector", [""])[0])
                    sel = q.get("labelSelector", [""])[0]
                    if sel:
                        want = dict(kv.split("=", 1)
                                    for kv in sel.split(",") if "=" in kv)
                        objs = [o for o in objs
                                if all(getattr(o, "labels", {}).get(k) == v
                                       for k, v in want.items())]
                    return self._send(200, {
                        "kind": kind + "List", "resourceVersion": rv,
                        "items": [wire.encode(o, kind=kind) for o in objs]})
                if method == "POST":
                    obj = wire.decode_any(self._body(), kind=kind)
                    if ns and hasattr(obj, "namespace"):
                        obj.namespace = ns
                    rv = api.create(kind, obj, cred=cred)
                    return self._send(201, {"resourceVersion": rv})
                if method == "PUT" and name:
                    obj = wire.decode_any(self._body(), kind=kind)
                    expect = q.get("resourceVersion", [None])[0]
                    rv = api.update(kind, obj, cred=cred,
                                    expect_rv=int(expect) if expect else None)
                    return self._send(200, {"resourceVersion": rv})
                if method == "DELETE" and name:
                    api.delete(kind, ns, name, cred=cred)
                    return self._send(200, {"status": "deleted"})
                raise NotFound(self.path)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self.metrics_text = metrics_text
        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
