"""Single-threaded async event loop speaking the binary fleet framing
(ISSUE 11) — the transport that kills the thread-per-connection wall.

PROFILE_r12: a NO-OP ThreadingHTTPServer with 100 in-process clients
measures ~196 req/s on the 2-core box — ~200 Python threads in GIL
rotation IS the platform wall, and the fleet saturates it ~25x below the
service's measured in-process capacity. This server replaces the
thread-per-connection model with ONE asyncio event loop owning every
socket: accepts, reads, frame parsing and response writes all run on the
loop thread; the only other threads are a small bounded executor where
the service core's evaluations and commits run (they take the backend
lock and touch the device — they cannot run on the loop without wedging
it).

Group-commit batching AT the transport: concurrent FILTER frames from
different connections pile into one pending list; a single dispatcher
task drains it in batches of ``max_batch`` through
``VerdictService.eval_batch`` — ONE fused [C, N] dispatch per batch,
exactly the thread coalescer's leader/follower economics without parking
a thread per request. While a batch is on the device, new arrivals
queue and ride the next batch (a lone client never waits). BIND frames
ride the SAME pump cycle: at fleet load a per-bind executor hop costs
more event-loop/GIL churn than the ~0.2 ms fenced commit itself, so
commits batch onto the dispatcher's worker round too (measured: the
100-client fleet's p99 request latency dropped ~3x when binds joined
the pump). Pod spec blobs decode ONCE per spec on the worker — never on
the event loop — through a bounded LRU shared by both verbs and every
retry.

The robustness envelope carries over VERBATIM — it lives below the
transport (server/embedded.py docstring):

  - BACKPRESSURE: bounded pending queues (filters AND binds) + in-flight
    cap (syncs); past any, the typed OVERLOADED frame answers with
    a jittered retry-after-ms (the HTTP 429 + Retry-After twin — a fleet
    shed together must not return together).
  - DEADLINES: the frame's deadline field sheds queued-dead work at
    batch formation (DEADLINE frame, nothing evaluated) and rides into
    bind_verdict for the commit side.
  - IDEMPOTENCY: the BIND frame carries the ledger key; replay semantics
    are bind_verdict's, untouched.
  - FRAMING FAULTS: a payload-level decode error answers a typed ERROR
    frame and the connection continues; a corrupt length prefix is an
    unrecoverable stream desync — the connection closes (the client
    reconnects; every verb is idempotent or ledger-keyed). Neither path
    can wedge the loop or leak a pending ticket: every queued ticket is
    resolved by the dispatcher regardless of its connection's fate
    (tests/test_framing.py + test_asyncwire.py fuzz this).

This module is pure HOST-side plumbing: it imports no jax and fetches no
device values — all device work happens behind the service core's
blessed seams, which is exactly what the graftlint fixture
(test_graftlint.py::test_gl002_registry_does_not_taint_async_wire) pins.
"""

from __future__ import annotations

import asyncio
import random
import threading
from kubernetes_tpu.analysis import lockcheck
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

from kubernetes_tpu.server import framing
from kubernetes_tpu.server.embedded import VerdictService


class _Ticket:
    __slots__ = ("blob", "top_k", "compact", "deadline_s", "arrival", "fut")

    def __init__(self, blob, top_k, compact, deadline_s, arrival, fut):
        self.blob = blob  # raw spec blob; decoded (cached) on the worker
        self.top_k = top_k
        self.compact = compact
        self.deadline_s = deadline_s
        self.arrival = arrival
        self.fut = fut


class _BindTicket:
    __slots__ = ("args", "deadline_s", "blob", "arrival", "fut", "tid")

    def __init__(self, args, deadline_s, blob, arrival, fut, tid=None):
        self.args = args  # (name, ns, uid, node, gen, idem_key)
        self.deadline_s = deadline_s
        self.blob = blob
        self.arrival = arrival
        self.fut = fut
        self.tid = tid  # pod-trace context (ISSUE 15), None untraced


class AsyncBinaryServer:
    """The binary fleet wire over one VerdictService.

    start() spins the event loop on a daemon thread and binds the
    listener; stop() tears both down. ``port`` is live after start()."""

    def __init__(self, service: VerdictService, host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 64,
                 max_pending: int = 512, max_inflight: int = 256,
                 workers: int = 4,
                 max_frame: int = framing.MAX_FRAME):
        self.service = service
        self.host = host
        self._want_port = port
        self.port: int = 0
        self.max_batch = max(int(max_batch), 1)
        self.max_pending = max(int(max_pending), 1)
        self.max_inflight = max(int(max_inflight), 1)
        self.max_frame = max_frame
        # loop-thread-only state: the event loop is single-threaded, so
        # none of these need locks — that absence IS the design
        self._pend: List[_Ticket] = []
        self._bind_pend: List[_BindTicket] = []
        # tickets currently ON the worker (popped from the pend lists):
        # stop() must resolve these too — once the loop halts, the pump
        # can never resume to answer them
        self._inflight_tickets: List = []
        self._inflight = 0
        self._pump_task: Optional[asyncio.Task] = None
        self._rng = random.Random(0xA51C)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(workers), 2),
            thread_name_prefix="asyncwire")
        # decoded-pod LRU keyed on the raw spec blob: a fleet scheduleOne
        # ships the SAME blob on /filter, /bind and every retry, so the
        # (comparatively expensive) pod decode runs once per spec, on a
        # WORKER — never on the event loop — and the shared Pod object
        # keeps its key/class-hash memos warm across verbs
        self._pod_cache: "OrderedDict[bytes, object]" = OrderedDict()
        self._pod_cache_lock = lockcheck.make_lock("AsyncBinaryServer._pod_cache_lock")
        self.pod_cache_max = 8192
        # live per-connection reader tasks (loop-thread-only, like the
        # pend lists): teardown() cancels these explicitly — loop.stop()
        # alone strands them pending forever, which leaks a task (and
        # its reader/writer transports) per worker process that ever
        # connected (ISSUE 16 satellite fix)
        self._conn_tasks: set = set()
        # observable leak count: how many connection tasks were still
        # alive (and had to be cancelled) at teardown — tests assert 0
        # after a clean client close, and that stop() drains stragglers
        self.cancelled_conn_tasks = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        ready = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def boot():
                self._server = await asyncio.start_server(
                    self._client, self.host, self._want_port)
                self.port = self._server.sockets[0].getsockname()[1]
                ready.set()

            loop.run_until_complete(boot())
            try:
                loop.run_forever()
            finally:
                loop.run_until_complete(loop.shutdown_asyncgens())
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="asyncwire-loop")
        self._thread.start()
        if not ready.wait(timeout=10):
            raise RuntimeError("asyncwire server failed to start")

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return

        async def teardown():
            if self._server is not None:
                self._server.close()
                await self._server.wait_closed()
            # resolve anything queued OR on the worker — FILTERs and
            # BINDs — so no ticket leaks into a future nobody will
            # complete (an in-flight bind may still LAND downstream:
            # that is the at-most-once ambiguity the client's ledger-key
            # replay converges, same as any ambiguous bind error)
            for t in (self._pend + self._bind_pend
                      + self._inflight_tickets):
                if not t.fut.done():
                    t.fut.set_result((framing.ERROR,
                                      framing.encode_error("server stopped")))
            self._pend.clear()
            self._bind_pend.clear()
            # the set_result wakeups are queued behind this coroutine:
            # yield so the awaiting _handle coroutines resume and write
            # their ERROR responses BEFORE the loop dies (otherwise a
            # blocking client sits in recv() for its full timeout)
            await asyncio.sleep(0)
            await asyncio.sleep(0)
            # cancel surviving connection reader tasks — without this,
            # loop.stop() leaves every still-connected client's _client
            # task pending forever (the reader-task leak): the task, its
            # transports and its buffers outlive the server object
            stragglers = [t for t in self._conn_tasks if not t.done()]
            self.cancelled_conn_tasks = len(stragglers)
            for t in stragglers:
                t.cancel()
            if stragglers:
                await asyncio.gather(*stragglers, return_exceptions=True)
            loop.stop()

        asyncio.run_coroutine_threadsafe(teardown(), loop)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=False)
        self._loop = None

    # ------------------------------------------------------------- helpers

    def _count(self, name: str, n: int = 1) -> None:
        count = getattr(self.service.backend, "_count", None)
        if count is not None:
            count(name, n)

    def _retry_ms(self) -> int:
        # jittered so a fleet shed together does not return together
        return self._rng.randint(5, 40)

    @staticmethod
    def _trace_hop(trace_id: str, hop_verb: int) -> None:
        """Pod-trace context honor (ISSUE 15): one WIRE_HOP stamp on the
        pod's timeline — host-pure, one lock, safe on the event loop
        (the tracer off is one attribute check)."""
        from kubernetes_tpu.observability import podtrace
        if podtrace.TRACER.enabled:
            podtrace.TRACER.wire_hop(trace_id, podtrace.WIRE_BINARY,
                                     hop_verb)

    def _decode_pod(self, blob: bytes):
        """Worker-side cached pod decode (constructor comment)."""
        if not blob:
            return None
        with self._pod_cache_lock:
            pod = self._pod_cache.get(blob)
            if pod is not None:
                self._pod_cache.move_to_end(blob)
                return pod
        pod = framing.decode_pod_blob(blob)
        with self._pod_cache_lock:
            self._pod_cache[blob] = pod
            while len(self._pod_cache) > self.pod_cache_max:
                self._pod_cache.popitem(last=False)
        return pod

    # ------------------------------------------------------- connection IO

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        dec = framing.FrameDecoder(self.max_frame)
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                try:
                    frames = dec.feed(data)
                except framing.FrameError as e:
                    # stream desync (corrupt length): typed ERROR, then
                    # close — the client reconnects and replays
                    self._count("wire_frame_errors")
                    writer.write(framing.encode_frame(
                        framing.ERROR, 0,
                        framing.encode_error(f"FrameError: {e}")))
                    await writer.drain()
                    break
                for verb, flags, req_id, payload in frames:
                    await self._dispatch(verb, flags, req_id, payload,
                                         writer)
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass  # a dead peer is a fleet norm, not a server error
        except Exception:
            # an unexpected escape must never take the accept loop down
            self._count("wire_conn_errors")
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, verb: int, flags: int, req_id: int,
                        payload: bytes,
                        writer: asyncio.StreamWriter) -> None:
        """One frame -> one response frame, errors typed in-band."""
        try:
            rverb, rpayload = await self._handle(verb, flags, payload)
        except framing.FrameError as e:
            # payload-scoped decode fault: the STREAM is intact (the
            # length prefix was valid) — answer typed, keep serving
            self._count("wire_frame_errors")
            rverb, rpayload = framing.ERROR, framing.encode_error(
                f"FrameError: {e}")
        except Exception as e:  # typed in-band, like the HTTP 500 path
            rverb, rpayload = framing.ERROR, framing.encode_error(
                f"{type(e).__name__}: {e}")
        try:
            writer.write(framing.encode_frame(rverb, req_id, rpayload))
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass  # client gave up; its ticket was already resolved

    # ---------------------------------------------------------- verb logic

    async def _handle(self, verb: int, flags: int,
                      payload: bytes) -> Tuple[int, bytes]:
        loop = self._loop
        assert loop is not None
        if verb == framing.PING:
            return framing.PONG, b""
        if verb == framing.FILTER:
            if len(self._pend) >= self.max_pending:
                self._count("admission_shed")
                return framing.OVERLOADED, framing.encode_overloaded(
                    self._retry_ms())
            tid, payload = framing.unwrap_trace(payload, flags)
            if tid is not None:
                self._trace_hop(tid, 0)
            # LAZY parse: header fields only — the pod blob decodes on
            # the worker (cached), never on the event loop
            blob, top_k, deadline_ms = \
                framing.decode_filter_request_lazy(payload)
            fut: asyncio.Future = loop.create_future()
            self._pend.append(_Ticket(
                blob, top_k, bool(flags & framing.FLAG_COMPACT),
                deadline_ms / 1e3 if deadline_ms else None,
                loop.time(), fut))
            if self._pump_task is None or self._pump_task.done():
                self._pump_task = loop.create_task(self._pump())
            return await fut
        if verb == framing.BIND:
            # binds ride the SAME pump cycle as filters: at fleet load a
            # per-bind executor hop costs more loop/GIL churn than the
            # ~0.2 ms commit itself — group-commit batching for the
            # commit side too. The queue is bounded like the filter side.
            if len(self._bind_pend) >= self.max_inflight:
                self._count("admission_shed")
                return framing.OVERLOADED, framing.encode_overloaded(
                    self._retry_ms())
            tid, payload = framing.unwrap_trace(payload, flags)
            if tid is not None:
                self._trace_hop(tid, 1)
            (name, ns, uid, node, gen, idem_key, deadline_ms,
             blob) = framing.decode_bind_request_lazy(payload)
            fut = loop.create_future()
            self._bind_pend.append(_BindTicket(
                (name, ns, uid, node, gen, idem_key),
                deadline_ms / 1e3 if deadline_ms else None,
                blob, loop.time(), fut, tid=tid))
            if self._pump_task is None or self._pump_task.done():
                self._pump_task = loop.create_task(self._pump())
            return await fut
        if verb in (framing.SYNC_NODES, framing.SYNC_PODS):
            if self._inflight >= self.max_inflight:
                self._count("admission_shed")
                return framing.OVERLOADED, framing.encode_overloaded(
                    self._retry_ms())
            kind = "nodes" if verb == framing.SYNC_NODES else "pods"
            self._inflight += 1
            try:
                n = await loop.run_in_executor(
                    self._pool, lambda: self._sync(kind, payload))
            finally:
                self._inflight -= 1
            return framing.SYNCED, framing.encode_synced(n)
        if verb == framing.METRICS:
            text = await loop.run_in_executor(self._pool,
                                              self.service.metrics_text)
            return framing.METRICS_TEXT, framing.encode_metrics_text(text)
        if verb == framing.RELIST:
            # bounded-stale snapshot pull (ISSUE 16): a freshly spawned
            # scheduler process hydrates its local cache from store
            # truth in one round trip. The backend walk takes the
            # backend lock — off the event loop like every service touch
            nodes, pods = await loop.run_in_executor(
                self._pool, self.service.relist)
            return (framing.RELIST_RESULT,
                    framing.encode_relist_result(nodes, pods))
        if verb == framing.CELL_AGG:
            # federation pull (ISSUE 20): fold-and-answer the cell's
            # routing column; drain/evacuate mutate the store — off the
            # event loop like every service touch
            fn = getattr(self.service, "cell_aggregate", None)
            if fn is None:
                return framing.ERROR, framing.encode_error(
                    "service has no federation tier")
            drain, evac = framing.decode_cell_agg_request(payload)
            agg, spilled = await loop.run_in_executor(
                self._pool,
                lambda: fn(drain_spill=drain, evacuate=evac))
            return (framing.CELL_AGG_RESULT,
                    framing.encode_cell_agg_result(agg, spilled))
        if verb == framing.ADMIT:
            fn = getattr(self.service, "admit", None)
            if fn is None:
                return framing.ERROR, framing.encode_error(
                    "service has no federation tier")
            if self._inflight >= self.max_inflight:
                self._count("admission_shed")
                return framing.OVERLOADED, framing.encode_overloaded(
                    self._retry_ms())
            self._inflight += 1
            try:
                # decode on the worker: a router batch blob must not
                # stall every connection's reads while it parses
                accepted, replayed = await loop.run_in_executor(
                    self._pool, lambda: fn(*framing.decode_admit_request(
                        payload)))
            finally:
                self._inflight -= 1
            return (framing.ADMIT_RESULT,
                    framing.encode_admit_result(accepted, replayed))
        if verb == framing.STATS:
            # live introspection (ISSUE 13): the registry snapshot takes
            # per-source locks — off the event loop like every other
            # service touch
            last = framing.decode_stats_request(payload)
            snap = await loop.run_in_executor(
                self._pool, lambda: self.service.debug_snapshot(last))
            return framing.STATS_RESULT, framing.encode_stats_result(snap)
        raise framing.FrameError(f"unknown verb 0x{verb:02x}")

    def _sync(self, kind: str, payload: bytes) -> int:
        # decode runs on the worker too: a multi-MB sync blob must not
        # stall every connection's reads while it parses
        items = framing.decode_items_blob(payload, kind)
        if kind == "nodes":
            return self.service.sync_nodes(items)
        return self.service.sync_pods(items)

    # ----------------------------------------------------- filter dispatch

    async def _pump(self) -> None:
        """The single dispatcher: drain pending FILTER and BIND tickets
        in fused batches — one executor round per cycle. One batch on
        the device at a time; arrivals during a batch ride the next one
        (group-commit on both the verdict and the commit side)."""
        loop = self._loop
        assert loop is not None
        while self._pend or self._bind_pend:
            batch = self._pend[:self.max_batch]
            del self._pend[:len(batch)]
            binds = self._bind_pend[:self.max_batch]
            del self._bind_pend[:len(binds)]
            now = loop.time()
            live = []
            for t in batch:
                if t.deadline_s is not None \
                        and now - t.arrival > t.deadline_s:
                    self._count("deadline_shed")
                    if not t.fut.done():
                        t.fut.set_result((framing.DEADLINE, b""))
                else:
                    live.append(t)
            live_b = []
            for t in binds:
                if t.deadline_s is not None \
                        and now - t.arrival > t.deadline_s:
                    # queued-dead commit: shed BEFORE the fence — nothing
                    # happened, a same-key retry starts fresh
                    self._count("deadline_shed")
                    if not t.fut.done():
                        t.fut.set_result((framing.DEADLINE, b""))
                else:
                    live_b.append(t)
            if not live and not live_b:
                continue
            if live:
                self._count("wire_batches")
                self._count("wire_requests", len(live))
            items = [(t.blob, t.top_k, t.compact) for t in live]
            bitems = [(t.args, t.deadline_s, t.blob, now - t.arrival,
                       t.tid) for t in live_b]
            self._inflight_tickets = live + live_b
            try:
                results, bresults = await loop.run_in_executor(
                    self._pool,
                    lambda: (self._eval_encode(items),
                             self._bind_encode(bitems)))
            except Exception as e:  # a dying dispatcher must resolve its
                # tickets — an unresolved future is a wedged connection
                self._count("wire_conn_errors")
                err = (framing.ERROR, framing.encode_error(
                    f"{type(e).__name__}: {e}"))
                results = [err] * len(live)
                bresults = [err] * len(live_b)
            for t, r in zip(live, results):
                if not t.fut.done():
                    t.fut.set_result(r)
            for t, r in zip(live_b, bresults):
                if not t.fut.done():
                    t.fut.set_result(r)
            self._inflight_tickets = []

    def _bind_encode(self, bitems) -> List[Tuple[int, bytes]]:
        """Worker-side bind batch: cached decode + the fenced commit per
        ticket, faults isolated per ticket. The binder write inside
        bind_verdict runs outside the backend lock but inside this
        worker round — co-located/in-process binders (the deployment
        this wire serves; a remote apiserver amortizes through
        bind_pods_bulk upstream) keep the round short."""
        from kubernetes_tpu.server.embedded import VerdictService
        res: List[Tuple[int, bytes]] = []
        for (args, deadline_s, blob, waited, tid) in bitems:
            name, ns, uid, node, gen, idem_key = args
            try:
                remaining = None if deadline_s is None \
                    else max(deadline_s - waited, 0.0)
                r = self.service.bind(
                    name, ns, uid, node, snapshot_gen=gen,
                    idem_key=idem_key, deadline_s=remaining,
                    pod=self._decode_pod(blob))
                if tid and r.kind == "ok":
                    # complete the wire-path trace (embedded.py
                    # trace_bound docstring): no scheduler bind path
                    # exists here to terminate the timeline
                    VerdictService.trace_bound(tid)
                res.append((framing.BIND_RESULT, framing.encode_bind_result(
                    r.kind, max(int(r.retry_after_s * 1e3), 1)
                    if r.retry_after_s else 0, r.error)))
            except framing.FrameError as e:
                self._count("wire_frame_errors")
                res.append((framing.ERROR, framing.encode_error(
                    f"FrameError: {e}")))
            except Exception as e:  # noqa: BLE001 — ticket-isolated
                res.append((framing.ERROR, framing.encode_error(
                    f"{type(e).__name__}: {e}")))
        return res

    def _eval_encode(self, items) -> List[Tuple[int, bytes]]:
        """Worker-side batch body: cached pod decode + one fused eval +
        per-ticket response encoding, all off the event loop thread. A
        ticket whose blob will not decode gets its typed error without
        voiding the rest of the batch."""
        decoded: List = []
        outs: List = [None] * len(items)
        for idx, (blob, _k, _c) in enumerate(items):
            try:
                pod = self._decode_pod(blob)
                if pod is None:
                    raise framing.FrameError("empty pod blob")
                decoded.append((idx, pod))
            except Exception as e:  # noqa: BLE001 — per-ticket fault
                outs[idx] = e
        if decoded:
            evals = self.service.eval_batch([p for _i, p in decoded])
            for (idx, _p), v in zip(decoded, evals):
                outs[idx] = v
        res: List[Tuple[int, bytes]] = []
        for (blob, top_k, compact), v in zip(items, outs):
            if isinstance(v, Exception):
                res.append((framing.ERROR, framing.encode_error(
                    f"{type(v).__name__}: {v}")))
                continue
            try:
                fv = self.service.finish_filter(v, top_k=top_k,
                                                compact=compact)
                res.append((framing.VERDICT, framing.encode_verdict(
                    fv.snapshot_gen, fv.all_passed, fv.passed_count,
                    fv.passed, sorted(fv.failed), fv.top_scores or [])))
            except Exception as e:  # ticket-isolated: one bad verdict
                # must not void the whole batch's responses
                res.append((framing.ERROR, framing.encode_error(
                    f"{type(e).__name__}: {e}")))
        return res


__all__ = ["AsyncBinaryServer"]
