"""Scheduler-extender HTTP sidecar: the integration seam into a real
kube-scheduler.

Implements the reference's extender wire contract verbatim so an unmodified
kube-scheduler with `--policy-config-file` pointing at an ExtenderConfig
(api/types.go:129) offloads findNodesThatFit / PrioritizeNodes here
(generic_scheduler.go:211-228,381-399 -> core/extender.go:100 Filter,
:157 Prioritize, :199 Bind, :226 send):

  POST {prefix}/filter      ExtenderArgs -> ExtenderFilterResult
  POST {prefix}/prioritize  ExtenderArgs -> HostPriorityList
  POST {prefix}/bind        ExtenderBindingArgs -> ExtenderBindingResult
  GET  /healthz, /metrics

JSON keys: the reference posts the *internal* structs (no json tags ->
capitalized keys: "Pod", "Nodes", "NodeNames"); Go's json.Unmarshal is
case-insensitive, so we accept either case and respond capitalized.

nodeCacheCapable mode (extender.go:113-124): only candidate node NAMES cross
the wire; the sidecar keeps full node/pod state in its own cache, synced via
the bulk endpoints POST /cache/nodes and /cache/pods (the "snapshot POSTs"
variant of SURVEY.md §7 step 3) and updated optimistically by bind calls.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Protocol, Tuple

from kubernetes_tpu.api import serde
from kubernetes_tpu.api.types import Node, Pod


class ExtenderBackend(Protocol):
    def filter(self, pod: Pod, nodes: Optional[List[Node]],
               node_names: Optional[List[str]]
               ) -> Tuple[List[str], Dict[str, str]]: ...

    def prioritize(self, pod: Pod, nodes: Optional[List[Node]],
                   node_names: Optional[List[str]]
                   ) -> List[Tuple[str, int]]: ...

    def bind(self, pod_name: str, pod_namespace: str, pod_uid: str,
             node: str) -> str: ...

    def sync_nodes(self, nodes: List[Node]) -> None: ...

    def sync_pods(self, pods: List[Pod]) -> None: ...

    def metrics_text(self) -> str: ...


class ExtenderHTTPServer:
    def __init__(self, backend: ExtenderBackend, host: str = "127.0.0.1",
                 port: int = 0, prefix: str = ""):
        self.backend = backend
        self.prefix = prefix.rstrip("/")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _read_raw(self):
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

            def _read_json(self):
                return json.loads(self._read_raw() or b"{}")

            def _write_json(self, obj, code: int = 200):
                # compact separators: a 5k-node HostPriorityList is ~230KB
                # of response; the default ", " padding costs measurable
                # serialize+wire time at compat-mode request rates
                body = json.dumps(obj, separators=(",", ":")).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics":
                    body = outer.backend.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._write_json({"error": "not found"}, 404)

            def do_POST(self):
                path = self.path
                if outer.prefix and path.startswith(outer.prefix):
                    path = path[len(outer.prefix):]
                try:
                    if path in ("/cache/nodes", "/cache/pods"):
                        # bulk sync: binary fast path (protobuf, SURVEY
                        # §5.8 — the --kube-api-content-type analog) or
                        # the JSON contract, picked by Content-Type
                        from kubernetes_tpu.api import protowire
                        ctype = self.headers.get("Content-Type", "")
                        raw = self._read_raw()
                        is_nodes = path == "/cache/nodes"
                        if ctype == protowire.CONTENT_TYPE:
                            if not protowire.available():
                                # negotiable failure: tell the client to
                                # fall back to the JSON contract
                                self._write_json(
                                    {"Error": "protobuf unavailable; use "
                                     "application/json"}, 415)
                                return
                            items = (protowire.decode_nodes(raw) if is_nodes
                                     else protowire.decode_pods(raw))
                        else:
                            raw_items = json.loads(raw or b"{}").get(
                                "items", [])
                            items = [(serde.decode_node(o) if is_nodes
                                      else serde.decode_pod(o))
                                     for o in raw_items]
                        if is_nodes:
                            outer.backend.sync_nodes(items)
                        else:
                            outer.backend.sync_pods(items)
                        self._write_json({"synced": len(items)})
                        return
                    payload = self._read_json()
                    if path == "/filter":
                        self._write_json(outer.handle_filter(payload))
                    elif path == "/prioritize":
                        self._write_json(outer.handle_prioritize(payload))
                    elif path == "/bind":
                        self._write_json(outer.handle_bind(payload))
                    else:
                        self._write_json({"error": f"unknown path {self.path}"}, 404)
                except Exception as e:  # wire errors surface in-band, like the
                    # reference's ExtenderFilterResult.Error (types.go:177)
                    self._write_json({"Error": f"{type(e).__name__}: {e}"}, 500)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- handlers

    @staticmethod
    def _get(payload: Dict, *names):
        for n in names:
            if n in payload:
                return payload[n]
        return None

    def _parse_args(self, payload: Dict) -> Tuple[Pod, Optional[List[Node]],
                                                  Optional[List[str]]]:
        pod_obj = self._get(payload, "Pod", "pod") or {}
        pod = serde.decode_pod(pod_obj)
        nodes_obj = self._get(payload, "Nodes", "nodes")
        nodes = None
        if nodes_obj:
            nodes = [serde.decode_node(n)
                     for n in (nodes_obj.get("Items")
                               or nodes_obj.get("items") or [])]
        names = self._get(payload, "NodeNames", "nodenames", "nodeNames")
        return pod, nodes, names

    def handle_filter(self, payload: Dict) -> Dict:
        pod, nodes, names = self._parse_args(payload)
        passed, failed = self.backend.filter(pod, nodes, names)
        if nodes is not None:
            by_name = {n.name: n for n in nodes}
            return {
                "Nodes": {"Items": [serde.encode_node(by_name[nm])
                                    for nm in passed if nm in by_name]},
                "FailedNodes": failed,
                "Error": "",
            }
        return {"NodeNames": passed, "FailedNodes": failed, "Error": ""}

    def handle_prioritize(self, payload: Dict) -> List[Dict]:
        pod, nodes, names = self._parse_args(payload)
        scores = self.backend.prioritize(pod, nodes, names)
        return [{"Host": h, "Score": int(s)} for h, s in scores]

    def handle_bind(self, payload: Dict) -> Dict:
        err = self.backend.bind(
            self._get(payload, "PodName", "podName") or "",
            self._get(payload, "PodNamespace", "podNamespace") or "",
            str(self._get(payload, "PodUID", "podUID") or ""),
            self._get(payload, "Node", "node") or "")
        return {"Error": err}

    # ------------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


class TPUExtenderBackend:
    """The TPU-offload backend: sidecar-owned SchedulerCache + fused kernels.

    Filter/prioritize evaluate the pod against the sidecar's cached cluster
    state (or against the Nodes shipped in the args when not cache-capable),
    restricted to the candidate set the scheduler sent — exactly the
    contract of extender.go:100-198. Bind assumes into the local cache and
    delegates the apiserver write to `binder` (None = extender not configured
    with BindVerb).

    Warm fast lane (the cache-capable path): cluster state lives DEVICE-
    resident between requests. The backend owns its SchedulerCache
    exclusively — every mutation arrives through sync_nodes / sync_pods /
    bind — so it tracks staleness itself instead of re-deriving it per
    request:

      - sync_* marks a FULL refresh (membership/spec may have moved) and
        invalidates the EvalCache (on_sync);
      - bind marks a TARGETED refresh of just the bound node
        (snapshot.refresh changed_hint — one dynamic row, not an N-node
        generation walk);
      - a request with nothing dirty touches no cluster state at all: the
        snapshot, the uploaded node arrays, the encoded classes and the
        (fits, scores) result memo are all valid, so /prioritize after
        /filter is a dict hit.

    Node arrays ride SchedulingEngine._nodes_on_device (incremental
    dirty-only host->HBM sync), so a bind re-uploads three small dynamic
    arrays, not the 40MB+ snapshot."""

    def __init__(self, binder=None):
        # jax-dependent imports are local so the wire layer stays importable
        # without a TPU runtime
        from kubernetes_tpu.state.cache import SchedulerCache
        from kubernetes_tpu.engine.scheduler_engine import (
            EvalCache,
            SchedulingEngine,
        )
        from kubernetes_tpu.utils.metrics import SchedulerMetrics

        self.cache = SchedulerCache()
        self.engine = SchedulingEngine(self.cache)
        self.metrics = SchedulerMetrics()
        self.binder = binder
        self._known_pods: Dict[str, Pod] = {}
        # per-request amortization + vocab-growth isolation (EvalCache
        # docstring; the reference amortizes the same work through its
        # scheduler cache + equivalence LRU)
        self.eval_cache = EvalCache()
        # staleness ledger for the warm lane (class docstring); guarded by
        # _lock — ThreadingHTTPServer serves each request on its own thread
        self._lock = threading.RLock()
        self._state_dirty = True          # full refresh needed
        self._bind_hint: set = set()      # targeted refresh of these nodes
        self._infos = None                # cached node_infos() view
        self._aff_pod_count = 0           # cached pods carrying pod affinity
        # pods assumed by bind BEFORE any sync shipped their spec: /bind
        # carries only identifiers, so their accounting is spec-less until
        # the bulk cache sync delivers the real object (and replaces it)
        self._assumed_bare: Dict[str, Pod] = {}
        self._last_cleanup = 0.0
        self.eval_cache.cluster_aff_free = True

    # -- cache sync ---------------------------------------------------------

    # assumed-pod TTL sweep cadence: the sidecar has no informer confirm
    # loop — the bulk cache sync IS the confirmation — so a bind whose pod
    # never reappears in a sync (deleted at the apiserver, write lost)
    # must expire via the cache's own TTL or its phantom pod_count/capacity
    # leaks for the process lifetime
    CLEANUP_INTERVAL_S = 5.0

    def _maybe_cleanup_assumed(self) -> None:
        """Time-gated cleanup_assumed (cache.go:355 analog) — called with
        the lock held from the sync/refresh paths."""
        import time as _time
        now = _time.monotonic()
        if now - self._last_cleanup < self.CLEANUP_INTERVAL_S:
            return
        self._last_cleanup = now
        expired = self.cache.cleanup_assumed()
        if expired:
            for k in expired:
                self._assumed_bare.pop(k, None)
            self._state_dirty = True  # released capacity: full re-walk

    def sync_nodes(self, nodes: List[Node]) -> None:
        with self._lock:
            self.eval_cache.on_sync()
            self._state_dirty = True
            self._bind_hint.clear()
            self._maybe_cleanup_assumed()
            seen = set()
            for n in nodes:
                self.cache.update_node(n)
                seen.add(n.name)
            removed = False
            for name in list(self.cache.node_infos().keys()):
                if name not in seen:
                    self.cache.remove_node(name)
                    removed = True
            if removed:
                # the sidecar's sync is a wholesale reconcile that already
                # escalates to a full refresh — compact the ISSUE 8
                # tombstones right away instead of accruing dead rows
                self.cache.purge_tombstones()

    def sync_pods(self, pods: List[Pod]) -> None:
        from kubernetes_tpu.ops.affinity import _has_affinity
        with self._lock:
            self.eval_cache.on_sync()
            self._state_dirty = True
            self._bind_hint.clear()
            self._maybe_cleanup_assumed()
            seen = set()
            for p in pods:
                if not p.node_name:
                    continue
                seen.add(p.key())
                prev = self._known_pods.get(p.key())
                if prev is None:
                    bare = self._assumed_bare.pop(p.key(), None)
                    if bare is not None:
                        # bind assumed this pod WITHOUT its spec (wire
                        # carries identifiers only): swap the spec-less
                        # accounting for the real object — the confirm
                        # path alone would keep the zero-resource rows
                        self.cache.remove_pod(bare)
                    self.cache.add_pod(p)
                else:
                    self.cache.update_pod(prev, p)
                self._known_pods[p.key()] = p
            # full-state semantics, like sync_nodes: pods absent from the
            # snapshot were deleted — release their capacity
            for key in list(self._known_pods):
                if key not in seen:
                    self.cache.remove_pod(self._known_pods.pop(key))
            self._aff_pod_count = sum(
                1 for p in self._known_pods.values() if _has_affinity(p))
            self.eval_cache.cluster_aff_free = self._aff_pod_count == 0

    # -- extender verbs -----------------------------------------------------

    def _refresh_warm(self):
        """Bring the persistent snapshot up to date with the cache, paying
        only for what actually moved (class docstring). Returns the live
        infos view."""
        from kubernetes_tpu.utils.trace import timed_span
        snap = self.engine.snapshot
        self._maybe_cleanup_assumed()  # time-gated; a bind-only deployment
        # (no syncs ever) must still expire unconfirmed assumptions
        if self._state_dirty or self._infos is None:
            with timed_span("extender.refresh_full"):
                self._infos = self.cache.node_infos()
                snap.refresh(self._infos)
            self._state_dirty = False
            self._bind_hint.clear()
        elif self._bind_hint:
            with timed_span("extender.refresh_hint"):
                hint = tuple(self._bind_hint)
                self._bind_hint.clear()
                snap.refresh(self._infos, changed_hint=hint)
        return self._infos

    def _port_words_for(self, pod: Pod) -> int:
        from kubernetes_tpu.ops.predicates import bucket
        snap = self.engine.snapshot
        words = snap.port_words_used()
        for c in pod.containers:
            for p in c.ports:
                if p.host_port > 0:
                    words = max(words, p.host_port // 32 + 1)
        return bucket(max(words, 1), lo=1)

    def _eval(self, pod: Pod, nodes: Optional[List[Node]]):
        from kubernetes_tpu.engine.scheduler_engine import evaluate_pod
        from kubernetes_tpu.state.snapshot import ClusterSnapshot

        if nodes is not None:
            # non-cache-capable: full node state ships in every request, so
            # evaluate against a FRESH snapshot — reusing the persistent one
            # would diff generation counters of unrelated NodeInfo objects
            # and silently serve stale rows
            from kubernetes_tpu.state.node_info import node_info_map
            infos = node_info_map(nodes, [p for p in self._known_pods.values()])
            snap = ClusterSnapshot()
            snap.refresh(infos)
            m, s = evaluate_pod(
                pod, infos, snap, self.engine.priorities,
                workloads=self.engine.workloads_provider(),
                hard_weight=self.engine.hard_pod_affinity_weight,
                volume_ctx=self.engine.volume_ctx, eval_cache=None)
            return snap, m, s
        snap = self.engine.snapshot
        infos = self._refresh_warm()
        # deferred: evaluate_pod invokes this only after vocab flushes, so
        # a label-matrix rebuild can never race a stale device upload
        provider = (lambda: self.engine._nodes_on_device(
            port_words=self._port_words_for(pod)))
        m, s = evaluate_pod(
            pod, infos, snap, self.engine.priorities,
            workloads=self.engine.workloads_provider(),
            hard_weight=self.engine.hard_pod_affinity_weight,
            volume_ctx=self.engine.volume_ctx,
            eval_cache=self.eval_cache, device_nodes_provider=provider)
        return snap, m, s

    FAIL_REASON = "node(s) didn't satisfy TPU predicate kernel"

    def filter(self, pod, nodes, node_names):
        # response building runs OUTSIDE the lock: names/index/m are
        # captured references (a refresh REPLACES the list/dict objects,
        # never mutates them in place), so concurrent compat drivers only
        # serialize on the evaluation itself
        with self._lock:
            snap, m, _ = self._eval(pod, nodes)
            names = snap.node_names
            idx = snap.node_index
        if node_names is None and nodes is None:
            # whole-cluster candidate set: vectorized split instead of
            # a per-name dict-lookup loop over N nodes
            import numpy as np
            mask = m[:len(names)]
            if mask.all():
                return list(names), {}
            passed = [names[i] for i in np.nonzero(mask)[0]]
            failed = {names[i]: self.FAIL_REASON
                      for i in np.nonzero(~mask)[0]}
            return passed, failed
        candidates = node_names if node_names is not None else \
            [n.name for n in nodes]
        passed, failed = [], {}
        for nm in candidates:
            i = idx.get(nm, -1)
            if i >= 0 and m[i]:
                passed.append(nm)
            else:
                failed[nm] = self.FAIL_REASON
        return passed, failed

    def prioritize(self, pod, nodes, node_names):
        with self._lock:
            snap, _, s = self._eval(pod, nodes)
            names = snap.node_names
            idx = snap.node_index
        sl = s.tolist()  # one bulk convert beats N np-scalar __int__s
        if node_names is None and nodes is None:
            return list(zip(names, sl[:len(names)]))
        candidates = node_names if node_names is not None else \
            [n.name for n in nodes]
        return [(nm, sl[idx[nm]]) for nm in candidates if nm in idx]

    def bind(self, pod_name, pod_namespace, pod_uid, node):
        # NOTE on affinity: the /bind wire carries identifiers only
        # (ExtenderBindingArgs), so a freshly bound pod's SPEC — including
        # any pod (anti-)affinity — is unknown here and stays unknown
        # until the bulk cache sync ships the real object. cluster_aff_free
        # therefore changes only at sync boundaries (sync_pods recount):
        # between bind and sync, NO evaluation path (fast lane or oracle)
        # can see the unknown affinity, so the fast lane is exactly as
        # informed as the slow one.
        import dataclasses
        key = f"{pod_namespace}/{pod_name}"
        assumed_now = False
        with self._lock:
            pod = self._known_pods.get(key)
            if pod is None:
                pod = Pod(name=pod_name, namespace=pod_namespace, uid=pod_uid)
            pod = dataclasses.replace(pod, node_name=node)
            try:
                self.cache.assume_pod(pod)
                self.cache.finish_binding(pod)
                assumed_now = True
                if key not in self._known_pods:
                    self._assumed_bare[key] = pod
                # the warm lane's staleness ledger: exactly one node's
                # dynamic row moved
                self._bind_hint.add(node)
            except KeyError:
                pass  # already known (e.g. a client retry of a bind that
                # succeeded) — do NOT treat the existing assumption as ours
        # the apiserver write runs OUTSIDE the lock: a slow apiserver must
        # not stall every concurrent /filter//prioritize for the duration
        # of an external HTTP call. Concurrent evaluations meanwhile see
        # the optimistic assume — exactly the reference's semantics
        # (scheduler.go:224-250: assume first, bind async, forget on
        # failure), compensated below.
        if self.binder is not None:
            try:
                self.binder(pod_name, pod_namespace, pod_uid, node)
            except Exception as e:
                if assumed_now:
                    # undo ONLY what this call assumed: a duplicate /bind
                    # whose write fails must not forget a legitimately
                    # bound pod (that would leak its capacity until the
                    # next sync)
                    with self._lock:
                        self.cache.forget_pod(pod)
                        self._assumed_bare.pop(key, None)
                        self._bind_hint.add(node)
                return str(e)
        return ""

    def metrics_text(self) -> str:
        return self.metrics.render()
