"""Scheduler-extender HTTP sidecar: the integration seam into a real
kube-scheduler.

Implements the reference's extender wire contract verbatim so an unmodified
kube-scheduler with `--policy-config-file` pointing at an ExtenderConfig
(api/types.go:129) offloads findNodesThatFit / PrioritizeNodes here
(generic_scheduler.go:211-228,381-399 -> core/extender.go:100 Filter,
:157 Prioritize, :199 Bind, :226 send):

  POST {prefix}/filter      ExtenderArgs -> ExtenderFilterResult
  POST {prefix}/prioritize  ExtenderArgs -> HostPriorityList
  POST {prefix}/bind        ExtenderBindingArgs -> ExtenderBindingResult
  GET  /healthz, /metrics

JSON keys: the reference posts the *internal* structs (no json tags ->
capitalized keys: "Pod", "Nodes", "NodeNames"); Go's json.Unmarshal is
case-insensitive, so we accept either case and respond capitalized.

nodeCacheCapable mode (extender.go:113-124): only candidate node NAMES cross
the wire; the sidecar keeps full node/pod state in its own cache, synced via
the bulk endpoints POST /cache/nodes and /cache/pods (the "snapshot POSTs"
variant of SURVEY.md §7 step 3) and updated optimistically by bind calls.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Protocol, Tuple

from kubernetes_tpu.api import serde
from kubernetes_tpu.api.types import Node, Pod


class ExtenderBackend(Protocol):
    def filter(self, pod: Pod, nodes: Optional[List[Node]],
               node_names: Optional[List[str]]
               ) -> Tuple[List[str], Dict[str, str]]: ...

    def prioritize(self, pod: Pod, nodes: Optional[List[Node]],
                   node_names: Optional[List[str]]
                   ) -> List[Tuple[str, int]]: ...

    def bind(self, pod_name: str, pod_namespace: str, pod_uid: str,
             node: str) -> str: ...

    def sync_nodes(self, nodes: List[Node]) -> None: ...

    def sync_pods(self, pods: List[Pod]) -> None: ...

    def metrics_text(self) -> str: ...


class ExtenderHTTPServer:
    def __init__(self, backend: ExtenderBackend, host: str = "127.0.0.1",
                 port: int = 0, prefix: str = ""):
        self.backend = backend
        self.prefix = prefix.rstrip("/")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _read_raw(self):
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

            def _read_json(self):
                return json.loads(self._read_raw() or b"{}")

            def _write_json(self, obj, code: int = 200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics":
                    body = outer.backend.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._write_json({"error": "not found"}, 404)

            def do_POST(self):
                path = self.path
                if outer.prefix and path.startswith(outer.prefix):
                    path = path[len(outer.prefix):]
                try:
                    if path in ("/cache/nodes", "/cache/pods"):
                        # bulk sync: binary fast path (protobuf, SURVEY
                        # §5.8 — the --kube-api-content-type analog) or
                        # the JSON contract, picked by Content-Type
                        from kubernetes_tpu.api import protowire
                        ctype = self.headers.get("Content-Type", "")
                        raw = self._read_raw()
                        is_nodes = path == "/cache/nodes"
                        if ctype == protowire.CONTENT_TYPE:
                            if not protowire.available():
                                # negotiable failure: tell the client to
                                # fall back to the JSON contract
                                self._write_json(
                                    {"Error": "protobuf unavailable; use "
                                     "application/json"}, 415)
                                return
                            items = (protowire.decode_nodes(raw) if is_nodes
                                     else protowire.decode_pods(raw))
                        else:
                            raw_items = json.loads(raw or b"{}").get(
                                "items", [])
                            items = [(serde.decode_node(o) if is_nodes
                                      else serde.decode_pod(o))
                                     for o in raw_items]
                        if is_nodes:
                            outer.backend.sync_nodes(items)
                        else:
                            outer.backend.sync_pods(items)
                        self._write_json({"synced": len(items)})
                        return
                    payload = self._read_json()
                    if path == "/filter":
                        self._write_json(outer.handle_filter(payload))
                    elif path == "/prioritize":
                        self._write_json(outer.handle_prioritize(payload))
                    elif path == "/bind":
                        self._write_json(outer.handle_bind(payload))
                    else:
                        self._write_json({"error": f"unknown path {self.path}"}, 404)
                except Exception as e:  # wire errors surface in-band, like the
                    # reference's ExtenderFilterResult.Error (types.go:177)
                    self._write_json({"Error": f"{type(e).__name__}: {e}"}, 500)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- handlers

    @staticmethod
    def _get(payload: Dict, *names):
        for n in names:
            if n in payload:
                return payload[n]
        return None

    def _parse_args(self, payload: Dict) -> Tuple[Pod, Optional[List[Node]],
                                                  Optional[List[str]]]:
        pod_obj = self._get(payload, "Pod", "pod") or {}
        pod = serde.decode_pod(pod_obj)
        nodes_obj = self._get(payload, "Nodes", "nodes")
        nodes = None
        if nodes_obj:
            nodes = [serde.decode_node(n)
                     for n in (nodes_obj.get("Items")
                               or nodes_obj.get("items") or [])]
        names = self._get(payload, "NodeNames", "nodenames", "nodeNames")
        return pod, nodes, names

    def handle_filter(self, payload: Dict) -> Dict:
        pod, nodes, names = self._parse_args(payload)
        passed, failed = self.backend.filter(pod, nodes, names)
        if nodes is not None:
            by_name = {n.name: n for n in nodes}
            return {
                "Nodes": {"Items": [serde.encode_node(by_name[nm])
                                    for nm in passed if nm in by_name]},
                "FailedNodes": failed,
                "Error": "",
            }
        return {"NodeNames": passed, "FailedNodes": failed, "Error": ""}

    def handle_prioritize(self, payload: Dict) -> List[Dict]:
        pod, nodes, names = self._parse_args(payload)
        scores = self.backend.prioritize(pod, nodes, names)
        return [{"Host": h, "Score": int(s)} for h, s in scores]

    def handle_bind(self, payload: Dict) -> Dict:
        err = self.backend.bind(
            self._get(payload, "PodName", "podName") or "",
            self._get(payload, "PodNamespace", "podNamespace") or "",
            str(self._get(payload, "PodUID", "podUID") or ""),
            self._get(payload, "Node", "node") or "")
        return {"Error": err}

    # ------------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


class TPUExtenderBackend:
    """The TPU-offload backend: sidecar-owned SchedulerCache + fused kernels.

    Filter/prioritize evaluate the pod against the sidecar's cached cluster
    state (or against the Nodes shipped in the args when not cache-capable),
    restricted to the candidate set the scheduler sent — exactly the
    contract of extender.go:100-198. Bind assumes into the local cache and
    delegates the apiserver write to `binder` (None = extender not configured
    with BindVerb)."""

    def __init__(self, binder=None):
        # jax-dependent imports are local so the wire layer stays importable
        # without a TPU runtime
        from kubernetes_tpu.state.cache import SchedulerCache
        from kubernetes_tpu.engine.scheduler_engine import (
            EvalCache,
            SchedulingEngine,
        )
        from kubernetes_tpu.utils.metrics import SchedulerMetrics

        self.cache = SchedulerCache()
        self.engine = SchedulingEngine(self.cache)
        self.metrics = SchedulerMetrics()
        self.binder = binder
        self._known_pods: Dict[str, Pod] = {}
        # per-request amortization + vocab-growth isolation (EvalCache
        # docstring; the reference amortizes the same work through its
        # scheduler cache + equivalence LRU)
        self.eval_cache = EvalCache()

    # -- cache sync ---------------------------------------------------------

    def sync_nodes(self, nodes: List[Node]) -> None:
        self.eval_cache.on_sync()
        seen = set()
        for n in nodes:
            self.cache.update_node(n)
            seen.add(n.name)
        for name in list(self.cache.node_infos().keys()):
            if name not in seen:
                self.cache.remove_node(name)

    def sync_pods(self, pods: List[Pod]) -> None:
        self.eval_cache.on_sync()
        seen = set()
        for p in pods:
            if not p.node_name:
                continue
            seen.add(p.key())
            prev = self._known_pods.get(p.key())
            if prev is None:
                self.cache.add_pod(p)
            else:
                self.cache.update_pod(prev, p)
            self._known_pods[p.key()] = p
        # full-state semantics, like sync_nodes: pods absent from the
        # snapshot were deleted — release their capacity
        for key in list(self._known_pods):
            if key not in seen:
                self.cache.remove_pod(self._known_pods.pop(key))

    # -- extender verbs -----------------------------------------------------

    def _eval(self, pod: Pod, nodes: Optional[List[Node]]):
        from kubernetes_tpu.engine.scheduler_engine import evaluate_pod
        from kubernetes_tpu.state.snapshot import ClusterSnapshot

        if nodes is not None:
            # non-cache-capable: full node state ships in every request, so
            # evaluate against a FRESH snapshot — reusing the persistent one
            # would diff generation counters of unrelated NodeInfo objects
            # and silently serve stale rows
            from kubernetes_tpu.state.node_info import node_info_map
            infos = node_info_map(nodes, [p for p in self._known_pods.values()])
            snap = ClusterSnapshot()
            snap.refresh(infos)
        else:
            snap = self.engine.snapshot
            infos = self.cache.node_infos()
            snap.refresh(infos)
        m, s = evaluate_pod(
            pod, infos, snap, self.engine.priorities,
            workloads=self.engine.workloads_provider(),
            hard_weight=self.engine.hard_pod_affinity_weight,
            volume_ctx=self.engine.volume_ctx,
            eval_cache=self.eval_cache if nodes is None else None)
        return snap, m, s

    def filter(self, pod, nodes, node_names):
        snap, m, _ = self._eval(pod, nodes)
        candidates = node_names if node_names is not None else \
            [n.name for n in nodes] if nodes is not None else snap.node_names
        passed, failed = [], {}
        for nm in candidates:
            i = snap.node_index.get(nm, -1)
            if i >= 0 and m[i]:
                passed.append(nm)
            else:
                failed[nm] = "node(s) didn't satisfy TPU predicate kernel"
        return passed, failed

    def prioritize(self, pod, nodes, node_names):
        snap, _, s = self._eval(pod, nodes)
        candidates = node_names if node_names is not None else \
            [n.name for n in nodes] if nodes is not None else snap.node_names
        return [(nm, int(s[snap.node_index[nm]]))
                for nm in candidates if nm in snap.node_index]

    def bind(self, pod_name, pod_namespace, pod_uid, node):
        import dataclasses
        key = f"{pod_namespace}/{pod_name}"
        pod = self._known_pods.get(key)
        if pod is None:
            pod = Pod(name=pod_name, namespace=pod_namespace, uid=pod_uid)
        pod = dataclasses.replace(pod, node_name=node)
        try:
            self.cache.assume_pod(pod)
            self.cache.finish_binding(pod)
        except KeyError:
            pass  # already known
        if self.binder is not None:
            try:
                self.binder(pod_name, pod_namespace, pod_uid, node)
            except Exception as e:
                self.cache.forget_pod(pod)
                return str(e)
        return ""

    def metrics_text(self) -> str:
        return self.metrics.render()
